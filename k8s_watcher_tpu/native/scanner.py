"""Watch-frame scanner: native (ctypes → fastscan.cpp) with Python fallback.

``FrameScanner.scan(raw)`` answers, per raw watch frame, without a JSON
parse: the event type, the object's resourceVersion, and whether the
accelerator resource key can possibly be present. The client's watch loop
(k8s/client.py) uses the verdict to skip ``json.loads`` entirely for frames
the TpuResourceFilter would discard anyway — the dominant case in a real
cluster, where most pods request no accelerator.

Correctness contract (both implementations):

- a frame is only skippable when the quoted resource key is ABSENT — key
  presence anywhere (even in a label) just routes to the full-parse path,
  so false positives cost time, never correctness;
- the reported resourceVersion is the first ``"resourceVersion"`` value in
  the frame, which for serialized k8s objects is metadata's own (Go emits
  struct fields in declaration order; managedFields sits later);
- any structural doubt (escapes, missing fields, non-object frame) yields
  ``type=None``/``rv=None`` and the caller full-parses.
"""

from __future__ import annotations

import ctypes
import dataclasses
import logging
import re
from typing import Optional

logger = logging.getLogger(__name__)

_TYPE_RE = re.compile(rb'"type"\s*:\s*"([^"\\]*)"')
_RV_RE = re.compile(rb'"resourceVersion"\s*:\s*"([^"\\]*)"')
# first "uid" value: for serialized k8s objects that is metadata's own
# (same declaration-order argument as resourceVersion above). Consumed by
# the sharded-ingest client-side ownership skip (k8s/client.py): a frame
# whose uid hashes to another shard is dropped pre-parse.
_UID_RE = re.compile(rb'"uid"\s*:\s*"([^"\\]*)"')


@dataclasses.dataclass(frozen=True)
class FrameScan:
    type: Optional[str]  # None = could not tell — full-parse
    resource_version: Optional[str]
    has_key: bool  # True also when in doubt — full-parse
    uid: Optional[str] = None  # None = could not tell — no shard verdict

    # Event types that may be skipped when the key is absent. ERROR and
    # BOOKMARK frames never carry the key but must take the full path (they
    # drive 410 handling and resume bookkeeping in the caller).
    _SKIPPABLE = frozenset({"ADDED", "MODIFIED", "DELETED"})

    @property
    def skippable(self) -> bool:
        return (
            not self.has_key
            and self.type in self._SKIPPABLE
            and self.resource_version is not None
        )

    def foreign_shard(self, shard: int, shards: int) -> bool:
        """True when this frame provably belongs to ANOTHER ingest shard
        (uid extracted, hash owned elsewhere) and is safe to skip as an
        rv-only marker. Doubt (no uid/type/rv) routes to the full parse —
        the watch source's post-parse ownership filter keeps correctness,
        same false-positives-cost-time-never-correctness contract as
        ``skippable``."""
        if shards <= 1 or self.uid is None:
            return False
        from k8s_watcher_tpu.watch.sharded import shard_of

        return (
            self.type in self._SKIPPABLE
            and self.resource_version is not None
            and shard_of(self.uid, shards) != shard
        )


_FULL_PARSE = FrameScan(type=None, resource_version=None, has_key=True)


class PythonFrameScanner:
    """Regex fallback with semantics identical to the native scanner.

    ``extract_uid=False`` (an UNSHARDED stream — ``foreign_shard`` never
    consults the uid there) skips the uid regex on the per-frame path;
    the sharded construction sites opt in."""

    def __init__(self, resource_key: str, *, extract_uid: bool = True):
        self.resource_key = resource_key
        self.extract_uid = extract_uid
        self._quoted_key = f'"{resource_key}"'.encode()

    def scan(self, raw: bytes) -> FrameScan:
        if not raw.lstrip()[:1] == b"{":
            return _FULL_PARSE
        t = _TYPE_RE.search(raw)
        rv = _RV_RE.search(raw)
        uid = _UID_RE.search(raw) if self.extract_uid else None
        return FrameScan(
            type=t.group(1).decode() if t else None,
            resource_version=rv.group(1).decode() if rv else None,
            has_key=self._quoted_key in raw,
            uid=uid.group(1).decode() if uid else None,
        )

    def scan_chunk(self, buf: bytes, shard: Optional[tuple] = None):
        """Split ``buf`` into newline-delimited frames and scan each.

        Returns ``(records, consumed)``: records are
        ``(start, length, rv, count)`` tuples. ``rv is not None`` means the
        record stands for ``count`` consecutive skippable frames whose last
        resume version is ``rv``; ``rv is None`` means ``count == 1`` and
        the caller must full-parse ``buf[start:start+length]``.
        ``buf[consumed:]`` is the incomplete tail to prepend to the next
        chunk.

        ``shard`` (``(i, n)``) adds the uid-hash ownership skip: a frame
        whose extracted uid provably belongs to another ingest shard is
        skippable even when the resource key is present (the owning stream
        delivers it; this one only needs the resume point). No extractable
        uid -> no shard verdict -> full parse (``foreign_shard`` contract).
        """
        records = []
        pos = 0
        n = len(buf)
        while pos < n:
            nl = buf.find(b"\n", pos)
            if nl < 0:
                break
            end = nl
            if end > pos and buf[end - 1] == 0x0D:  # \r
                end -= 1
            if end > pos:
                scan = self.scan(buf[pos:end])
                skip = scan.skippable or (
                    shard is not None and scan.foreign_shard(*shard)
                )
                if skip and records and records[-1][2] is not None:
                    # coalesce the skip-run (rv monotonic: keep the last)
                    start, length, _, count = records[-1]
                    records[-1] = (start, end - start, scan.resource_version, count + 1)
                else:
                    rv = scan.resource_version if skip else None
                    records.append((pos, end - pos, rv, 1))
            pos = nl + 1
        return records, pos


class _FastScanRec(ctypes.Structure):
    _fields_ = [
        ("start", ctypes.c_long),
        ("len", ctypes.c_long),
        ("count", ctypes.c_long),
        ("flags", ctypes.c_int),
        ("type", ctypes.c_char * 32),
        ("rv", ctypes.c_char * 96),
    ]


_CHUNK_RECS = 256  # frames decoded per native call


class NativeFrameScanner:
    """ctypes front-end for the fastscan C ABI."""

    def __init__(self, resource_key: str, lib_path, *, extract_uid: bool = True):
        self.resource_key = resource_key
        self.extract_uid = extract_uid
        self._quoted_key = f'"{resource_key}"'.encode()
        lib = ctypes.CDLL(str(lib_path))
        self._fn = lib.fastscan_frame
        self._fn.restype = ctypes.c_int
        self._fn.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.c_char_p, ctypes.c_long,
            ctypes.c_char_p, ctypes.c_long,
            ctypes.c_char_p, ctypes.c_long,
        ]
        self._type_buf = ctypes.create_string_buffer(64)
        self._rv_buf = ctypes.create_string_buffer(128)
        self._chunk_fn = lib.fastscan_chunk
        self._chunk_fn.restype = ctypes.c_long
        self._chunk_fn.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.c_char_p, ctypes.c_long,
            ctypes.c_long, ctypes.c_long,
            ctypes.POINTER(_FastScanRec), ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
        ]
        self._recs = (_FastScanRec * _CHUNK_RECS)()

    def scan_chunk(self, buf: bytes, shard: Optional[tuple] = None):
        """Batch counterpart of ``scan``: one native call decodes up to
        ``_CHUNK_RECS`` frames; the skip verdict (flags bit 3) — the
        key-absence test AND the ``shard`` uid-hash ownership test (C-side
        crc32, identical to ``shard_of``) — is computed in C so the
        per-skipped-frame Python cost is one flag test. Same return
        contract as ``PythonFrameScanner.scan_chunk``."""
        shard_idx, shards = shard if shard is not None else (0, 0)
        records = []
        base = 0
        view = buf
        while True:
            consumed = ctypes.c_long(0)
            n = self._chunk_fn(
                view, len(view),
                self._quoted_key, len(self._quoted_key),
                shard_idx, shards,
                self._recs, _CHUNK_RECS,
                ctypes.byref(consumed),
            )
            recs = self._recs
            for i in range(n):
                rec = recs[i]
                flags = rec.flags  # -1 (not JSON) has all bits set: test > 0
                if flags > 0 and flags & 8:
                    rec_tuple = (base + rec.start, rec.len, rec.rv.decode(), rec.count)
                    # merge a skip-run continuing across the cap boundary
                    if records and records[-1][2] is not None:
                        pstart, _, _, pcount = records[-1]
                        rec_tuple = (
                            pstart,
                            base + rec.start + rec.len - pstart,
                            rec_tuple[2],
                            pcount + rec.count,
                        )
                        records[-1] = rec_tuple
                        continue
                else:
                    rec_tuple = (base + rec.start, rec.len, None, 1)
                records.append(rec_tuple)
            if consumed.value == 0 or n < _CHUNK_RECS:
                base += consumed.value
                break
            base += consumed.value
            view = buf[base:]
        return records, base

    def scan(self, raw: bytes) -> FrameScan:
        flags = self._fn(
            raw, len(raw),
            self._quoted_key, len(self._quoted_key),
            self._type_buf, ctypes.sizeof(self._type_buf),
            self._rv_buf, ctypes.sizeof(self._rv_buf),
        )
        if flags < 0:
            return _FULL_PARSE
        # uid rides the Python regex on this per-frame path (the C ABI
        # predates shard ingest and extracts only type/rv) — semantics
        # stay IDENTICAL to PythonFrameScanner, which the parity test
        # pins. The chunked hot path never builds FrameScans, so this
        # regex never runs per-frame there.
        uid = _UID_RE.search(raw) if self.extract_uid else None
        return FrameScan(
            type=self._type_buf.value.decode() if flags & 2 else None,
            resource_version=self._rv_buf.value.decode() if flags & 4 else None,
            has_key=bool(flags & 1),
            uid=uid.group(1).decode() if uid else None,
        )


def make_scanner(
    resource_key: str,
    *,
    prefer_native: bool = True,
    extract_uid: bool = True,
    mode: str = "auto",
):
    """Scanner for ``resource_key`` per ``mode`` (``ingest.prefilter``):

    - ``auto``  — native when it builds/loads, else Python, one INFO log on
      the downgrade (the default: degradation is expected on hosts without
      a toolchain and must not look like a fault);
    - ``native`` — pinned: the same fallback, but the downgrade logs a
      WARNING (the operator asked for native and is not getting it — the
      analytics backend-pin posture);
    - ``python`` — the pure-Python scanner, no build attempted;
    - ``off``   — None (caller runs the full-parse path).

    NEVER raises: any build/load failure — missing compiler, broken cache
    dir, unloadable object — degrades to ``PythonFrameScanner``.
    ``extract_uid=False`` for unsharded streams skips the per-frame uid
    work nothing would consume. ``prefer_native=False`` is the legacy
    spelling of ``mode="python"``.
    """
    if mode == "off":
        return None
    if mode == "python" or not prefer_native:
        return PythonFrameScanner(resource_key, extract_uid=extract_uid)
    pinned = mode == "native"
    reason = None
    try:
        from k8s_watcher_tpu.native.build import build_fastscan, last_build_error

        lib_path = build_fastscan()
        if lib_path is not None:
            return NativeFrameScanner(resource_key, lib_path, extract_uid=extract_uid)
        reason = last_build_error()
    except Exception as exc:  # noqa: BLE001 — degrade, never kill app start
        reason = str(exc)
    logger.log(
        logging.WARNING if pinned else logging.INFO,
        "native fastscan unavailable (%s)%s; using Python scanner",
        reason or "unknown",
        " — ingest.prefilter pinned to 'native'" if pinned else "",
    )
    return PythonFrameScanner(resource_key, extract_uid=extract_uid)
