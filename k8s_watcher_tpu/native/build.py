"""Lazy native build: compile fastscan.cpp into a cached shared object.

No pybind11 in this image, so the extension is plain C ABI loaded via
ctypes. The build is a single g++ invocation, cached by source hash inside
the package tree (override with K8S_WATCHER_TPU_NATIVE_CACHE); any failure
— no compiler, read-only filesystem, broken cache dir, exotic platform —
degrades to the pure-Python scanner, never to an import error and never to
a raise at app start. The operator-facing downgrade log is owned by the
caller (``scanner.make_scanner``: one INFO line on ``auto``, WARNING when
``ingest.prefilter`` pins ``native``); this module records WHY in
``last_build_error()`` and keeps its own logging at DEBUG.
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
import sysconfig
import tempfile
from pathlib import Path
from typing import Optional

logger = logging.getLogger(__name__)

_SRC = Path(__file__).resolve().parent / "fastscan.cpp"
_last_error: Optional[str] = None


def last_build_error() -> Optional[str]:
    """Why the most recent ``build_fastscan`` returned None (or None after
    a success) — surfaced in the caller's single downgrade log line."""
    return _last_error


def _cache_dir() -> Path:
    override = os.environ.get("K8S_WATCHER_TPU_NATIVE_CACHE")
    return Path(override) if override else _SRC.parent / "_cache"


def _ext_suffix() -> str:
    return sysconfig.get_config_var("SHLIB_SUFFIX") or ".so"


def _fail(reason: str) -> None:
    global _last_error
    _last_error = reason
    logger.debug("fastscan build unavailable: %s", reason)


def build_fastscan(force: bool = False) -> Optional[Path]:
    """Path to the compiled shared object, building it if needed.

    Returns None when the library cannot be produced (caller falls back to
    the pure-Python scanner). Never raises on build/filesystem failure.
    """
    global _last_error
    if os.environ.get("K8S_WATCHER_TPU_DISABLE_NATIVE"):
        _last_error = "disabled via K8S_WATCHER_TPU_DISABLE_NATIVE"
        return None
    try:
        source = _SRC.read_bytes()
    except OSError as exc:
        _fail(f"source unreadable: {exc}")
        return None
    digest = hashlib.sha256(source).hexdigest()[:16]
    cache = _cache_dir()
    out = cache / f"fastscan-{digest}{_ext_suffix()}"
    if out.exists() and not force:
        _last_error = None
        return out
    compiler = os.environ.get("CXX", "g++")
    try:
        cache.mkdir(parents=True, exist_ok=True)
        # compile to a temp name then os.replace: concurrent builders
        # (several watcher processes starting at once) each win atomically
        with tempfile.NamedTemporaryFile(
            dir=cache, suffix=_ext_suffix(), delete=False
        ) as tmp:
            tmp_path = Path(tmp.name)
        cmd = [
            compiler, "-O3", "-shared", "-fPIC", "-std=c++17",
            "-fno-exceptions", "-fno-rtti",
            str(_SRC), "-o", str(tmp_path),
        ]
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if proc.returncode != 0:
            _fail(f"{compiler} failed: {proc.stderr[:500]}")
            tmp_path.unlink(missing_ok=True)
            return None
        os.replace(tmp_path, out)
        logger.info("Built native fastscan: %s", out)
        _last_error = None
        return out
    except (OSError, subprocess.SubprocessError) as exc:
        _fail(str(exc))
        return None
