"""Native hot-path components (C++ via ctypes, pure-Python fallbacks)."""

from k8s_watcher_tpu.native.scanner import (  # noqa: F401
    FrameScan,
    NativeFrameScanner,
    PythonFrameScanner,
    make_scanner,
)
