// fastscan — native watch-frame scanner for the event hot loop.
//
// The watcher's hot path (SURVEY.md §3.1: one iteration per cluster pod
// event, forever) is dominated by JSON-decoding pod objects that the
// TpuResourceFilter then throws away: in a real cluster most pods request no
// accelerator. This scanner reads a raw watch frame
// ({"type":"...","object":{...}}) WITHOUT parsing it and answers the three
// questions the Python layer needs to decide whether a full json.loads is
// necessary at all:
//
//   1. the event "type" (ADDED/MODIFIED/DELETED/BOOKMARK/ERROR),
//   2. the object's metadata.resourceVersion (so a skipped frame still
//      advances the watch resume point),
//   3. whether the accelerator resource key (e.g. "google.com/tpu") appears
//      anywhere in the frame — if the quoted key is absent, the pod cannot
//      be requesting the resource and the frame can be dropped unparsed.
//
// Deliberately conservative: any structural surprise (escapes in the value,
// missing fields) returns a "cannot tell" verdict and the caller falls back
// to the full JSON path. C ABI only — loaded via ctypes (no pybind11).
//
// Assumptions (documented in scanner.py and enforced by fallback-on-doubt):
// the first `"resourceVersion"` in a serialized k8s object is the
// metadata's own (Go's encoding/json emits struct fields in declaration
// order: ObjectMeta precedes Spec/Status, and managedFields — the only
// other resourceVersion carrier — sits inside metadata after it).

#ifndef _GNU_SOURCE
#define _GNU_SOURCE  // memmem
#endif
#include <cstddef>
#include <cstring>

namespace {

// Find the first occurrence of needle (quoted JSON string token) in buf.
// glibc memmem is SIMD-accelerated; anchoring a byte search on '"' would
// stall on every quote in the frame.
inline const char* find_token(const char* buf, size_t len, const char* needle, size_t nlen) {
    if (nlen == 0 || len < nlen) return nullptr;
    return static_cast<const char*>(memmem(buf, len, needle, nlen));
}

// After a `"key"` token: skip whitespace, expect ':', skip whitespace,
// expect '"', then copy the string value into out (cap includes NUL).
// Returns 0 on success, -1 on structural surprise (escape, overflow, EOF).
int read_quoted_value(const char* p, const char* end, char* out, long cap) {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
    if (p >= end || *p != ':') return -1;
    ++p;
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
    if (p >= end || *p != '"') return -1;
    ++p;
    long i = 0;
    while (p < end && *p != '"') {
        if (*p == '\\') return -1;  // escaped value: let Python parse it
        if (i + 1 >= cap) return -1;
        out[i++] = *p++;
    }
    if (p >= end) return -1;
    out[i] = '\0';
    return 0;
}

// CRC-32 (IEEE 802.3, the zlib/crc32 polynomial) over the uid bytes — MUST
// match Python's zlib.crc32 exactly, because the shard verdict computed here
// has to agree with watch/sharded.py shard_of() (a disagreement would make
// the native prefilter drop frames the Python partition owns). The table is
// a C++11 magic static (constructor-initialized): concurrent first calls
// from N shard pump threads get a thread-safe one-time init — a hand-rolled
// `static bool ready` flag here would be a data race.
struct Crc32Table {
    unsigned int t[256];
    Crc32Table() {
        for (unsigned int i = 0; i < 256; ++i) {
            unsigned int c = i;
            for (int k = 0; k < 8; ++k)
                c = (c & 1u) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
            t[i] = c;
        }
    }
};

unsigned int crc32_ieee(const char* data, long len) {
    static const Crc32Table table;  // thread-safe magic-static init
    unsigned int crc = 0xFFFFFFFFu;
    for (long i = 0; i < len; ++i)
        crc = table.t[(crc ^ static_cast<unsigned char>(data[i])) & 0xFFu] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

}  // namespace

extern "C" {

// Scan one watch frame.
//   type_out / rv_out: NUL-terminated outputs ("" = not found).
// Returns a flag bitmask (>= 0) or -1 if the frame is not even a JSON
// object; callers treat any missing piece as "full-parse this frame".
//   bit 0: resource key present somewhere in the frame
//   bit 1: type extracted
//   bit 2: resourceVersion extracted
int fastscan_frame(const char* buf, long len,
                   const char* key, long key_len,
                   char* type_out, long type_cap,
                   char* rv_out, long rv_cap) {
    if (buf == nullptr || len <= 0) return -1;
    const char* end = buf + len;
    const char* p = buf;
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
    if (p >= end || *p != '{') return -1;

    int flags = 0;
    if (type_cap > 0) type_out[0] = '\0';
    if (rv_cap > 0) rv_out[0] = '\0';

    // 1. event type: first `"type"` token (the WatchEvent struct's first
    //    field; searched, not assumed, so reordered frames still work)
    static const char kType[] = "\"type\"";
    const char* t = find_token(p, end - p, kType, sizeof(kType) - 1);
    if (t != nullptr &&
        read_quoted_value(t + sizeof(kType) - 1, end, type_out, type_cap) == 0) {
        flags |= 2;
    }

    // 2. resume point: first `"resourceVersion"` token
    static const char kRv[] = "\"resourceVersion\"";
    const char* r = find_token(p, end - p, kRv, sizeof(kRv) - 1);
    if (r != nullptr &&
        read_quoted_value(r + sizeof(kRv) - 1, end, rv_out, rv_cap) == 0) {
        flags |= 4;
    }

    // 3. accelerator key: quoted substring anywhere (conservative — a hit
    //    in a label/annotation just means we full-parse; only a miss allows
    //    skipping, and a miss is exact because resources.requests/limits
    //    keys are serialized as plain quoted strings)
    if (key != nullptr && key_len > 0) {
        if (find_token(p, end - p, key, key_len) != nullptr) flags |= 1;
    }
    return flags;
}

// ---------------------------------------------------------------------------
// Chunk API: split a raw HTTP-chunk buffer into newline-delimited frames and
// scan each one in a single native call. ctypes call overhead (~µs) is paid
// once per chunk instead of once per frame — the difference between the
// native path losing and winning against CPython's C-accelerated regexes.

typedef struct {
    long start;     // frame offset in buf
    long len;       // frame length (trailing \r / \n excluded)
    long count;     // frames this record stands for (skip-runs coalesce)
    int flags;      // fastscan_frame bitmask, or -1 (not a JSON object)
    char type[32];
    char rv[96];
} FastScanRec;

// Returns the number of records written (<= cap); *consumed is set to the
// offset just past the last processed complete frame — the caller keeps
// buf[*consumed:] as the tail for the next chunk. Empty lines are consumed
// without a record. When more than `cap` frames are present the caller
// simply calls again with the unconsumed remainder.
//
// shard/shards: the caller's uid-hash partition (watch/sharded.py). With
// shards > 1, a frame whose first `"uid"` value hashes (crc32 % shards) to
// ANOTHER shard is skippable (bit 3) even when the resource key is present
// — the owning shard's stream will deliver it; this stream only needs the
// resourceVersion. A uid that cannot be extracted cleanly (escape, missing,
// overflow) yields no shard verdict and the frame full-parses — the watch
// source's post-parse ownership filter keeps the partition correct. Pass
// shards <= 1 to disable.
long fastscan_chunk(const char* buf, long len,
                    const char* key, long key_len,
                    long shard, long shards,
                    FastScanRec* out, long cap, long* consumed) {
    static const char kUid[] = "\"uid\"";
    char uid_buf[128];
    long n = 0;
    long pos = 0;
    *consumed = 0;
    while (pos < len && n < cap) {
        const char* nl = static_cast<const char*>(
            memchr(buf + pos, '\n', len - pos));
        if (nl == nullptr) break;  // incomplete frame: leave as tail
        long frame_len = nl - (buf + pos);
        if (frame_len > 0 && buf[pos + frame_len - 1] == '\r') --frame_len;
        if (frame_len > 0) {
            FastScanRec* rec = &out[n];
            rec->start = pos;
            rec->len = frame_len;
            rec->count = 1;
            rec->flags = fastscan_frame(buf + pos, frame_len, key, key_len,
                                        rec->type, sizeof(rec->type),
                                        rec->rv, sizeof(rec->rv));
            // bit 3: frame is skippable — type+rv extracted, no key, and the
            // type is a plain pod event (never ERROR/BOOKMARK). Computed
            // here so Python's per-frame work for a skipped frame is one
            // flag test instead of object construction.
            if (rec->flags >= 0 && (rec->flags & 6) == 6 && !(rec->flags & 1)) {
                const char* t = rec->type;
                if (strcmp(t, "ADDED") == 0 || strcmp(t, "MODIFIED") == 0 ||
                    strcmp(t, "DELETED") == 0) {
                    rec->flags |= 8;
                }
            }
            // foreign-shard skip: key presence does NOT matter here — the
            // owning shard's stream delivers the event; this one only needs
            // the resume point. Gated on the same type+rv extraction the
            // key skip needs (rv-only treatment must still advance resume).
            if (shards > 1 && rec->flags >= 0 && (rec->flags & 6) == 6 &&
                !(rec->flags & 8)) {
                const char* t = rec->type;
                if (strcmp(t, "ADDED") == 0 || strcmp(t, "MODIFIED") == 0 ||
                    strcmp(t, "DELETED") == 0) {
                    const char* u = find_token(buf + pos, frame_len, kUid,
                                               sizeof(kUid) - 1);
                    if (u != nullptr &&
                        read_quoted_value(u + sizeof(kUid) - 1,
                                          buf + pos + frame_len,
                                          uid_buf, sizeof(uid_buf)) == 0 &&
                        uid_buf[0] != '\0') {
                        long owner = crc32_ieee(uid_buf,
                                                strlen(uid_buf)) % shards;
                        if (owner != shard) rec->flags |= 8;
                    }
                }
            }
            // coalesce a run of skippable frames into the previous record:
            // only the run's LAST resourceVersion matters for resume (rv is
            // monotonic), so a non-TPU event storm costs Python one record
            // NB: both flags must be tested >= 0 first — flags == -1 has
            // every bit set, so `-1 & 8` alone would swallow malformed
            // frames into the skip run with a stale rv
            if (rec->flags >= 0 && (rec->flags & 8) && n > 0 &&
                out[n - 1].flags >= 0 && (out[n - 1].flags & 8)) {
                FastScanRec* prev = &out[n - 1];
                memcpy(prev->rv, rec->rv, sizeof(prev->rv));
                prev->count += 1;
                prev->len = (pos + frame_len) - prev->start;
            } else {
                ++n;
            }
        }
        pos = (nl - buf) + 1;
        *consumed = pos;
    }
    return n;
}

}  // extern "C"
