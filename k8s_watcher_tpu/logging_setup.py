"""Logging subsystem.

Parity with the reference (pod_watcher.py:77-94): level comes from
``watcher.log_level``; production gets structured JSON logs, other
environments a human-readable ``[ENV] ts - name - level - msg`` format.

Improvement: the reference built its "JSON" line by string concatenation
(pod_watcher.py:84), which produces invalid JSON whenever a message contains
a quote. We emit real ``json.dumps`` records.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Optional


class JsonFormatter(logging.Formatter):
    """Structured JSON log records for production."""

    def __init__(self, environment: str):
        super().__init__()
        self.environment = environment

    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "timestamp": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
            "environment": self.environment,
        }
        # correlation key with the tracing plane: any log call made with
        # extra={"trace_id": ...} (trace.Tracer.finish does) joins this
        # line against /debug/trace and the trace_* metrics
        trace_id = getattr(record, "trace_id", None)
        if trace_id is not None:
            payload["trace_id"] = trace_id
        if record.exc_info:
            payload["exc_info"] = self.formatException(record.exc_info)
        return json.dumps(payload, ensure_ascii=False)

    def formatTime(self, record: logging.LogRecord, datefmt: Optional[str] = None) -> str:
        ct = time.gmtime(record.created)
        return time.strftime("%Y-%m-%dT%H:%M:%S", ct) + f".{int(record.msecs):03d}Z"


def setup_logging(environment: str, log_level: str = "INFO", *, force: bool = True) -> logging.Logger:
    """Configure root logging for ``environment`` and return this package's logger."""
    level = getattr(logging, log_level.upper(), logging.INFO)
    handler = logging.StreamHandler()
    if environment == "production":
        handler.setFormatter(JsonFormatter(environment))
    else:
        handler.setFormatter(
            logging.Formatter(f"[{environment.upper()}] %(asctime)s - %(name)s - %(levelname)s - %(message)s")
        )
    root = logging.getLogger()
    if force:
        for h in list(root.handlers):
            root.removeHandler(h)
    root.addHandler(handler)
    root.setLevel(level)
    logger = logging.getLogger("k8s_watcher_tpu")
    logger.info("Starting k8s-watcher-tpu in %s environment", environment)
    return logger
