"""Relay plane: zero-re-encode fan-out of ONE upstream serving plane.

The serving plane's epoll core carries direct consumers to the
``serve.max_subscribers`` ceiling (production ships 10k); past that —
100k+ streaming subscribers — one process cannot hold the sockets, and
N processes each holding a direct watch would multiply the root's
fan-out bill N-fold. Podracer's actor/learner topology (PAPERS.md) is
the blueprint this plane implements: a small TREE of relays multiplies
one publisher to fleet scale while the root pays O(relays), not
O(subscribers).

A relay node is an ordinary serve node whose ``FleetView`` is fed by a
``FleetSubscriber`` consuming ONE upstream ``?watch=1`` stream instead
of a local pipeline:

- **Zero re-encode.** The subscriber runs the raw-bytes passthrough
  (``FleetClient.watch_batches(raw=True)``): each wire frame arrives as
  decoded metadata + the upstream's untouched payload bytes. The relay
  re-adds the per-frame chunk framing (a length prefix — no
  serialization) and journals the bytes straight into the view's
  per-codec frame arrays (``FleetView.publish_relayed``). PR 7's
  shared-bytes invariant now spans PROCESSES: the relay's
  ``serve_frame_encodes*`` counters stay 0 for every relayed delta
  served in the upstream-negotiated shape; only a subscriber that
  negotiates a shape the upstream wire didn't carry (e.g. plain JSON
  under a stamped upstream) pays the usual lazy at-most-once-per-delta
  encode — and those frames are byte-golden, because the decoded dicts
  round-trip deterministically.
- **The rv line is the UPSTREAM's.** ``adopt_relay`` takes the
  upstream's view instance id and rv space verbatim, so a resume token
  minted at any relay is valid at every sibling relay AND at the root —
  a subscriber moving between relays (or falling back to the root)
  stays gapless. Snapshots serve from the existing rv-keyed byte cache
  over the relay's mirrored objects: one serialization per rv per
  codec, and the re-snapshot herd after a resync lands on the relay,
  never the root.
- **410/GONE/COMPACTED propagate end-to-end.** A pre-stream 410 or
  in-band GONE from the upstream re-snapshots the relay (its own
  subscribers see GONE and re-snapshot FROM THE RELAY); an upstream
  COMPACTED (the relay itself lagged) marks the relayed journal sparse,
  and reads resuming below the mark carry the compacted flag — the
  skip is sanctioned downstream exactly as it was sanctioned to us.
- **Backfill.** On (re)connect the relay subscribes BELOW its snapshot
  (bounded by ``relay.backfill`` and the upstream's retention floor),
  warming its journal with the recent window so resume tokens minted
  before a relay restart keep resuming — gapless — against the new
  process. Backfilled entries extend the journal without touching
  object state (the snapshot already reflects them).
- **Depth-stamped.** Each relay reads its upstream's ``/serve/healthz``
  relay fold and stamps ``depth = upstream_depth + 1`` (a root serve
  plane is depth 0). ``relay.depth_limit`` bounds the tree — a
  mis-wired relay cycle escalates its own depth on every reconnect and
  self-quarantines at the limit instead of looping frames forever.
  Per-hop freshness rides PR 10's negotiated ``ts`` stamps:
  ``relay_hop_seconds`` (upstream publish → relay receive) and
  ``watch_to_relay_seconds`` (origin → relay) make watch→leaf latency
  measurable at every tier, and the stamps pass through to leaves
  untouched so a tier-2 consumer's ``now - ts[0]`` is the true
  end-to-end age.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional

from k8s_watcher_tpu.federate.client import (
    FleetClient,
    FleetSubscriber,
    ResyncRequired,
    Snapshot,
)
from k8s_watcher_tpu.serve.view import Delta, FleetView, chunk_wrap, frame_variant

logger = logging.getLogger(__name__)

#: extra rvs kept above the upstream's retention floor when choosing the
#: backfill base: churn between the healthz read and the watch connect
#: must not race the base past the floor into a resync loop
BACKFILL_FLOOR_MARGIN = 64


class RelayPlane:
    """Feeds a FleetView from one upstream serving plane (see module
    docstring). Built when ``relay.enabled``; the app starts it BEFORE
    the local serve plane binds and waits for the initial sync, so the
    first subscriber never sees a half-adopted view."""

    def __init__(self, config, view: FleetView, *, metrics=None):
        self.config = config
        self.view = view
        self.metrics = metrics
        self.depth: Optional[int] = None
        self.depth_exceeded = False
        self.adopts = 0
        self._sync_rv = -1  # rv of the last adopted upstream snapshot
        self._backfill_base = -1
        # True while the LAST adopt guessed a backfill base without
        # upstream retention info and hasn't seen a frame yet — the next
        # adopt then skips the guess (bounds a 410'd guess to one resync)
        self._blind_backfill = False
        self._synced = threading.Event()
        self._started = False
        self.client = FleetClient(
            config.upstream.url,
            token=config.upstream.token,
            # request timeout floored well above the staleness knob (the
            # federation plane's posture): a tight stale_after must not
            # shrink the snapshot-read budget with it
            timeout=max(5.0, config.stale_after_seconds),
            codec=config.codec,
            # the negotiated superset this relay's own clients may ask
            # for: stamped frames when relay.fresh (the default — depth
            # freshness needs ts anyway), trace forwarding when
            # relay.trace. An upstream that predates a field serves
            # plain frames and the passthrough stays byte-consistent.
            fresh=config.fresh,
            trace=config.trace,
        )
        self.subscriber = FleetSubscriber(
            self.client,
            on_snapshot=self._on_snapshot,
            on_raw_batch=self._on_raw_batch,
            stale_after_seconds=config.stale_after_seconds,
            backoff_seconds=config.resync_backoff_seconds,
            name=config.upstream.name,
        )
        self._thread: Optional[threading.Thread] = None
        self._monitor: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if metrics is not None:
            self._frames_counter = metrics.counter("relay_frames_relayed")
            self._batches_counter = metrics.counter("relay_batches")
            self._bytes_counter = metrics.counter("relay_bytes")
            self._backfill_counter = metrics.counter("relay_backfill_deltas")
            self._adopts_counter = metrics.counter("relay_adopts")
            self._depth_gauge = metrics.gauge("relay_depth")
            self._lag_gauge = metrics.gauge("relay_lag_rv")
            self._connected_gauge = metrics.gauge("relay_connected")
            # per-hop freshness off the negotiated ts stamps (wall
            # clocks across hosts — the documented skew caveat applies):
            # hop = upstream publish -> relay receive; watch_to_relay =
            # origin -> relay apply (the tier-N propagation histogram)
            self._hop_hist = metrics.histogram("relay_hop_seconds")
            self._w2r_hist = metrics.histogram("watch_to_relay_seconds")
            # the cross-process encode-once invariant, surfaced: these
            # are the view's own counters, read back for health()
            self._encode_counters = tuple(
                metrics.counter(name)
                for name in (
                    "serve_frame_encodes",
                    "serve_frame_encodes_msgpack",
                    "serve_frame_encodes_fresh",
                    "serve_frame_encodes_trace",
                )
            )
        else:
            self._frames_counter = self._batches_counter = None
            self._bytes_counter = self._backfill_counter = None
            self._adopts_counter = None
            self._depth_gauge = self._lag_gauge = self._connected_gauge = None
            self._hop_hist = self._w2r_hist = None
            self._encode_counters = ()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RelayPlane":
        self._stop.clear()
        self._started = True
        self._thread = threading.Thread(
            target=self.subscriber.run, name="relay-subscriber", daemon=True
        )
        self._thread.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="relay-monitor", daemon=True
        )
        self._monitor.start()
        logger.info(
            "Relay plane started: upstream %s (%s), depth_limit=%d, codec=%s, "
            "fresh=%s, trace=%s, backfill=%d",
            self.config.upstream.name, self.config.upstream.url,
            self.config.depth_limit, self.config.codec,
            self.config.fresh, self.config.trace, self.config.backfill,
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        self.subscriber.stop()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
        self._started = False

    def wait_synced(self, timeout: float) -> bool:
        """Block until the first upstream adopt (+ backfill catch-up to
        the snapshot rv) or ``timeout``. The app gates local serving on
        this so the first subscriber never races a half-warmed journal;
        on timeout serving starts anyway (degraded — health says so)."""
        ok = self._synced.wait(timeout)
        if not ok:
            logger.warning(
                "Relay did not sync with upstream %s within %.1fs; serving "
                "anyway (degraded until the upstream answers)",
                self.config.upstream.url, timeout,
            )
        return ok

    # -- subscriber callbacks (subscriber thread) --------------------------

    def _on_snapshot(self, snap: Snapshot) -> None:
        """Adopt the upstream state wholesale, then aim the watch cursor
        BELOW the snapshot for the journal backfill."""
        info: Dict[str, Any] = {}
        try:
            info = self.client.healthz() or {}
        except Exception:  # noqa: BLE001 - healthz is advisory
            info = {}
        upstream_depth = 0
        relay_fold = info.get("relay")
        if isinstance(relay_fold, dict):
            try:
                upstream_depth = int(relay_fold.get("depth") or 0)
            except (TypeError, ValueError):
                upstream_depth = 0
        depth = upstream_depth + 1
        if depth > self.config.depth_limit:
            # the loop-breaker: a relay cycle re-discovers a growing
            # depth on every reconnect and self-quarantines here instead
            # of circulating frames forever. MUST be ResyncRequired: its
            # subscriber arm clears the resume cursor (rv=None), so every
            # escalating backoff re-snapshots and re-checks the depth — a
            # transient-error exception here would leave _resnapshot's
            # already-set cursor in place and the next iteration would
            # stream frames into a view this relay never adopted.
            self.depth_exceeded = True
            raise ResyncRequired(
                f"relay depth {depth} exceeds relay.depth_limit="
                f"{self.config.depth_limit} (upstream {self.config.upstream.url} "
                f"reports depth {upstream_depth}) — mis-wired relay chain?"
            )
        self.depth_exceeded = False
        self.depth = depth
        if self._depth_gauge is not None:
            self._depth_gauge.set(depth)
        self.view.adopt_relay(
            instance=snap.view,
            rv=snap.rv,
            objects={
                (o.get("kind", ""), o.get("key", "")): o for o in snap.objects
            },
        )
        self._sync_rv = snap.rv
        self.adopts += 1
        if self._adopts_counter is not None:
            self._adopts_counter.inc()
        # backfill base: recent window below the snapshot, floored by the
        # upstream's retention (+ a churn margin so the watch connect
        # doesn't race the floor into a pre-stream 410 loop). When the
        # upstream's healthz doesn't advertise oldest_rv (bare
        # ServeServer, older build), we still ATTEMPT the backfill — but
        # only while the previous adopt wasn't itself a blind attempt
        # that 410'd before delivering a frame (self._blind_backfill):
        # that alternation bounds a too-deep guess to one extra resync
        # instead of a loop.
        base = snap.rv
        if self.config.backfill > 0:
            oldest = info.get("oldest_rv")
            if isinstance(oldest, int) and not isinstance(oldest, bool):
                base = max(oldest, snap.rv - self.config.backfill)
                if base == oldest and snap.rv - base > 2 * BACKFILL_FLOOR_MARGIN:
                    # pinned at the retention floor of a deep window:
                    # stand clear of the advancing trim so the watch
                    # connect doesn't race it into a pre-stream 410
                    base += BACKFILL_FLOOR_MARGIN
                base = min(base, snap.rv)
                self._blind_backfill = False
            elif not self._blind_backfill:
                base = max(0, snap.rv - self.config.backfill)
                self._blind_backfill = base < snap.rv
        self._backfill_base = base
        # the subscriber's next watch window starts at the backfill base
        # (we run on its thread, between its _resnapshot and its
        # _watch_window — the one safe moment to retarget the cursor)
        self.subscriber.rv = base
        if base >= snap.rv:
            self._synced.set()
        logger.info(
            "Relay adopted upstream %s at rv=%d (view=%s, depth=%d%s)",
            self.config.upstream.name, snap.rv, snap.view, depth,
            f", backfilling from rv={base}" if base < snap.rv else "",
        )

    def _on_raw_batch(self, pairs) -> None:
        """Fold one wire read: chunk-frame the upstream payload bytes
        (a length prefix — never a re-serialization) and journal them at
        their upstream rvs. Entries at or below the adopted snapshot rv
        are backfill (journal only); the rest fold object state too."""
        if not pairs:
            return
        self._blind_backfill = False  # the guessed base delivered frames
        now_wall = time.time()
        t_mono = time.monotonic()
        variant = frame_variant(
            self.client.active_codec, self.config.fresh, self.config.trace
        )
        sync_rv = self._sync_rv
        backfill = []
        live = []
        nbytes = 0
        hop = self._hop_hist
        w2r = self._w2r_hist
        for frame, raw in pairs:
            rv = frame["rv"]
            ts = frame.get("ts")
            ts_wall, pub_wall = (ts[0], ts[1]) if ts else (None, 0.0)
            delta = Delta(
                rv, frame.get("kind", ""), frame.get("key", ""), frame["type"],
                frame.get("object"), t_mono, ts_wall, pub_wall,
                frame.get("trace"),
            )
            chunked = chunk_wrap(raw)
            nbytes += len(raw)
            if rv <= sync_rv:
                backfill.append((delta, chunked))
            else:
                live.append((delta, chunked))
                if ts is not None:
                    # per-hop freshness (live frames only — backfill ages
                    # are history, not propagation)
                    if hop is not None:
                        hop.record(max(0.0, now_wall - ts[1]))
                    if w2r is not None:
                        w2r.record(max(0.0, now_wall - ts[0]))
        if backfill:
            n = self.view.publish_relayed(backfill, variant=variant, fold_objects=False)
            if self._backfill_counter is not None:
                self._backfill_counter.inc(n)
        if live:
            self.view.publish_relayed(live, variant=variant)
        if self._frames_counter is not None:
            self._frames_counter.inc(len(pairs))
            self._batches_counter.inc()
            self._bytes_counter.inc(nbytes)
        if not self._synced.is_set() and pairs[-1][0]["rv"] >= sync_rv:
            self._synced.set()

    # -- monitor tick ------------------------------------------------------

    def _monitor_loop(self) -> None:
        interval = max(0.1, min(1.0, self.config.stale_after_seconds / 4.0))
        while not self._stop.wait(interval):
            self._tick()

    def _tick(self) -> None:
        sub = self.subscriber
        rv = sub.rv
        # a SYNC heartbeat can outrun the journal only when the upstream
        # compacted/paged our stream: adopt the rv (sparse-sanctioned) so
        # downstream long-polls don't park behind a cursor the journal
        # will never mint
        if rv is not None and self._synced.is_set() and rv > self.view.rv:
            self.view.note_upstream_rv(rv)
        if self._lag_gauge is not None:
            self._lag_gauge.set(max(0, sub.wire_rv - (rv or 0)))
            self._connected_gauge.set(1.0 if sub.connected else 0.0)
        if not self._synced.is_set() and rv is not None and 0 <= self._sync_rv <= rv:
            self._synced.set()

    # -- surfaces ----------------------------------------------------------

    @property
    def synced(self) -> bool:
        return self._synced.is_set()

    def frame_encodes(self) -> Optional[int]:
        """Sum of the view's encode counters — 0 is the cross-process
        encode-once invariant for a relay whose subscribers all ride the
        upstream-negotiated frame shape (the bench asserts it exactly)."""
        if not self._encode_counters:
            return None
        return sum(int(c.value) for c in self._encode_counters)

    def health(self) -> Dict[str, Any]:
        """The relay fold for ``/serve/healthz`` (downstream relays read
        ``depth`` here to stamp their own) and ``/debug/relay``. Healthy
        = subscriber thread alive, synced, inside the staleness window,
        and the depth limit holds. A dark upstream degrades this body
        but never the status plane's liveness verdict — restarting a
        relay cannot revive its upstream."""
        sub = self.subscriber
        thread_alive = self._thread is not None and self._thread.is_alive()
        age = sub.last_frame_age()
        stale = self._started and (
            age is None or age > max(3.0, self.config.stale_after_seconds)
        )
        healthy = (
            not self._started
            or (
                thread_alive
                and self._synced.is_set()
                and not self.depth_exceeded
                and not stale
            )
        )
        return {
            "healthy": healthy,
            "started": self._started,
            "thread_alive": thread_alive,
            "synced": self._synced.is_set(),
            "depth": self.depth,
            "depth_limit": self.config.depth_limit,
            "depth_exceeded": self.depth_exceeded,
            "upstream": self.config.upstream.name,
            "upstream_url": self.config.upstream.url,
            "connected": sub.connected,
            "stale": stale,
            "codec": self.client.active_codec,
            "rv": self.view.rv,
            "wire_rv": sub.wire_rv,
            "backfill_base": self._backfill_base,
            "adopts": self.adopts,
            "resyncs": sub.resyncs,
            "reconnects": sub.reconnects,
            "stalls": sub.stalls,
            "gaps": sub.checker.gaps,
            "dups": sub.checker.dups,
            "frames_relayed": sub.frames,
            "frame_encodes": self.frame_encodes(),
            "last_frame_age_seconds": round(age, 3) if age is not None else None,
            "last_error": sub.last_error,
        }
