"""Relay/edge fan-out tier: zero-re-encode frame relaying (relay/plane.py).

A relay node consumes ONE upstream ``?watch=1`` stream via the
federation client's raw-bytes passthrough and re-broadcasts the
already-encoded wire frames verbatim through the existing serve
broadcast core — the PR-7 encode-once invariant extended across
processes, forming a depth-stamped fan-out tree that carries 100k+
streaming subscribers off one publisher.
"""

from k8s_watcher_tpu.relay.plane import RelayPlane

__all__ = ["RelayPlane"]
