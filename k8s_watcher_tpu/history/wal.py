"""Durable fleet history: a segmented, CRC-framed delta WAL.

The serving plane's delta journal (serve/view.py) is memory-only: a
process restart used to reset the rv space, invalidate every resume
token (410 per *incarnation*), and erase the event history a postmortem
needs. This module is the persistence layer under that journal — the
ARGUS/Guard-class forensic loop (PAPERS.md) applied to the watcher's own
fleet view:

- every ``FleetView`` delta is appended to an append-only **WAL**,
  framed ``length(4B BE) + crc32(4B BE) + payload`` (payload = compact
  sorted-keys JSON, so identical state serializes to identical bytes —
  the replay-determinism substrate);
- the WAL is **segmented**: the active segment rotates once it outgrows
  ``segment_max_bytes`` or ``segment_max_age_seconds``; every segment
  OPENS with a full snapshot record of the shadow state at rotation, so
  any retained segment is a self-contained recovery/time-travel anchor;
- **retention** keeps the newest ``retain_segments`` segments; the
  oldest retained segment's snapshot is the durable horizon — resume
  tokens and ``?at=`` reads 410 only past it, never per incarnation;
- an **fsync policy knob** (``never`` / ``interval`` / ``always``)
  trades durability for write cost; ``interval`` (the default) bounds
  the crash-loss window without paying a sync per batch;
- a crash tears at most the tail of the active segment: the frame CRC
  finds the tear, and the writer **truncates the torn tail** when it
  reopens the directory (readers just stop at it).

Hot-path contract: :meth:`HistoryStore.publish` is called by the view
*under its publish lock* (that is what keeps the WAL rv-ordered across
the pipeline thread and the sink-tap threads) and must therefore be
O(1): it appends the delta refs to a queue and returns. A dedicated
writer thread serializes, frames, rotates, writes and fsyncs — disk
latency never rides the publish path (``bench_wal_overhead`` gates the
enqueue cost at <5% of the ingest hot path). The writer keeps its own
shadow map of fleet state, advanced delta-by-delta as it writes, so
snapshot records are exactly consistent with the delta prefix on disk.

If the writer ever falls ``max_queue_deltas`` behind (wedged disk), the
backlog is dropped, counted (``history_wal_overruns``), and the next
thing written is a fresh **rebase snapshot** — the WAL stays
self-consistent (snapshot records reset state wherever they appear) at
the cost of a hole in the delta history.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import re
import struct
import threading
import time
import zlib
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

# msgpack (in the base image) packs a drain batch ~3x faster than
# json.dumps — the difference between the WAL costing ~16% and <5% of
# the ingest hot path (bench_wal_overhead). The image bakes it in; a
# stripped environment falls back to JSON payloads, and the decoder
# accepts either (the frame CRC, not the codec, is the integrity check).
try:
    import msgpack as _msgpack
except ImportError:  # pragma: no cover - the image bakes msgpack in
    _msgpack = None

#: frame header: payload length + payload crc32, both 4-byte big-endian
FRAME_HEADER = struct.Struct(">II")
#: a length field above this is treated as corruption, not a record
MAX_RECORD_BYTES = 32 * 1024 * 1024
#: segment file naming: wal-<8-digit seq>.seg, seq strictly increasing
SEGMENT_RE = re.compile(r"^wal-(\d{8})\.seg$")

#: record types
SNAP = "snap"  # full shadow-state snapshot (opens every segment)
#: a BATCH of FleetView deltas: one framed record per writer drain, so
#: the per-delta cost is one list element inside one json.dumps — not a
#: dict build + dumps + crc + frame each (the <5% bench_wal_overhead
#: budget is won here). items: [[rv, kind, key, op, obj-or-null], ...]
#: — or, on the msgpack codec when the publisher handed over the delta's
#: already-encoded serve frame, the obj column holds the frame's JSON
#: payload BYTES instead of the dict (packed as bin = one memcpy, no
#: per-field re-pack; ``item_object`` decodes on read). rv-ascending and
#: contiguous within a record.
DELTAS = "d"
#: delta ops inside a DELTAS record
OP_UPSERT = "U"
OP_DELETE = "D"
#: bound on deltas per record: keeps one frame's blast radius (a torn
#: tail loses at most one frame) and memory bounded under huge drains.
#: 16384 (vs the original 4096) quarters the per-record overhead (wall
#: stamp, CRC frame, dict envelope) under sustained drains — a record is
#: still at most a few MB of pod skeletons, far under MAX_RECORD_BYTES
MAX_DELTAS_PER_RECORD = 16384

FSYNC_POLICIES = ("never", "interval", "always")


def encode_record(record: Dict[str, Any], *, sort: bool = False) -> bytes:
    """Compact record bytes (msgpack; JSON when msgpack is absent).
    Record bytes are deterministic either way (fixed key order, sorted
    snapshot objects), but replay determinism is defined over the
    canonical TERMINAL snapshot (history/replay.py), not raw WAL bytes.
    ``sort`` only affects the JSON fallback."""
    if _msgpack is not None:
        return _msgpack.packb(record, use_bin_type=True)
    return json.dumps(record, separators=(",", ":"), sort_keys=sort).encode()


def decode_record(payload: bytes):
    """Payload bytes -> record dict, or None when neither codec parses
    (the CRC already vouched for the bytes; this failing means a foreign
    writer, not a tear)."""
    if _msgpack is not None:
        try:
            return _msgpack.unpackb(payload, raw=False, strict_map_key=False)
        except Exception:  # noqa: BLE001 - fall through to the JSON fallback
            pass
    try:
        return json.loads(payload)
    except (json.JSONDecodeError, UnicodeDecodeError):
        return None


def frame(payload: bytes) -> bytes:
    return FRAME_HEADER.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload


def read_frames(data: bytes) -> Tuple[List[Dict[str, Any]], int, bool]:
    """Decode ``(records, clean_bytes, torn)`` from raw segment bytes.

    Stops at the first bad frame (short header, short payload, CRC or
    JSON mismatch, absurd length): everything before it is intact,
    everything after is unordered relative to the tear. ``clean_bytes``
    is the offset of the tear (== len(data) when the segment is clean).
    """
    records: List[Dict[str, Any]] = []
    offset = 0
    size = len(data)
    header = FRAME_HEADER
    while offset + header.size <= size:
        length, crc = header.unpack_from(data, offset)
        start = offset + header.size
        end = start + length
        if length == 0 or length > MAX_RECORD_BYTES or end > size:
            return records, offset, True
        payload = data[start:end]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return records, offset, True
        record = decode_record(payload)
        if not isinstance(record, dict) or "t" not in record:
            return records, offset, True
        records.append(record)
        offset = end
    return records, offset, offset != size


def segment_path(directory: Path, seq: int) -> Path:
    return directory / f"wal-{seq:08d}.seg"


def list_segments(directory: Path) -> List[Tuple[int, Path]]:
    """``(seq, path)`` pairs sorted by seq; ignores foreign files."""
    out: List[Tuple[int, Path]] = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        m = SEGMENT_RE.match(name)
        if m:
            out.append((int(m.group(1)), directory / name))
    out.sort()
    return out


def snapshot_record(
    rv: int,
    instance: str,
    state: Dict[Tuple[str, str], Dict[str, Any]],
    *,
    final: bool = False,
) -> Dict[str, Any]:
    """The segment-opening (and rebase / shutdown) full-state record.
    Objects are sorted by (kind, key), so identical state always encodes
    to identical bytes. ``final=True`` marks the terminal snapshot a
    clean close() writes — the marker recovery's clean-shutdown verdict
    keys off (an UNCLEAN end means acked-but-unwritten deltas may be
    lost, and the serve plane must mint a fresh view instance)."""
    record = {
        "t": SNAP,
        "rv": rv,
        "instance": instance,
        "wall": round(time.time(), 3),
        "objects": [
            [kind, key, state[(kind, key)]]
            for kind, key in sorted(state)
        ],
    }
    if final:
        record["final"] = True
    return record


def deltas_record(deltas, frames=None) -> Dict[str, Any]:
    """A batch of serve.view.Delta -> ONE WAL record (see ``DELTAS``).
    One wall stamp per record (forensics), not per delta.

    ``frames`` (parallel to ``deltas``, entries may be None) carries each
    delta's already-encoded chunk-framed JSON serve frame. On the msgpack
    codec, when EVERY delta in the batch has its frame (the eager-encode
    publish paths always do), the record is the frames CONCATENATED as
    one bin blob (``"f"``) — a join plus one memcpy into the record, no
    per-delta re-serialization at all; the chunk framing keeps the blob
    self-delimiting and each payload line carries rv/type/kind/key/object
    in full (``record_items`` decodes). A batch with holes falls back to
    the per-item ``"items"`` column shape, reusing frame payload bytes as
    the obj column where present (``item_object`` decodes). The JSON
    fallback codec cannot embed bytes, so it keeps packing dicts
    (correctness first — the <5% budget is a msgpack deployment's)."""
    wall = round(time.time(), 3)
    if (
        frames is not None
        and _msgpack is not None
        and len(frames) == len(deltas)
        and None not in frames
    ):
        return {"t": DELTAS, "wall": wall, "f": b"".join(frames)}
    items = []
    reuse = frames is not None and _msgpack is not None
    for i, d in enumerate(deltas):
        if d.object is None:
            items.append([d.rv, d.kind, d.key, OP_DELETE, None])
            continue
        obj: Any = d.object
        if reuse:
            fr = frames[i]
            if fr is not None:
                head_end = fr.index(b"\r\n")
                obj = bytes(fr[head_end + 2:-2])  # strip chunk framing
        items.append([d.rv, d.kind, d.key, OP_UPSERT, obj])
    return {
        "t": DELTAS,
        "wall": wall,
        "items": items,
    }


def item_object(obj):
    """The obj column of one DELTAS item -> the object dict (or None).
    Frame-payload BYTES columns (see ``deltas_record``) decode through
    the wire line's ``object`` field; dict columns pass through."""
    if isinstance(obj, (bytes, bytearray)):
        return json.loads(obj).get("object")
    return obj


def record_items(record: Dict[str, Any]):
    """One DELTAS record -> its ``[rv, kind, key, op, obj-or-bytes]``
    items, whichever shape the writer chose (``"items"`` column lists,
    or the ``"f"`` concatenated-frames blob — decoded here by walking
    the chunk framing and reading each payload line's wire fields).
    Callers still pass the obj column through ``item_object``."""
    blob = record.get("f")
    if not blob:
        return record.get("items", ())
    items = []
    off, size = 0, len(blob)
    while off < size:
        head_end = blob.index(b"\r\n", off)
        length = int(blob[off:head_end], 16)
        start = head_end + 2
        line = json.loads(blob[start:start + length])
        off = start + length + 2
        items.append([
            line.get("rv"),
            line.get("kind"),
            line.get("key"),
            OP_DELETE if line.get("type") == "DELETE" else OP_UPSERT,
            line.get("object"),
        ])
    return items


class _Segment:
    """The writer's view of one on-disk segment (active or sealed)."""

    __slots__ = ("seq", "path", "bytes", "records", "first_rv", "last_rv", "opened_monotonic")

    def __init__(self, seq: int, path: Path):
        self.seq = seq
        self.path = path
        self.bytes = 0
        self.records = 0
        self.first_rv: Optional[int] = None
        self.last_rv: Optional[int] = None
        self.opened_monotonic = time.monotonic()

    def note(self, rv: int, nbytes: int, nrecords: int = 1) -> None:
        self.bytes += nbytes
        self.records += nrecords
        if self.first_rv is None:
            self.first_rv = rv
        self.last_rv = rv

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.path.name,
            "seq": self.seq,
            "bytes": self.bytes,
            "records": self.records,
            "first_rv": self.first_rv,
            "last_rv": self.last_rv,
            "age_seconds": round(time.monotonic() - self.opened_monotonic, 1),
        }


class HistoryStore:
    """The durable history plane: WAL writer + recovery/read surface.

    Lifecycle::

        store = HistoryStore(dir, ...)        # scans + truncates torn tail
        recovered = store.recover()           # -> recovery.RecoveredState
        view.restore(...recovered...)         # caller rebuilds the view
        store.open(view.instance)             # writer thread starts
        view.attach_history(store)            # publishes flow in
        ...
        store.close()                         # drain + final snapshot + fsync

    ``publish`` is the only hot-path entry point (O(1) enqueue, called
    under the view's publish lock — see the module docstring for why the
    lock ordering is what keeps the WAL rv-ordered).
    """

    def __init__(
        self,
        directory: os.PathLike | str,
        *,
        segment_max_bytes: int = 8 * 1024 * 1024,
        segment_max_age_seconds: float = 3600.0,
        retain_segments: int = 8,
        fsync: str = "interval",
        fsync_interval_seconds: float = 1.0,
        max_queue_deltas: int = 65536,
        metrics=None,  # metrics.MetricsRegistry, optional
    ):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(f"fsync policy must be one of {FSYNC_POLICIES}, got {fsync!r}")
        self.directory = Path(directory)
        self.segment_max_bytes = max(4096, int(segment_max_bytes))
        self.segment_max_age_seconds = float(segment_max_age_seconds)
        self.retain_segments = max(2, int(retain_segments))
        self.fsync = fsync
        self.fsync_interval_seconds = max(0.01, float(fsync_interval_seconds))
        self.max_queue_deltas = max(1024, int(max_queue_deltas))
        self.metrics = metrics
        self.instance: Optional[str] = None
        # Callable[[], (rv, {(kind, key): obj})] — the live view's state,
        # used ONLY on overrun rebase: the dropped backlog means the
        # shadow no longer equals the view, so the rebase snapshot must
        # come from the source of truth (FleetView.state_for_history;
        # attach_history wires it)
        self.state_provider = None

        self._cond = threading.Condition()
        # deque[(deltas, frames-or-None)] — see publish()
        self._queue: collections.deque = collections.deque()
        self._queued = 0
        self._overrun = False  # queue blew past the cap; writer must rebase
        self._stop = False
        self._thread: Optional[threading.Thread] = None

        # writer-thread state (only the writer touches these after open(),
        # except under _cond for the stats/segments snapshot)
        self._state: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._rv = 0  # last rv written durably (well: handed to the OS)
        self._fh = None
        self._segments: List[_Segment] = []
        self._next_seq = 1
        self._last_fsync = time.monotonic()
        self._recovered = None  # recovery.RecoveredState after recover()
        # bumped whenever retained history RESHAPES under existing rvs —
        # overrun rebase (an rv hole opens) and retention deletion (the
        # floor moves): the serve plane's ?at= reconstruction LRU keys on
        # this, so anything that can change what an rv reconstructs to
        # (or whether it still can) invalidates cached bodies by simply
        # no longer matching their key
        self._cache_epoch = 0

        if metrics is not None:
            self._deltas_counter = metrics.counter("history_wal_deltas")
            self._records_counter = metrics.counter("history_wal_records")
            self._bytes_counter = metrics.counter("history_wal_bytes")
            self._fsync_counter = metrics.counter("history_wal_fsyncs")
            self._overrun_counter = metrics.counter("history_wal_overruns")
            self._snap_counter = metrics.counter("history_snapshots")
            self._segments_gauge = metrics.gauge("history_segments")
            self._rv_gauge = metrics.gauge("history_wal_rv")
            self._queue_gauge = metrics.gauge("history_wal_queue_depth")
            self._write_seconds = metrics.histogram("history_wal_write_seconds")
        else:
            self._deltas_counter = None
            self._records_counter = self._bytes_counter = self._fsync_counter = None
            self._overrun_counter = self._snap_counter = None
            self._segments_gauge = self._rv_gauge = self._queue_gauge = None
            self._write_seconds = None

    # -- recovery ---------------------------------------------------------

    def recover(self, *, journal_limit: int = 8192):
        """Scan the WAL directory, truncate the active segment's torn
        tail, rebuild the terminal state + the last ``journal_limit``
        deltas, and prime the writer's shadow. Returns the
        :class:`~k8s_watcher_tpu.history.recovery.RecoveredState`."""
        from k8s_watcher_tpu.history.recovery import recover_state

        self.directory.mkdir(parents=True, exist_ok=True)
        t0 = time.monotonic()
        recovered = recover_state(self.directory, journal_limit=journal_limit, truncate_tail=True)
        self._recovered = recovered
        self._state = dict(recovered.objects)
        self._rv = recovered.rv
        self.instance = recovered.instance
        self._segments = []
        for seq, path in list_segments(self.directory):
            seg = _Segment(seq, path)
            try:
                seg.bytes = path.stat().st_size
            except OSError:
                seg.bytes = 0
            info = recovered.segment_rvs.get(seq)
            if info is not None:
                seg.first_rv, seg.last_rv, seg.records = info
            self._segments.append(seg)
            self._next_seq = max(self._next_seq, seq + 1)
        if self._segments:
            logger.info(
                "History WAL recovered: rv=%d instance=%s segments=%d journal=%d%s",
                recovered.rv, recovered.instance, len(self._segments),
                len(recovered.journal),
                f" (truncated {recovered.truncated_bytes}B torn tail)" if recovered.truncated_bytes else "",
            )
        return recovered

    # -- lifecycle --------------------------------------------------------

    def open(self, instance: str) -> "HistoryStore":
        """Adopt the view's instance id and start the writer. On a cold
        directory (or after the view minted a fresh instance) the first
        thing written is a snapshot record of the current shadow state,
        so the WAL is never without a recovery anchor."""
        if self._thread is not None:
            return self
        self.directory.mkdir(parents=True, exist_ok=True)
        self.instance = instance
        if not self._segments:
            self._open_segment(write_snapshot=True)
        else:
            # append to the recovered active segment
            active = self._segments[-1]
            try:
                self._fh = open(active.path, "ab")
                # dirty marker: once this incarnation is appending, the
                # previous terminal snapshot is no longer the last record
                # — a crash from here on reads as UNCLEAN even if no
                # delta ever hits the disk (acked-but-unwritten deltas
                # may still have existed). Readers skip unknown types.
                self._write_bytes(frame(encode_record({"t": "open", "wall": round(time.time(), 3)})), self._rv, 1)
                self._sync(force=self.fsync != "never")
            except OSError as exc:
                logger.error("Could not reopen WAL segment %s (%s); rotating", active.path, exc)
                self._open_segment(write_snapshot=True)
        self._stop = False
        self._thread = threading.Thread(target=self._writer, name="history-wal", daemon=True)
        self._thread.start()
        return self

    @property
    def writer_alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def recovered(self):
        """The :meth:`recover` result (None before recover ran)."""
        return self._recovered

    def close(self, *, final_snapshot: bool = True, timeout: float = 10.0) -> None:
        """Drain the queue, optionally write a terminal snapshot record
        (the fast-recovery anchor a clean SIGTERM leaves behind), fsync,
        and stop the writer. ``final_snapshot=False`` stops WITHOUT the
        terminal anchor — the 'pause' shape crash tests use."""
        thread = self._thread
        if thread is None:
            self._close_fh()
            return
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        thread.join(timeout=timeout)
        if thread.is_alive():
            # wedged writer (stalled disk/NFS): do NOT touch the shared
            # file handle or shadow from this thread — interleaved writes
            # would tear the active segment. Leave the daemon detached
            # (it exits when it unwedges; _stop rejects new publishes);
            # the missing terminal snapshot makes the next boot read the
            # WAL as unclean, which is the truth.
            logger.error(
                "History WAL writer did not stop within %.1fs; detaching without a terminal snapshot",
                timeout,
            )
            return
        self._thread = None
        # the writer exited with the queue drained; anything left arrived
        # in the closing race — write it from this thread
        self._drain_once()
        if final_snapshot and self._fh is not None and self.instance is not None:
            self._write_snapshot(final=True)
        self._sync(force=self.fsync != "never")
        self._close_fh()

    def _close_fh(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    # -- hot path ---------------------------------------------------------

    def publish(self, deltas: Sequence, frames: Optional[Sequence] = None) -> None:
        """O(1) hand-off, called under the view's publish lock (that
        ordering IS the WAL's rv ordering). Never blocks on IO.
        ``frames`` (optional, parallel to ``deltas``, entries may be
        None) lets the writer reuse already-encoded serve frame bytes
        instead of re-packing objects — see ``deltas_record``."""
        with self._cond:
            if self._stop:
                return
            # callers hand over a fresh slice (never mutated after) — no
            # defensive copy on the hot path
            self._queue.append((deltas, frames))
            self._queued += len(deltas)
            if self._queued > self.max_queue_deltas:
                # wedged disk: drop the backlog, rebase with a snapshot
                dropped = self._queued
                self._queue.clear()
                self._queued = 0
                self._overrun = True
                if self._overrun_counter is not None:
                    self._overrun_counter.inc(dropped)
                logger.error(
                    "History WAL writer fell %d deltas behind; dropped backlog, "
                    "will rebase with a snapshot record", dropped,
                )
            self._cond.notify()

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until everything queued at call time is on disk (well,
        handed to the OS; fsync still follows the policy). The barrier
        ``reconstruct`` and the replay/smoke paths use."""
        deadline = time.monotonic() + timeout
        with self._cond:
            self._cond.notify_all()
            while self._queue or self._queued:
                if self._thread is None or not self._thread.is_alive():
                    # a dead writer with _queued deltas popped-but-unwritten
                    # means the barrier did NOT hold — never report success
                    return not self._queue and not self._queued
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(remaining, 0.1))
        return True

    # -- writer thread ----------------------------------------------------

    def _writer(self) -> None:
        while True:
            idle_sync = False
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(timeout=min(0.5, self.fsync_interval_seconds))
                    if self.fsync == "interval" and not self._queue and not self._stop:
                        # idle clusters still get their crash-loss bound:
                        # without this, a batch written just after the
                        # previous fsync would sit unsynced until the
                        # NEXT publish — indefinitely on a quiet fleet.
                        # The sync itself runs OUTSIDE the cond (file IO
                        # must never block publish); _sync re-checks the
                        # interval, so early wakes are free.
                        idle_sync = True
                        break
                if not self._queue and self._stop:
                    return
            if idle_sync:
                self._sync()
                continue
            self._drain_once()
            if self.fsync == "interval":
                self._sync()

    def _drain_once(self) -> None:
        """Write everything currently queued as one buffered write (plus
        rotation / rebase snapshots as needed)."""
        with self._cond:
            batches = list(self._queue)
            self._queue.clear()
            overrun = self._overrun
            self._overrun = False
        if overrun:
            # rebase: the dropped backlog left a hole, so re-anchor on a
            # snapshot of the LIVE view (the shadow is stale past the
            # hole); recovery clears its journal across the rv jump
            self._cache_epoch += 1
            if self.state_provider is not None:
                try:
                    self._rv, state = self.state_provider()
                    self._state = dict(state)
                except Exception:  # noqa: BLE001 — never kill the writer
                    logger.exception("History state provider failed during rebase")
            self._maybe_rotate()
            self._write_snapshot()
        if not batches:
            with self._cond:
                self._queued = 0
                self._cond.notify_all()
            return
        t0 = time.monotonic()
        self._maybe_rotate()
        flat = []
        flat_frames = []
        for deltas, frames in batches:
            flat.extend(deltas)
            if frames is None:
                flat_frames.extend([None] * len(deltas))
            else:
                flat_frames.extend(frames)
        count = len(flat)
        last_rv = self._rv
        buf = bytearray()
        nrecords = 0
        for start in range(0, count, MAX_DELTAS_PER_RECORD):
            chunk = flat[start:start + MAX_DELTAS_PER_RECORD]
            fchunk = flat_frames[start:start + MAX_DELTAS_PER_RECORD]
            buf += frame(encode_record(deltas_record(chunk, fchunk)))
            nrecords += 1
        if flat:
            last_rv = flat[-1].rv
        written = bool(buf) and self._write_bytes(bytes(buf), last_rv, nrecords)
        if flat and not written:
            # the disk refused (open/write failure): these deltas are
            # LOST — count them so /metrics shows durable history
            # silently bleeding, and leave the shadow un-folded so the
            # next snapshot stays consistent with what is actually on
            # disk (the rv hole makes recovery clear journal continuity)
            if self._overrun_counter is not None:
                self._overrun_counter.inc(count)
            logger.error("History WAL dropped %d deltas on write failure", count)
        if written:
            self._rv = last_rv
            # advance the shadow AFTER the write sticks, so snapshots
            # stay exactly consistent with the delta prefix ON DISK —
            # a failed write leaves an rv hole (recovery clears journal
            # continuity across it), never deltas smuggled into a
            # snapshot without their rvs
            state = self._state
            for delta in flat:
                if delta.object is None:
                    state.pop((delta.kind, delta.key), None)
                else:
                    state[(delta.kind, delta.key)] = delta.object
            if self._deltas_counter is not None:
                self._deltas_counter.inc(count)
        if self._fh is not None:
            # hand the buffered bytes to the OS once per drain (NOT an
            # fsync): concurrent readers — ?at= reconstruction, replay,
            # the flush() barrier's contract — read the files directly
            try:
                self._fh.flush()
            except OSError as exc:
                logger.error("History WAL buffer flush failed: %s", exc)
        if self.fsync == "always":
            self._sync(force=True)
        if self._write_seconds is not None:
            self._write_seconds.record(time.monotonic() - t0)
        if self._rv_gauge is not None:
            self._rv_gauge.set(self._rv)
        with self._cond:
            self._queued = max(0, self._queued - count)
            if not self._queue:
                self._queued = 0
            self._cond.notify_all()
        if self._queue_gauge is not None:
            self._queue_gauge.set(self._queued)

    def _write_bytes(self, blob: bytes, last_rv: int, nrecords: int) -> bool:
        if self._fh is None:
            self._open_segment(write_snapshot=True)
            if self._fh is None:
                return False  # disk refused; deltas are lost (counted)
        try:
            self._fh.write(blob)
        except OSError as exc:
            logger.error("History WAL write failed: %s", exc)
            self._close_fh()
            return False
        seg = self._segments[-1]
        seg.note(last_rv, len(blob), nrecords)
        if self._records_counter is not None:
            self._records_counter.inc(nrecords)
            self._bytes_counter.inc(len(blob))
        return True

    def _write_snapshot(self, *, final: bool = False) -> bool:
        payload = encode_record(
            snapshot_record(self._rv, self.instance or "", self._state, final=final),
            sort=True,
        )
        ok = self._write_bytes(frame(payload), self._rv, 1)
        if ok and self._snap_counter is not None:
            self._snap_counter.inc()
        return ok

    def _sync(self, force: bool = False) -> None:
        if self._fh is None:
            return
        now = time.monotonic()
        if not force and now - self._last_fsync < self.fsync_interval_seconds:
            return
        try:
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._last_fsync = now
            if self._fsync_counter is not None:
                self._fsync_counter.inc()
        except OSError as exc:
            logger.warning("History WAL fsync failed: %s", exc)

    def _maybe_rotate(self) -> None:
        if self._fh is None or not self._segments:
            return
        active = self._segments[-1]
        if (
            active.bytes >= self.segment_max_bytes
            or time.monotonic() - active.opened_monotonic >= self.segment_max_age_seconds
        ):
            self._sync(force=self.fsync != "never")
            self._close_fh()
            self._open_segment(write_snapshot=True)
            self._enforce_retention()

    def _open_segment(self, write_snapshot: bool) -> None:
        seq = self._next_seq
        path = segment_path(self.directory, seq)
        try:
            self.directory.mkdir(parents=True, exist_ok=True)
            self._fh = open(path, "ab")
        except OSError as exc:
            logger.error("Could not open WAL segment %s: %s", path, exc)
            self._fh = None
            return
        self._next_seq = seq + 1
        with self._cond:
            self._segments.append(_Segment(seq, path))
        if self._segments_gauge is not None:
            self._segments_gauge.set(len(self._segments))
        if write_snapshot and self.instance is not None:
            self._write_snapshot()

    def _enforce_retention(self) -> None:
        while len(self._segments) > self.retain_segments:
            with self._cond:
                victim = self._segments.pop(0)
            # the retention floor moved: rvs under it stop reconstructing,
            # so cached ?at= bodies keyed on the old epoch must die
            self._cache_epoch += 1
            try:
                victim.path.unlink()
            except OSError as exc:
                logger.warning("Could not delete expired WAL segment %s: %s", victim.path, exc)
        if self._segments_gauge is not None:
            self._segments_gauge.set(len(self._segments))

    # -- read surface (time travel / debug) -------------------------------

    @property
    def cache_epoch(self) -> int:
        """Monotonic counter naming the current shape of retained history
        (bumped on overrun rebase and retention deletion) — the serve
        plane's ``?at=`` LRU cache-key component."""
        return self._cache_epoch

    def retention_floor_rv(self) -> int:
        """The oldest rv reconstructible from retained segments: the
        opening snapshot rv of the oldest segment (0 on a cold WAL)."""
        with self._cond:
            for seg in self._segments:
                if seg.first_rv is not None:
                    return seg.first_rv
        return 0

    def reconstruct(self, at_rv: int, *, flush_timeout: float = 2.0):
        """Rebuild the fleet state as of ``at_rv`` from snapshot+deltas.

        Returns ``(status, rv, objects)`` where status is ``"ok"``
        (objects is the ``{(kind, key): obj}`` map at exactly ``at_rv``),
        ``"gone"`` (``at_rv`` precedes the retention horizon; rv carries
        the floor) or ``"future"`` (``at_rv`` was never written; rv
        carries the newest durable rv). Reads sealed files end to end —
        a forensic path, deliberately not the hot one.
        """
        from k8s_watcher_tpu.history.recovery import reconstruct_at

        self.flush(timeout=flush_timeout)
        return reconstruct_at(self.directory, at_rv)

    def stats(self) -> Dict[str, Any]:
        """The ``/debug/history`` segment inventory."""
        with self._cond:
            segments = [seg.to_dict() for seg in self._segments]
            queued = self._queued
        return {
            "dir": str(self.directory),
            "instance": self.instance,
            "fsync": self.fsync,
            "fsync_interval_seconds": self.fsync_interval_seconds,
            "segment_max_bytes": self.segment_max_bytes,
            "segment_max_age_seconds": self.segment_max_age_seconds,
            "retain_segments": self.retain_segments,
            "writer_alive": self.writer_alive,
            "durable_rv": self._rv,
            "retention_floor_rv": self.retention_floor_rv(),
            "queued_deltas": queued,
            "segments": segments,
            "total_bytes": sum(s["bytes"] for s in segments),
        }

    def health(self) -> Dict[str, Any]:
        """Folded into the serve plane's health: a dead writer thread
        means deltas silently stop persisting."""
        alive = self._thread is None or self._thread.is_alive()
        return {"healthy": alive, "writer_alive": self.writer_alive, "durable_rv": self._rv}
