"""Durable fleet history plane: segmented delta WAL + restart-surviving
recovery + deterministic replay (see ARCHITECTURE.md "History plane")."""

from k8s_watcher_tpu.history.recovery import (
    RecoveredState,
    journal_deltas,
    reconstruct_at,
    recover_state,
)
from k8s_watcher_tpu.history.replay import (
    ReplayResult,
    canonical_snapshot,
    replay_digest,
    replay_wal,
    snapshot_sha256,
)
from k8s_watcher_tpu.history.wal import FSYNC_POLICIES, HistoryStore

__all__ = [
    "FSYNC_POLICIES",
    "HistoryStore",
    "RecoveredState",
    "ReplayResult",
    "canonical_snapshot",
    "journal_deltas",
    "reconstruct_at",
    "recover_state",
    "replay_digest",
    "replay_wal",
    "snapshot_sha256",
]
