"""WAL recovery: rebuild the fleet view (and its rv line) from disk.

The contract that makes restart-surviving resume tokens possible:

- **State**: fold every retained segment in order — a ``snap`` record
  replaces the whole state, a ``delta`` record mutates one key — and the
  terminal fold IS the view the process died with (modulo the torn
  tail, which is truncated away).
- **rv continuity**: the recovered ``rv`` is the newest durable rv; the
  restarted view keeps counting from it, so the monotonic rv line spans
  incarnations and a pre-restart token stays meaningful.
- **Instance continuity**: the view's instance id rides every snapshot
  record; recovery re-adopts it, so the ``&view=`` epoch check passes
  across restarts instead of 410ing per incarnation.
- **Journal preload**: the last ``journal_limit`` deltas are handed back
  so the in-memory delta journal (the thing ``read_since`` actually
  serves) starts warm — a token minted before SIGTERM resumes from
  memory exactly as if the process had never died. Tokens older than
  the preloaded journal 410 — the same compaction-horizon semantics as
  steady state, now applied across restarts.

Columnar view core: the recovered ``objects`` dict seeds
``FleetView.restore`` which, on the columnar core, reseeds the store's
columns IN PLACE — pods land in the lazy pending buffer (no O(fleet)
``json.dumps`` on the boot path; the first snapshot-body build pays the
serialization it was going to pay anyway) and the node/cluster interners
keep their codes across the restore, so any cached analytics
materializations stay decodable. The fold order below (snapshot objects,
then deltas in rv order) is exactly the dict-insertion order the dict
core would have ended with, which is what keeps post-restore snapshot
bodies byte-identical across the two cores.

Tear handling: a crash tears at most the tail of the *active* segment
(one buffered write per drain), which the writer truncates on reopen. A
torn *sealed* segment (bit rot, foreign truncation) does not end the
world either: the fold skips the segment's damaged tail and resyncs at
the NEXT segment's opening snapshot — the journal is cleared across the
resync because delta continuity was lost, never silently bridged.
"""

from __future__ import annotations

import logging
import time
from pathlib import Path
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

from k8s_watcher_tpu.history.wal import (
    DELTAS,
    FRAME_HEADER,
    MAX_RECORD_BYTES,
    OP_DELETE,
    SNAP,
    decode_record,
    item_object,
    list_segments,
    read_frames,
    record_items,
)

logger = logging.getLogger(__name__)


class RecoveredState(NamedTuple):
    """Everything a restarted view needs from the WAL."""

    instance: Optional[str]  # None on a cold (empty) WAL
    rv: int
    objects: Dict[Tuple[str, str], Dict[str, Any]]
    journal: List[Dict[str, Any]]  # delta records, rv-ascending, tail only
    #: seq -> (first_rv, last_rv, records) for the segment inventory
    segment_rvs: Dict[int, Tuple[int, int, int]]
    segments: int
    truncated_bytes: int  # torn tail removed from the active segment
    #: True iff the WAL ends in the terminal snapshot a clean close()
    #: writes (and nothing tore anywhere). An UNCLEAN end means deltas
    #: acked to subscribers beyond the durable rv may have been lost —
    #: the serve plane must then mint a FRESH view instance so
    #: pre-crash resume tokens 410 into a re-snapshot instead of
    #: silently grafting onto a re-minted (divergent) rv line.
    clean: bool


def _fold_records(
    records,
    state: Dict[Tuple[str, str], Dict[str, Any]],
    journal,  # deque(maxlen=journal_limit) — the tail bound is the deque's
    rv: int,
    instance: Optional[str],
) -> Tuple[int, Optional[str]]:
    """Fold one segment's records into (state, journal); returns the
    updated (rv, instance)."""
    for record in records:
        rtype = record.get("t")
        if rtype == SNAP:
            snap_rv = int(record.get("rv", 0))
            state.clear()
            for entry in record.get("objects", ()):  # [[kind, key, obj], ...]
                try:
                    kind, key, obj = entry
                except (TypeError, ValueError):
                    continue
                state[(str(kind), str(key))] = obj
            if snap_rv != rv:
                # a rebase (overrun hole) or a resync after a torn sealed
                # segment: delta continuity across this point is gone, so
                # the preloaded journal must not bridge it
                journal.clear()
            rv = snap_rv
            instance = record.get("instance") or instance
        elif rtype == DELTAS:
            for item in record_items(record):
                try:
                    delta_rv, kind, key, op, obj = item
                    delta_rv = int(delta_rv)
                    obj = item_object(obj)
                except (TypeError, ValueError):
                    continue
                if delta_rv <= rv and rv:
                    # replay of an already-folded rv — idempotent skip
                    continue
                if rv and delta_rv != rv + 1:
                    # an rv hole (overrun rebase without a provider, or a
                    # damaged record skipped upstream): the journal must
                    # stay contiguous — resume continuity across the hole
                    # is gone
                    journal.clear()
                kind = str(kind)
                key = str(key)
                if op == OP_DELETE:
                    state.pop((kind, key), None)
                    obj = None
                else:
                    state[(kind, key)] = obj
                rv = delta_rv
                journal.append({"rv": delta_rv, "kind": kind, "key": key, "op": op, "object": obj})
        # unknown record types are skipped (forward compatibility)
    return rv, instance


def journal_deltas(journal_records: List[Dict[str, Any]]):
    """Recovered journal records -> the ``serve.view.Delta`` tuples the
    in-memory journal preloads. Monotonic ``t`` stamps are re-minted at
    boot (monotonic clocks don't survive restarts); the wall stamps stay
    in the WAL for forensics."""
    from k8s_watcher_tpu.serve.view import Delta

    now_monotonic = time.monotonic()
    return [
        Delta(
            int(r.get("rv", 0)),
            str(r.get("kind", "")),
            str(r.get("key", "")),
            "DELETE" if r.get("op") == OP_DELETE else "UPSERT",
            None if r.get("op") == OP_DELETE else r.get("object"),
            now_monotonic,
        )
        for r in journal_records
    ]


def _first_rv(records, fallback: int) -> int:
    """The first rv a segment's records cover (its opening snapshot's rv
    in the normal layout; the first delta's for a headless segment)."""
    for record in records:
        if record.get("t") == SNAP:
            return int(record.get("rv", fallback))
        if record.get("t") == DELTAS:
            items = record_items(record) or ()
            if items:
                try:
                    return int(items[0][0])
                except (TypeError, ValueError, IndexError):
                    continue
    return fallback


def recover_state(
    directory: Path | str,
    *,
    journal_limit: int = 8192,
    truncate_tail: bool = False,
) -> RecoveredState:
    """Fold every retained segment; optionally truncate the ACTIVE
    (last) segment's torn tail in place (the writer-owned open path —
    read-only consumers like replay leave files untouched)."""
    import collections

    directory = Path(directory)
    segments = list_segments(directory)
    state: Dict[Tuple[str, str], Dict[str, Any]] = {}
    # maxlen deque: a full production WAL folds millions of deltas, and
    # a list-based tail trim (del [:1] per delta past the limit) made
    # boot recovery quadratic — measured ~14x slower than the deque
    journal: collections.deque = collections.deque(maxlen=max(1, journal_limit))
    segment_rvs: Dict[int, Tuple[int, int, int]] = {}
    rv = 0
    instance: Optional[str] = None
    truncated = 0
    torn_any = False
    last_record_type: Optional[str] = None
    last_snap_rv = -1
    for index, (seq, path) in enumerate(segments):
        try:
            data = path.read_bytes()
        except OSError as exc:
            logger.warning("Unreadable WAL segment %s (%s); skipping", path, exc)
            continue
        records, clean_bytes, torn = read_frames(data)
        if torn:
            torn_any = True
            if index == len(segments) - 1:
                if truncate_tail:
                    try:
                        with open(path, "r+b") as fh:
                            fh.truncate(clean_bytes)
                        truncated = len(data) - clean_bytes
                        logger.warning(
                            "Truncated %dB torn tail off WAL segment %s",
                            truncated, path,
                        )
                    except OSError as exc:
                        logger.error("Could not truncate torn WAL tail %s: %s", path, exc)
            else:
                # a damaged SEALED segment: fold its clean prefix; the
                # next segment's opening snapshot resyncs (and clears the
                # journal — continuity was lost here)
                logger.warning(
                    "WAL segment %s is torn mid-chain (%d clean of %d bytes); "
                    "resyncing at the next segment's snapshot",
                    path, clean_bytes, len(data),
                )
        rvs_before = rv
        rv, instance = _fold_records(records, state, journal, rv, instance)
        if records:
            segment_rvs[seq] = (_first_rv(records, rvs_before), rv, len(records))
            last = records[-1]
            last_record_type = last.get("t")
            # only the FINAL-flagged terminal snapshot counts as a clean
            # end: a rotation/rebase snapshot as the last record means
            # the process died right after writing it — acked deltas may
            # still have been lost
            last_snap_rv = (
                int(last.get("rv", -1))
                if last_record_type == SNAP and last.get("final")
                else -1
            )
    return RecoveredState(
        instance=instance,
        rv=rv,
        objects=state,
        journal=list(journal),
        segment_rvs=segment_rvs,
        segments=len(segments),
        truncated_bytes=truncated,
        # clean close() leaves a terminal snapshot as the very last
        # record, at exactly the final rv, with nothing torn anywhere
        clean=(not torn_any and last_record_type == SNAP and last_snap_rv == rv),
    )


def _peek_first_record(path: Path):
    """Read just the first framed record of a segment (its opening
    snapshot, in the normal layout) — the cheap seek primitive
    ``reconstruct_at`` uses to skip whole segments."""
    import zlib

    try:
        with open(path, "rb") as fh:
            header = fh.read(FRAME_HEADER.size)
            if len(header) < FRAME_HEADER.size:
                return None
            length, crc = FRAME_HEADER.unpack(header)
            if length == 0 or length > MAX_RECORD_BYTES:
                return None
            payload = fh.read(length)
            if len(payload) < length or zlib.crc32(payload) & 0xFFFFFFFF != crc:
                return None
    except OSError:
        return None
    record = decode_record(payload)
    return record if isinstance(record, dict) else None


def reconstruct_at(directory: Path | str, at_rv: int):
    """Time travel: the fleet state as of exactly ``at_rv``.

    Returns ``(status, rv, objects)``:

    - ``("ok", at_rv, state)`` — folded from the newest snapshot at or
      before ``at_rv`` plus the deltas up to it;
    - ``("gone", anchor_rv, None)`` — ``at_rv`` is not reconstructible:
      it precedes the retention horizon OR sits inside a hole (overrun
      rebase / tear resync). ``anchor_rv`` is a reconstructible rv to
      re-anchor at (the retention floor, or the snapshot past the hole);
    - ``("future", newest_rv, None)`` — ``at_rv`` is past everything
      durable (the caller distinguishes "not yet flushed" from "never").
    """
    directory = Path(directory)
    segments = list_segments(directory)
    state: Dict[Tuple[str, str], Dict[str, Any]] = {}
    rv = 0
    floor: Optional[int] = None
    reached = False
    # seek: every segment opens with a full snapshot, so the fold can
    # start at the NEWEST segment whose opening snapshot is <= at_rv
    # instead of decoding the entire retained WAL (up to 256 MiB in the
    # production shape) on a serve handler thread per ?at= query. The
    # peeks also yield the true retention floor (oldest opening snap).
    start_idx = 0
    peeks = [_peek_first_record(path) for _seq, path in segments]
    for record in peeks:
        if record is not None and record.get("t") == SNAP:
            floor = int(record.get("rv", 0))
            break
    for i in range(len(segments) - 1, -1, -1):
        record = peeks[i]
        if (
            record is not None
            and record.get("t") == SNAP
            and int(record.get("rv", 0)) <= at_rv
        ):
            start_idx = i
            break
    for _seq, path in segments[start_idx:]:
        try:
            data = path.read_bytes()
        except OSError:
            continue
        records, _clean, _torn = read_frames(data)
        for record in records:
            rtype = record.get("t")
            if rtype == SNAP:
                snap_rv = int(record.get("rv", 0))
                if floor is None:
                    floor = snap_rv
                if snap_rv > at_rv:
                    # overshoot. The fold is the at_rv state ONLY when it
                    # stands exactly at at_rv: the rv line is dense, so a
                    # jump from rv < at_rv straight to snap_rv > at_rv
                    # means at_rv sits inside a HOLE (overrun rebase /
                    # tear resync) — serving the older state as
                    # "historical at at_rv" would be silently wrong data
                    # on the exact forensic surface built for postmortems
                    if reached and rv == at_rv:
                        return ("ok", at_rv, state)
                    return ("gone", floor if not reached else snap_rv, None)
                state.clear()
                for entry in record.get("objects", ()):
                    try:
                        kind, key, obj = entry
                    except (TypeError, ValueError):
                        continue
                    state[(str(kind), str(key))] = obj
                rv = snap_rv
                reached = rv <= at_rv
            elif rtype == DELTAS:
                for item in record_items(record):
                    try:
                        delta_rv, kind, key, op, obj = item
                        delta_rv = int(delta_rv)
                        obj = item_object(obj)
                    except (TypeError, ValueError):
                        continue
                    if delta_rv <= rv and rv:
                        continue
                    if delta_rv > at_rv:
                        # dense-line overshoot means rv == at_rv (the ok
                        # case); rv < at_rv here implies delta_rv > rv+1,
                        # i.e. at_rv sits inside a failed-write hole
                        if rv == at_rv:
                            return ("ok", at_rv, state)
                        return ("gone", delta_rv, None)
                    if op == OP_DELETE:
                        state.pop((str(kind), str(key)), None)
                    else:
                        state[(str(kind), str(key))] = obj
                    rv = delta_rv
                    reached = True
    if not reached:
        return ("gone", floor if floor is not None else 0, None)
    if rv < at_rv:
        return ("future", rv, None)
    return ("ok", at_rv, state)
