"""Deterministic WAL replay: any production capture becomes a fixture.

``replay_wal`` feeds a captured WAL back through a **fresh FleetView**
— the real apply machinery, not a shortcut fold — asserting at every
step that the view re-mints exactly the recorded rv. Because the view's
rv space is dense (one delta, one increment, no-ops burn nothing) and
WAL records serialize canonically (sorted keys, compact separators),
the same capture always reduces to the same terminal snapshot, byte for
byte: replay it twice, compare the bytes, and any divergence is a real
nondeterminism bug in the view/WAL contract — which is what makes a
captured incident WAL a regression fixture (``make history-smoke``
gates exactly this round trip).

What determinism does and does not guarantee:

- **Guaranteed**: identical WAL bytes -> identical terminal snapshot
  bytes (and identical snapshot at any ``--at`` rv), across processes,
  hosts and Python versions (no dict-order, timestamp or id leakage —
  wall stamps live in the WAL records but never in the canonical
  snapshot).
- **Not guaranteed**: that two *captures* of the same cluster churn are
  identical (thread interleaving legitimately orders concurrent deltas
  differently), or that the WAL is a complete k8s event log (it records
  view deltas — post-filter, post-dedup — and an overrun rebase leaves
  a documented hole bridged by a snapshot record).
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path
from typing import Any, Dict, NamedTuple, Optional, Tuple

from k8s_watcher_tpu.history.wal import (
    DELTAS,
    OP_DELETE,
    SNAP,
    item_object,
    list_segments,
    read_frames,
    record_items,
)


class ReplayResult(NamedTuple):
    rv: int
    instance: Optional[str]
    objects: Dict[Tuple[str, str], Dict[str, Any]]
    deltas_applied: int
    snapshots_seen: int
    segments: int
    #: rv-mint mismatches between the recorded WAL and the fresh view
    #: (always 0 on a healthy capture; non-zero means the WAL and the
    #: view disagree about the delta algebra — a real bug)
    rv_mismatches: int


def canonical_snapshot(rv: int, objects: Dict[Tuple[str, str], Dict[str, Any]]) -> bytes:
    """The byte-comparable terminal form: sorted keys at every level,
    compact separators, no timestamps."""
    doc = {
        "rv": rv,
        "objects": [
            [kind, key, objects[(kind, key)]]
            for kind, key in sorted(objects)
        ],
    }
    return json.dumps(doc, separators=(",", ":"), sort_keys=True).encode()


def snapshot_sha256(snapshot: bytes) -> str:
    return hashlib.sha256(snapshot).hexdigest()


def replay_wal(directory: Path | str, *, at: Optional[int] = None) -> ReplayResult:
    """Feed the WAL through a fresh :class:`~k8s_watcher_tpu.serve.view.FleetView`.

    Snapshot records seed (or re-seed, across a rebase hole) the view via
    ``restore``; delta records go through the REAL ``apply`` and the
    re-minted rv is checked against the recorded one. ``at`` stops the
    replay at that rv (inclusive) — the offline twin of ``?at=``.
    """
    from k8s_watcher_tpu.serve.view import FleetView

    directory = Path(directory)
    view = FleetView(compact_horizon=1)
    instance: Optional[str] = None
    deltas_applied = 0
    snapshots_seen = 0
    mismatches = 0
    rv = 0
    segments = list_segments(directory)
    for _seq, path in segments:
        try:
            data = path.read_bytes()
        except OSError:
            continue
        records, _clean, _torn = read_frames(data)
        for record in records:
            rtype = record.get("t")
            if rtype == SNAP:
                snap_rv = int(record.get("rv", 0))
                if at is not None and snap_rv > at:
                    break
                snapshots_seen += 1
                instance = record.get("instance") or instance
                state = {}
                for entry in record.get("objects", ()):
                    try:
                        kind, key, obj = entry
                    except (TypeError, ValueError):
                        continue
                    state[(str(kind), str(key))] = obj
                view = FleetView(compact_horizon=1)
                view.restore(instance=instance or view.instance, rv=snap_rv, objects=state, journal=[])
                rv = snap_rv
            elif rtype == DELTAS:
                past_at = False
                for item in record_items(record):
                    try:
                        delta_rv, kind, key, op, obj = item
                        delta_rv = int(delta_rv)
                        obj = item_object(obj)
                    except (TypeError, ValueError):
                        continue
                    if delta_rv <= rv and rv:
                        continue  # rotation re-read; idempotent
                    if at is not None and delta_rv > at:
                        past_at = True
                        break
                    view.apply(str(kind), str(key), None if op == OP_DELETE else obj)
                    if view.rv != delta_rv:
                        mismatches += 1
                        # resync the line so one mismatch doesn't cascade
                        view.restore(
                            instance=view.instance, rv=delta_rv,
                            objects=dict(view.state_for_history()[1]), journal=[],
                        )
                    rv = delta_rv
                    deltas_applied += 1
                if past_at:
                    break
        else:
            continue
        break  # inner break (past --at) propagates out
    _final_rv, objects = view.state_for_history()
    return ReplayResult(
        rv=rv,
        instance=instance,
        objects=objects,
        deltas_applied=deltas_applied,
        snapshots_seen=snapshots_seen,
        segments=len(segments),
        rv_mismatches=mismatches,
    )


def replay_digest(directory: Path | str, *, at: Optional[int] = None) -> Dict[str, Any]:
    """One replay pass reduced to the comparable facts (the smoke's
    byte-compare leg runs this twice)."""
    result = replay_wal(directory, at=at)
    snapshot = canonical_snapshot(result.rv, result.objects)
    return {
        "rv": result.rv,
        "instance": result.instance,
        "objects": len(result.objects),
        "deltas_applied": result.deltas_applied,
        "snapshots_seen": result.snapshots_seen,
        "segments": result.segments,
        "rv_mismatches": result.rv_mismatches,
        "snapshot_bytes": len(snapshot),
        "sha256": snapshot_sha256(snapshot),
    }
