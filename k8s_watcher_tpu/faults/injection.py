"""Deterministic fault/churn generators.

Acceptance config #5 (BASELINE.md) demands 1 k pod events/min sustained
under churn, preemption and fault injection. These helpers produce that
load deterministically (seeded PRNG — no wall-clock randomness) so the
churn test is reproducible:

- ``ChurnGenerator``: a scripted fleet of slice pods cycling through
  create/ready/preempt/fail/delete transitions.
- ``FaultyNotifier``: wraps a send callable, failing a configurable fraction
  of calls (and optionally delaying) to exercise retry + backpressure.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Dict, Iterator, Optional

from k8s_watcher_tpu.watch.fake import build_pod
from k8s_watcher_tpu.watch.source import EventType, WatchEvent


class ChurnGenerator:
    """Generate a deterministic stream of slice-pod churn events."""

    def __init__(
        self,
        *,
        n_slices: int = 4,
        workers_per_slice: int = 4,
        chips_per_worker: int = 4,
        namespace: str = "default",
        seed: int = 0,
        preempt_prob: float = 0.05,
        fail_prob: float = 0.02,
        node_namer: Optional[Callable[[int, int], str]] = None,
    ):
        self.n_slices = n_slices
        self.workers_per_slice = workers_per_slice
        self.chips_per_worker = chips_per_worker
        self.namespace = namespace
        self.rng = random.Random(seed)
        self.preempt_prob = preempt_prob
        self.fail_prob = fail_prob
        # (slice_idx, worker_idx) -> spec.nodeName: gives churned pods a
        # stable host identity so node-attributed consumers (the health
        # plane's phase-latency scoring, slice node-down folding) see
        # realistic placement. None keeps pods unscheduled, the
        # pre-round-13 shape.
        self.node_namer = node_namer
        self._rv = 0
        # worker state: (slice_idx, worker_idx) -> phase or None (deleted)
        self._phase: Dict[tuple, Optional[str]] = {}

    def _pod(self, s: int, w: int, phase: str, *, preempted: bool = False) -> Dict[str, Any]:
        self._rv += 1
        topology_chips = self.workers_per_slice * self.chips_per_worker
        conditions = None
        if preempted:
            # what a real spot/preemptible TPU worker carries on its way
            # out: the scheduler's status.reason plus the k8s >=1.26
            # DisruptionTarget condition — downstream payloads classify
            # this into the `disruption` block (pipeline/extract.py)
            conditions = [{
                "type": "DisruptionTarget",
                "status": "True",
                "reason": "PreemptionByScheduler",
                "message": "preempted by higher-priority workload",
            }]
        return build_pod(
            f"slice{s}-worker-{w}",
            self.namespace,
            uid=f"uid-s{s}-w{w}",
            node_name=self.node_namer(s, w) if self.node_namer is not None else None,
            phase=phase,
            tpu_chips=self.chips_per_worker,
            tpu_topology=f"1x1x{topology_chips}",
            tpu_accelerator="tpu-v5p-slice",
            gke_slice_fields={
                "jobset.sigs.k8s.io/jobset-name": f"train-{s}",
                "batch.kubernetes.io/job-name": f"train-{s}-job",
                "batch.kubernetes.io/job-completion-index": w,
            },
            container_statuses=[{"name": "main", "ready": phase == "Running", "restartCount": 0}],
            resource_version=str(self._rv),
            status_reason="Preempted" if preempted else None,
            conditions=conditions,
        )

    def events(self, n_events: int) -> Iterator[WatchEvent]:
        """Yield exactly ``n_events`` churn events."""
        emitted = 0
        while emitted < n_events:
            s = self.rng.randrange(self.n_slices)
            w = self.rng.randrange(self.workers_per_slice)
            key = (s, w)
            phase = self._phase.get(key)
            roll = self.rng.random()

            preempted = False
            if phase is None:  # (re)create
                new_phase, etype = "Pending", EventType.ADDED
            elif phase == "Pending":
                new_phase, etype = "Running", EventType.MODIFIED
            elif phase == "Running":
                if roll < self.fail_prob:
                    new_phase, etype = "Failed", EventType.MODIFIED
                elif roll < self.fail_prob + self.preempt_prob:
                    new_phase, etype = None, EventType.DELETED  # preemption
                    preempted = True
                else:
                    new_phase, etype = "Running", EventType.MODIFIED  # status noise
            else:  # Failed -> controller deletes, then recreated later
                new_phase, etype = None, EventType.DELETED

            pod_phase = new_phase if new_phase is not None else (phase or "Running")
            event = WatchEvent(
                type=etype,
                pod=self._pod(s, w, pod_phase, preempted=preempted),
                resource_version=str(self._rv),
            )
            self._phase[key] = new_phase
            emitted += 1
            yield event


class FaultyNotifier:
    """Wrap a ``send(payload) -> bool`` with seeded failures/latency."""

    def __init__(
        self,
        send: Callable[[Dict[str, Any]], bool],
        *,
        fail_prob: float = 0.0,
        delay_seconds: float = 0.0,
        seed: int = 0,
    ):
        self._send = send
        self.fail_prob = fail_prob
        self.delay_seconds = delay_seconds
        self.rng = random.Random(seed)
        self.calls = 0
        self.injected_failures = 0

    def __call__(self, payload: Dict[str, Any]) -> bool:
        self.calls += 1
        if self.delay_seconds:
            time.sleep(self.delay_seconds)
        if self.fail_prob and self.rng.random() < self.fail_prob:
            self.injected_failures += 1
            return False
        return self._send(payload)
