"""Fault injection for churn/resilience testing (SURVEY.md §5 — ABSENT in
the reference; required for acceptance config #5)."""

from k8s_watcher_tpu.faults.injection import ChurnGenerator, FaultyNotifier  # noqa: F401
