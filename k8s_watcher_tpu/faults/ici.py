"""ICI fault injection (SURVEY.md §5: "ICI fault injection hooks for the
v5p-128 acceptance config"; §7 hard part (d): link faults testable below
v5p scale).

A real degraded chip/link cannot be conjured on demand, so faults are
modeled *inside the probe programs themselves*, gated per-device with
compiler-friendly control flow (``lax.cond`` on the device's mesh position
— no data-dependent Python, SPMD-safe):

- **slow chip**: one device runs a chained-matmul delay before joining the
  collective, so every collective that waits on it stretches — exactly the
  wall-clock signature of a thermally-throttled or driver-degraded chip.
- **corrupt chip**: one device perturbs its contribution, so checksums
  fail — the signature of bad HBM / a flaky lane.

The probe kernels (parallel/collectives.py) accept an ``IciFaultSpec`` and
the link prober (probe/links.py) must then *localize* the injected fault;
tests assert it fingers the right device. The spec is test/chaos tooling:
production probes pass ``fault=None`` and the gating code is never traced.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class IciFaultSpec:
    """Which device misbehaves, and how.

    ``slow_device_id`` / ``corrupt_device_id`` are ``jax.Device.id`` values
    (global, stable across meshes — the same spec applies to the full-mesh
    psum probe and every 2-device link probe, which is what lets the link
    prober triangulate).
    """

    slow_device_id: Optional[int] = None
    slow_matmul_size: int = 128
    slow_iters: int = 100
    corrupt_device_id: Optional[int] = None
    corrupt_magnitude: float = 1e6

    @property
    def active(self) -> bool:
        return self.slow_device_id is not None or self.corrupt_device_id is not None


def apply_fault(
    x: jax.Array,
    fault: Optional[IciFaultSpec],
    member_device_ids: Sequence[int],
    linear_index: jax.Array,
) -> jax.Array:
    """Apply ``fault`` to this shard's value inside a shard_map'd program.

    ``member_device_ids`` is the static tuple of ``Device.id`` in linear mesh
    order; ``linear_index`` is this member's traced position in that order.
    Devices not named by the spec are untouched (the heavy branch is a
    ``lax.cond`` arm only the faulty device executes at runtime).
    """
    if fault is None or not fault.active:
        return x
    ids = tuple(member_device_ids)

    if fault.slow_device_id in ids:
        pos = ids.index(fault.slow_device_id)
        size, iters = fault.slow_matmul_size, fault.slow_iters

        def heavy() -> jax.Array:
            m = jnp.full((size, size), 1e-3, dtype=jnp.bfloat16)

            def body(_, c):
                y = jnp.dot(c, c, preferred_element_type=jnp.float32)
                # renormalize so the chain can't overflow bf16
                y = y * jax.lax.rsqrt(jnp.mean(y * y) + 1e-6)
                return y.astype(jnp.bfloat16)

            r = jax.lax.fori_loop(0, iters, body, m)
            # fold to a negligible-but-not-DCE-able scalar
            return r.astype(jnp.float32).sum() * jnp.float32(1e-30)

        extra = jax.lax.cond(linear_index == pos, heavy, lambda: jnp.float32(0.0))
        x = x + extra.astype(x.dtype)

    if fault.corrupt_device_id in ids:
        pos_c = ids.index(fault.corrupt_device_id)
        x = jnp.where(
            linear_index == pos_c,
            x + jnp.asarray(fault.corrupt_magnitude, dtype=x.dtype),
            x,
        )
    return x
