"""Probe agent: run the health probe on a cadence, report via the sink.

Process model (SURVEY.md §7 hard part (a)): the watcher is a cluster-external
singleton; the probe must execute on the TPU hosts. ``ProbeAgent`` is that
probe loop. In-process mode covers dev and single-host deployments; for
multi-host slices the same agent runs standalone on every slice host
(``scripts/probe_agent.py``, one process per host via DaemonSet/JobSet with
``jax.distributed`` initialized) and reports to clusterapi directly —
process 0 does the reporting, all processes join the collectives.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Callable, Optional

import jax
import numpy as np

from k8s_watcher_tpu.config.schema import TpuConfig
from k8s_watcher_tpu.metrics import MetricsRegistry
from k8s_watcher_tpu.pipeline.pipeline import Notification
from k8s_watcher_tpu.probe.device import enumerate_devices, host_identity, host_identity_map
from k8s_watcher_tpu.probe.ici import run_ici_probe, run_mxu_probe
from k8s_watcher_tpu.probe.report import ProbeReport
from k8s_watcher_tpu.probe.trend import TrendTracker

logger = logging.getLogger(__name__)


class ProbeAgent:
    def __init__(
        self,
        tpu_config: TpuConfig,
        *,
        environment: str,
        sink: Callable[[Notification], None],
        metrics: Optional[MetricsRegistry] = None,
        mesh=None,
        expected_platform: Optional[str] = "auto",
        heartbeat: Optional[Callable[[], None]] = None,  # stamped per completed cycle
    ):
        self.config = tpu_config
        self.environment = environment
        self.sink = sink
        self.metrics = metrics or MetricsRegistry()
        self.mesh = mesh
        self.heartbeat = heartbeat or (lambda: None)
        # "auto": the configured backend IS the platform contract — a tpu
        # probe finding only CPU devices reports unhealthy, not healthy-CPU.
        # Pass an explicit platform (or None to disable) for test meshes.
        self.expected_platform = tpu_config.backend if expected_platform == "auto" else expected_platform
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # optional per-report observer (remediate.ProbeRemediationPolicy):
        # sees every completed report, healthy or not, on the agent thread
        self.report_observer: Optional[Callable[..., Any]] = None
        # flight recorder: last-N cycle summaries for /debug/probes — the
        # trend endpoint shows anchors, this shows the raw recent history
        # an operator diffs them against
        self._cycles: collections.deque = collections.deque(maxlen=64)
        self._cycles_lock = threading.Lock()
        self.trend: Optional[TrendTracker] = None
        if tpu_config.probe_trend_enabled:
            self.trend = TrendTracker(
                window=tpu_config.probe_trend_window,
                recent=tpu_config.probe_trend_recent,
                drop_factor=tpu_config.probe_trend_drop_factor,
                rise_factor=tpu_config.probe_trend_rise_factor,
                min_history=tpu_config.probe_trend_min_history,
            )

    # traces retained under profile_dir; each probe cycle writes one run
    # dir, so without a cap a 30s-interval agent writes ~2880/day and
    # eventually fills the disk of the node it is meant to keep healthy
    MAX_PROFILE_RUNS = 20

    def run_once(self) -> ProbeReport:
        """One probe cycle; wrapped in a ``jax.profiler`` trace when
        ``tpu.probe.profile_dir`` is set (SURVEY.md §5: the tracing
        subsystem the reference lacked — each cycle becomes a
        TensorBoard-loadable trace of the device programs)."""
        if self.config.probe_profile_dir:
            with jax.profiler.trace(self.config.probe_profile_dir):
                report = self._run_once_inner()
            self._prune_profiles(self.config.probe_profile_dir)
            return report
        return self._run_once_inner()

    def _prune_profiles(self, profile_dir: str) -> None:
        """Keep only the newest MAX_PROFILE_RUNS trace run-dirs."""
        import shutil
        from pathlib import Path

        runs_root = Path(profile_dir) / "plugins" / "profile"
        if not runs_root.is_dir():
            return
        runs = sorted((d for d in runs_root.iterdir() if d.is_dir()), key=lambda d: d.name)
        for stale in runs[: -self.MAX_PROFILE_RUNS]:
            try:
                shutil.rmtree(stale)
            except OSError as exc:
                logger.warning("Could not prune old probe trace %s: %s", stale, exc)

    def _run_once_inner(self) -> ProbeReport:
        t0 = time.monotonic()
        devices = enumerate_devices(
            expected_per_host=self.config.expected_chips_per_host,
            expected_platform=self.expected_platform,
        )
        ici = run_ici_probe(self.mesh, payload_bytes=self.config.probe_payload_bytes)
        mxu = run_mxu_probe(
            self.config.probe_matmul_size,
            inner_iters=self.config.probe_matmul_inner_iters,
        )
        links = None
        if self.config.probe_links_enabled:
            from k8s_watcher_tpu.probe.links import run_link_probe

            links = run_link_probe(
                self.mesh,
                rtt_factor=self.config.probe_link_rtt_factor,
                rtt_floor_ms=self.config.probe_link_rtt_floor_ms,
            )
        multislice = None
        if self.config.probe_multislice_enabled:
            from k8s_watcher_tpu.probe.multislice import run_multislice_probe

            # the hybrid mesh has its own (slices, hosts, chips) shape —
            # built from the runtime topology, not from self.mesh
            multislice = run_multislice_probe(
                n_slices=self.config.probe_multislice_slices or None,
                pair_localization=self.config.probe_multislice_pair_localization,
            )
        hbm = None
        hbm_write = None
        if self.config.probe_hbm_bytes > 0:
            from k8s_watcher_tpu.probe.hbm import run_hbm_probe, run_hbm_write_probe

            hbm = run_hbm_probe(self.config.probe_hbm_bytes)
            if self.config.probe_hbm_write_enabled:
                hbm_write = run_hbm_write_probe(self.config.probe_hbm_bytes)
        report = ProbeReport(
            environment=self.environment,
            devices=devices,
            ici=ici,
            mxu=mxu,
            hbm=hbm,
            hbm_write=hbm_write,
            links=links,
            multislice=multislice,
            host=host_identity(),
            hosts=host_identity_map(),
            rtt_warn_ms=self.config.probe_rtt_warn_ms,
            duration_ms=1e3 * (time.monotonic() - t0),
        )
        # trend folding sees the PRE-TREND health verdict: a cycle already
        # unhealthy by per-cycle checks (RTT threshold, missing devices) is
        # still judged for drift, but its readings must not shape the
        # "healthy" anchor — an agent started during congestion would
        # otherwise freeze the congested readings in as the baseline
        report.trend_alerts = self._fold_trends(
            ici, mxu, hbm, hbm_write, links, multislice, cycle_healthy=report.healthy
        )
        self.metrics.counter("probe_runs").inc()
        if ici.psum_rtt_ms >= 0:
            self.metrics.histogram("probe_psum_rtt").record(ici.psum_rtt_ms / 1e3)
        if not report.healthy:
            self.metrics.counter("probe_unhealthy").inc()
        # a COMPLETED cycle — healthy or not — proves the agent is alive;
        # /healthz goes stale when cycles stop (wedged device, hung jit).
        # Deliberately NOT stamped at cycle start or on a raised cycle: a
        # crash-looping or mid-cycle-hung probe must read as dead. The
        # steady-state threshold must therefore bound cycle_duration +
        # interval + the observer's I/O below (it runs on this thread and
        # delays the NEXT beat; scripts/probe_agent.py sizes the threshold
        # and caps the observer's k8s request timeout accordingly).
        self.heartbeat()
        self._record_cycle(report)
        observer = self.report_observer
        if observer is not None:
            try:
                observer(report)
            except Exception as exc:  # noqa: BLE001 — policy bugs must not kill probing
                logger.error("Probe report observer failed: %s", exc)
                self.metrics.counter("probe_observer_errors").inc()
        return report

    # (reading, gauge name, higher_is_better) per sub-probe — the gauges
    # make per-cycle readings scrapeable and the trend tracker turns their
    # sustained drift into alerts. Median-based readings only: the noise
    # analysis the trend factors are calibrated for assumes them. A reading
    # of None means the sub-probe errored or doesn't apply THIS cycle: its
    # gauge is cleared (a frozen last-healthy value would show dashboards a
    # healthy chip while it is dead) and no trend sample is folded.
    def _fold_trends(
        self, ici, mxu, hbm, hbm_write, links, multislice=None, *, cycle_healthy: bool = True
    ) -> list:
        # gate on the SAME ok fields ProbeReport.healthy uses — an
        # integrity-failed or non-finite probe has no 'error' string but its
        # readings describe a broken chip and must neither stay on a gauge
        # nor shape the trend anchor
        ici_ok = (
            ici is not None and ici.error is None and ici.ok
            and not ici.timing_unreliable
        )
        # timing-unreliable readings (fence noise swamped the timed op —
        # probe/timing.py) are flagged measurements, not measurements:
        # folding one into a gauge or trend window presents noise as a
        # chip reading (an 11-min soak saw a single-cycle "1.8e10 TFLOPs"
        # median from exactly this)
        mxu_ok = (
            mxu is not None and mxu.get("ok", False)
            and not mxu.get("timing_unreliable", False)
        )
        # interpreter-mode (non-TPU) bandwidth numbers are meaningless
        hbm_ok = (
            hbm is not None and hbm.get("ok", False) and not hbm.get("interpreted")
            and not hbm.get("bandwidth_unreliable", False)
        )
        hbm_w_ok = (
            hbm_write is not None and hbm_write.get("ok", False)
            and not hbm_write.get("interpreted")
            and not hbm_write.get("bandwidth_unreliable", False)
        )
        # links: an errored walk withdraws the gauges, but a walk that FOUND
        # suspects is a valid reading — probe_link_suspects > 0 is exactly
        # what operators scrape for, so links.ok is deliberately not gated
        # on. Gate on n_observed, not n_links: a process can observe (and
        # suspect) links it doesn't own — its inter-host edges record on
        # the lower-indexed peer, leaving n_links == 0 on valid walks
        links_ok = links is not None and links.error is None and links.n_observed > 0
        # multislice DCN readings: like links, a walk that FOUND suspects is
        # a valid reading; an errored or unreliable-timing one is not. The
        # pair median trends the typical inter-slice route; dcn_overhead_ms
        # is the aggregated DCN cost a fabric event inflates first.
        ms_ok = multislice is not None and multislice.error is None and not multislice.timing_unreliable
        pair_valid = [p["rtt_ms"] for p in multislice.pair_rtts if p["rtt_ms"] >= 0] if ms_ok else []
        pair_median = float(np.median(pair_valid)) if pair_valid else None
        # On a SINGLE-device mesh the psum "RTT" and all-reduce "bandwidth"
        # measure host dispatch latency (over a dev tunnel: network
        # jitter), not any interconnect — there is no fabric to trend, and
        # folding them raised 4-9x false rise-alerts in an 11-min
        # real-chip soak (artifacts/probe_soak_real_tpu.json history)
        # while MXU/HBM stayed inside a 0.6% band. The gauges still
        # publish; only the trend fold is gated on a real multi-chip mesh.
        ici_fabric = ici_ok and ici.n_devices > 1
        # (name, value, higher_is_better, trend_eligible): value None
        # clears the gauge; trend_eligible=False publishes the gauge but
        # never folds a trend sample
        readings = [
            ("psum_rtt_median_ms", ici.psum_rtt_median_ms if ici_ok else None, False, ici_fabric),
            ("allreduce_bus_gbps_median", ici.bandwidth_gbps_median if ici_ok else None, True, ici_fabric),
            ("mxu_tflops_median", mxu.get("tflops_median", 0.0) if mxu_ok else None, True, True),
            ("hbm_read_gbps", hbm.get("read_gbps", 0.0) if hbm_ok else None, True, True),
            ("hbm_write_gbps", hbm_write.get("write_gbps", 0.0) if hbm_w_ok else None, True, True),
            ("link_median_rtt_ms", links.median_rtt_ms if links_ok else None, False, True),
            ("dcn_pair_median_rtt_ms", pair_median, False, True),
            ("dcn_overhead_ms", multislice.dcn_overhead_ms if ms_ok and multislice.n_slices > 1 else None, False, True),
        ]
        if links_ok:
            self.metrics.gauge("probe_link_suspects").set(len(links.suspect_links))
        elif links is not None:
            self.metrics.gauge("probe_link_suspects").clear()
        alerts = []
        for name, value, higher_is_better, trend_eligible in readings:
            gauge = self.metrics.gauge(f"probe_{name}")
            if value is not None and value > 0:
                gauge.set(value)
            else:
                gauge.clear()
                continue
            if not trend_eligible:
                continue
            if self.trend is not None:
                alert = self.trend.observe(
                    name, value,
                    higher_is_better=higher_is_better,
                    contribute_baseline=cycle_healthy,
                )
                if alert is not None:
                    logger.warning(
                        "Probe trend alert: %s %s to %.4g (baseline %.4g, ratio %.2f)",
                        alert.metric, alert.direction, alert.recent, alert.baseline, alert.ratio,
                    )
                    alerts.append(alert)
        if alerts:
            self.metrics.counter("probe_trend_alerts").inc(len(alerts))
        return alerts

    def _report(self, report: ProbeReport) -> None:
        # Process 0 reports for the slice; every OTHER process stays quiet
        # unless its own view is unhealthy. Local liveness only runs on a
        # host's own addressable chips (probe/device.py), so a dead chip on
        # host k is only ever observed by process k — gating all reporting
        # on process 0 would detect that fault and then drop it.
        if jax.process_index() == 0 or not report.healthy:
            self.sink(Notification(report.to_payload(), time.monotonic(), kind="probe"))

    def _record_cycle(self, report: ProbeReport) -> None:
        """Fold one completed cycle into the flight-recorder ring."""
        import datetime

        entry = {
            "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(timespec="seconds"),
            "healthy": report.healthy,
            "duration_ms": round(report.duration_ms, 1),
            # None means "probe did not run" — a near-zero reading from a
            # severely degraded chip must stay 0.0, not collapse to null
            "psum_rtt_ms": round(report.ici.psum_rtt_median_ms, 4) if report.ici else None,
            "mxu_tflops": round(report.mxu.get("tflops_median", 0.0), 2)
            if report.mxu else None,
            "hbm_read_gbps": round(report.hbm.get("read_gbps", 0.0), 1)
            if report.hbm else None,
            "hbm_write_gbps": round(report.hbm_write.get("write_gbps", 0.0), 1)
            if report.hbm_write else None,
            "link_suspects": len(report.links.suspect_links) if report.links else None,
            "dcn_suspect_slices": list(report.multislice.dcn_suspect_slices)
            if report.multislice else None,
            "trend_alerts": [
                {"metric": a.metric, "direction": a.direction, "ratio": round(a.ratio, 2)}
                for a in (report.trend_alerts or [])
            ],
        }
        with self._cycles_lock:
            self._cycles.append(entry)

    def recent_cycles(self, n: int = 20) -> list:
        """Last-``n`` cycle summaries, newest first (/debug/probes)."""
        with self._cycles_lock:
            entries = list(self._cycles)
        return entries[::-1][: max(0, n)]

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._report(self.run_once())
            except Exception as exc:
                logger.error("Probe iteration failed: %s", exc)
                self.metrics.counter("probe_errors").inc()
            if self._stop.wait(self.config.probe_interval_seconds):
                return

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, name="tpu-probe-agent", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
