"""Cross-cycle trend detection for probe readings.

A single probe cycle can only resolve degradation beyond its noise band
(ARCHITECTURE.md "minimum detectable degradation": ~15-35% on tunneled
links, ~2-10% locally). Slow decay — a chip throttling a few percent more
each hour — hides inside that band forever if each cycle is judged alone.

``TrendTracker`` learns a per-metric healthy **anchor** (the median of the
first ``window`` readings after startup, frozen once learned) and compares
the median of the last ``recent`` cycles against it. The anchor is frozen
deliberately: a *rolling* baseline decays along with the readings, so any
drift slower than the alert factor per window would never alert — the
exact slow-decay case this module exists for. Against a frozen anchor,
decay of any rate eventually crosses the factor and keeps alerting until
the part is fixed or drained.

Judging a recent-median vs a many-sample anchor means a single noisy cycle
can neither raise an alert nor poison the baseline — the same robustness
reasoning as the probes' own median-over-min discipline. (That guarantee
needs ``recent >= 3``: the median of 2 samples is their mean, which one
spike drags halfway. The default is 3.)

State is in-process: a restart re-learns its anchor within ``window``
cycles. That is deliberate and it is also the re-baselining story — after
an intentional operating-point change (downclocking, firmware update) or
a hardware swap (pod rescheduled onto a different chip), restart the agent
and the new normal becomes the anchor. Persisting anchors across restarts
would flag a replacement chip against its predecessor's characteristics.
"""

from __future__ import annotations

import collections
import dataclasses
import statistics
import threading
from typing import Any, Deque, Dict, List, Optional


@dataclasses.dataclass
class TrendAlert:
    metric: str
    baseline: float  # the frozen (or still-forming) healthy anchor
    recent: float  # median of the last ``recent`` cycles
    ratio: float  # recent / baseline
    direction: str  # "drop" (throughput fell) | "rise" (latency grew)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class TrendTracker:
    """Per-metric frozen-anchor drift detection.

    ``observe(name, value, higher_is_better)`` folds one cycle's reading
    and returns a ``TrendAlert`` when the recent median has drifted beyond
    the factor for that direction:

    - throughput metrics (``higher_is_better=True``, e.g. TFLOP/s, GB/s):
      alert when ``recent < drop_factor * anchor``;
    - latency metrics (``higher_is_better=False``, e.g. psum RTT): alert
      when ``recent > rise_factor * anchor``.

    No verdict until ``min_history`` total samples exist; until ``window``
    samples exist the anchor is the median of everything before the recent
    cycles (still forming), after which it freezes. A degraded part keeps
    alerting every cycle until fixed, drained, or the agent is restarted
    (restart = re-baseline, see module docstring). Thread-safe: the agent
    loop and any debug endpoint may race.
    """

    def __init__(
        self,
        *,
        window: int = 16,
        recent: int = 3,
        drop_factor: float = 0.75,
        rise_factor: float = 2.5,
        min_history: int = 6,
    ):
        if recent < 1 or window <= recent:
            raise ValueError("need window > recent >= 1")
        if min_history < recent + 1:
            raise ValueError("min_history must exceed recent (the anchor needs samples)")
        if min_history > window:
            raise ValueError(
                "min_history must be <= window: the anchor freezes at window "
                "samples, so a larger min_history would disable detection forever"
            )
        if not 0.0 < drop_factor < 1.0:
            raise ValueError("drop_factor must be in (0, 1): >= 1 alerts on every healthy cycle")
        if rise_factor <= 1.0:
            raise ValueError("rise_factor must be > 1: <= 1 alerts on every healthy cycle")
        self.window = window
        self.recent = recent
        self.drop_factor = drop_factor
        self.rise_factor = rise_factor
        self.min_history = min_history
        self._lock = threading.Lock()
        # forming[name] accumulates the first ``window`` readings; once full
        # its median freezes into anchor[name] and only ``recent`` readings
        # are retained per metric — O(window) memory regardless of uptime
        self._forming: Dict[str, List[float]] = {}
        self._anchor: Dict[str, float] = {}
        self._recent: Dict[str, Deque[float]] = {}
        # per-metric flags mirroring the recent deque: did that observe
        # contribute to the forming buffer? The interim anchor must exclude
        # exactly the trailing forming entries still inside the recent
        # window, and with non-contributing cycles interleaved that count
        # is NOT always recent-1
        self._recent_contributed: Dict[str, Deque[bool]] = {}

    def observe(
        self,
        name: str,
        value: float,
        *,
        higher_is_better: bool,
        contribute_baseline: bool = True,
    ) -> Optional[TrendAlert]:
        """Fold one reading; ``contribute_baseline=False`` judges the
        reading against the anchor but keeps it out of the forming buffer —
        for cycles the caller already knows are unhealthy by per-cycle
        checks (RTT threshold breach, missing devices), whose readings must
        not freeze into the "healthy" anchor."""
        if value is None or value <= 0:
            return None  # errored/absent readings carry no trend signal
        value = float(value)
        with self._lock:
            recent = self._recent.setdefault(name, collections.deque(maxlen=self.recent))
            recent.append(value)
            anchor = self._anchor.get(name)
            forming = None
            contributed = None
            if anchor is None:
                # contributed mirrors the recent deque while forming only;
                # once the anchor freezes nothing reads it again
                contributed = self._recent_contributed.setdefault(
                    name, collections.deque(maxlen=self.recent)
                )
                contributed.append(False)  # flipped below if this sample forms
                # the current sample is judged BEFORE it may enter the
                # forming buffer (see below)
                forming = self._forming.setdefault(name, [])
                if len(forming) + 1 < self.min_history:
                    if contribute_baseline:
                        forming.append(value)
                        contributed[-1] = True
                    return None
                # judge against the forming samples NOT still inside the
                # recent window (the overlap is however many of the last
                # ``recent`` observes contributed — with non-contributing
                # cycles interleaved it is less than recent-1). All-overlap
                # (reachable right at min_history == recent+1) degrades to
                # judging recent against itself: ratio ~1, no alert — the
                # correct bootstrap behavior.
                overlap = sum(1 for c in contributed if c)
                baseline_samples = forming[: len(forming) - overlap] or forming
                anchor = statistics.median(baseline_samples)
            recent_samples = list(recent)

            alert = None
            if anchor > 0:
                recent_median = statistics.median(recent_samples)
                ratio = recent_median / anchor
                if higher_is_better and ratio < self.drop_factor:
                    alert = TrendAlert(name, anchor, recent_median, ratio, "drop")
                elif not higher_is_better and ratio > self.rise_factor:
                    alert = TrendAlert(name, anchor, recent_median, ratio, "rise")

            if forming is not None and alert is None and contribute_baseline:
                # only non-alerting samples from healthy cycles may shape
                # the anchor: degradation that starts mid-forming must not
                # freeze into the baseline (it would silence alerts that
                # were already firing and judge all future decay against a
                # poisoned anchor). If degradation persists, the anchor
                # simply never freezes and every cycle keeps alerting
                # against the early-healthy baseline.
                forming.append(value)
                contributed[-1] = True
                if len(forming) >= self.window:
                    self._anchor[name] = statistics.median(forming)
                    del self._forming[name]
                    del self._recent_contributed[name]
        return alert

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Current anchors + recent windows (debug endpoints)."""
        with self._lock:
            return {
                name: {
                    "anchor": self._anchor.get(name),
                    "forming_samples": len(self._forming.get(name, ())),
                    "recent": list(series),
                }
                for name, series in self._recent.items()
            }
