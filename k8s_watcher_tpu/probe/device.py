"""Chip enumeration (north star: the probe runs ``jax.devices()`` and
reports chip status; BASELINE.json configs[2]).

Each visible device is reported with identity, host locality, and — where
the runtime exposes it — HBM usage. A per-device trivial computation
isolates chips that enumerate but cannot execute (a failure mode a bare
``jax.devices()`` call would miss).
"""

from __future__ import annotations

import logging
import os
import socket
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)


def host_identity() -> Dict[str, Any]:
    """This process's host identity — the join key that turns a suspect
    chip (``device.process_index``) into a drainable k8s node.

    ``NODE_NAME`` comes from the downward API (deploy/probe-daemonset.yaml);
    ``TPU_WORKER_ID``/``TPU_WORKER_HOSTNAMES`` are injected by GKE on TPU
    slice pods."""
    out: Dict[str, Any] = {
        "hostname": socket.gethostname(),
        "process_index": jax.process_index(),
    }
    for env in ("NODE_NAME", "TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES"):
        value = os.environ.get(env)
        if value:
            out[env.lower()] = value
    return out


# gathered once per process lifetime: identities (hostname, NODE_NAME) are
# stable, and the gather is a cross-process collective worth not repeating
# every probe cycle. Single-process identities are NOT cached (tests and
# sidecars may change env between agents).
_IDENTITY_MAP_CACHE: Optional[Dict[str, Dict[str, Any]]] = None
_IDENTITY_WIRE_BYTES = 512


def _encode_identity_wire(identity: Dict[str, Any]) -> bytes:
    """JSON-encode an identity to at most ``_IDENTITY_WIRE_BYTES - 1`` bytes
    of ALWAYS-decodable utf-8 — a blind byte slice could cut a multibyte
    sequence (or a ``\\uXXXX`` escape, which is why ``ensure_ascii=False``:
    the encoded length must equal the real byte cost) and make every peer's
    decode fail, losing the node join exactly in the oversize case."""
    import json

    def clip(s: str, max_bytes: int) -> str:
        return s.encode("utf-8")[:max_bytes].decode("utf-8", errors="ignore")

    raw = json.dumps(identity, ensure_ascii=False).encode("utf-8")
    if len(raw) < _IDENTITY_WIRE_BYTES:
        return raw
    logger.warning(
        "Host identity JSON (%d bytes) exceeds the %d-byte wire buffer; "
        "gathering a minimal identity instead", len(raw), _IDENTITY_WIRE_BYTES
    )
    minimal: Dict[str, Any] = {
        "hostname": clip(str(identity.get("hostname", "")), 180),
        "process_index": identity["process_index"],
    }
    if "node_name" in identity:
        minimal["node_name"] = clip(str(identity["node_name"]), 180)
    raw = json.dumps(minimal, ensure_ascii=False).encode("utf-8")
    if len(raw) < _IDENTITY_WIRE_BYTES:
        return raw
    # pathological values (every char escaping to multiple bytes): the
    # index alone still names WHICH process the operator must inspect
    return json.dumps({"process_index": identity["process_index"]}).encode("utf-8")


def host_identity_map() -> Dict[str, Dict[str, Any]]:
    """``str(process_index) -> host_identity()`` for EVERY process.

    Suspect chips found by the link probe live on remote processes, but
    process 0 does the reporting (probe/agent.py `_report`) — without this
    map a report saying "device.process_index == 2 is suspect" names no
    drainable node. Multi-controller mode gathers each process's identity
    (fixed-size utf-8 buffers over one allgather) exactly once."""
    global _IDENTITY_MAP_CACHE
    if jax.process_count() == 1:
        mine = host_identity()
        return {str(mine["process_index"]): mine}
    if _IDENTITY_MAP_CACHE is not None:
        return _IDENTITY_MAP_CACHE

    import json

    import numpy as np
    from jax.experimental import multihost_utils

    # wire identity excludes TPU_WORKER_HOSTNAMES: it is identical on every
    # worker and grows with slice size — on 16+ worker slices it would
    # overflow the fixed wire buffer and corrupt the JSON mid-string,
    # killing the node_name join exactly on the large slices it targets
    mine = {k: v for k, v in host_identity().items() if k != "tpu_worker_hostnames"}
    raw = _encode_identity_wire(mine)
    buf = np.zeros(_IDENTITY_WIRE_BYTES, dtype=np.uint8)
    buf[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    out: Dict[str, Dict[str, Any]] = {}
    for idx in range(gathered.shape[0]):
        row = bytes(gathered[idx]).rstrip(b"\x00")
        try:
            out[str(idx)] = json.loads(row.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            # a peer sent garbage: keep the index mapped so the operator
            # still sees WHICH process is unidentifiable
            out[str(idx)] = {"process_index": idx, "error": "identity decode failed"}
    _IDENTITY_MAP_CACHE = out
    return out


def _device_entry(device: jax.Device) -> Dict[str, Any]:
    entry: Dict[str, Any] = {
        "id": device.id,
        "platform": device.platform,
        "device_kind": device.device_kind,
        "process_index": device.process_index,
    }
    coords = getattr(device, "coords", None)
    if coords is not None:
        entry["coords"] = list(coords)
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats:
        entry["memory"] = {
            "bytes_in_use": stats.get("bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
        }
    return entry


def _device_alive(device: jax.Device) -> bool:
    """Run a one-element computation pinned to ``device``."""
    try:
        x = jax.device_put(jnp.float32(2.0), device)
        return float(jax.block_until_ready(x * x)) == 4.0
    except Exception as exc:
        logger.error("Device %s failed liveness computation: %s", device, exc)
        return False


def enumerate_devices(
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    expected_per_host: int = 0,
    check_liveness: bool = True,
    expected_platform: Optional[str] = None,
) -> Dict[str, Any]:
    """Inventory of visible chips + liveness verdicts.

    ``expected_per_host > 0`` (from ``tpu.probe.expected_chips_per_host``)
    flags hosts that enumerate fewer chips than the slice shape demands.
    ``expected_platform`` (e.g. ``"tpu"``) flags devices on the wrong
    backend — a probe that silently measures CPU "health" on a host with no
    TPUs must not report the slice healthy.
    """
    devices = list(devices if devices is not None else jax.devices())
    entries: List[Dict[str, Any]] = []
    healthy = 0
    process_index = jax.process_index()
    for device in devices:
        entry = _device_entry(device)
        if check_liveness and device.process_index == process_index:
            # only local devices are addressable; each host vouches for its
            # own chips (remote chips stay alive=None — their host's probe
            # covers them, and the collective probes cover the links)
            entry["alive"] = _device_alive(device)
        else:
            entry["alive"] = None
        if entry["alive"] is not False:
            healthy += 1
        entries.append(entry)

    local = [d for d in devices if d.process_index == process_index]
    result: Dict[str, Any] = {
        "process_index": process_index,
        "process_count": jax.process_count(),
        "visible_devices": len(devices),
        "local_devices": len(local),
        "healthy_devices": healthy,
        "devices": entries,
    }
    if expected_per_host > 0:
        result["expected_local_devices"] = expected_per_host
        result["missing_local_devices"] = max(0, expected_per_host - len(local))
    if expected_platform:
        mismatched = sum(1 for d in devices if d.platform != expected_platform)
        result["expected_platform"] = expected_platform
        result["platform_mismatch"] = mismatched
        if mismatched:
            logger.warning(
                "%d/%d devices are not %s (found: %s)",
                mismatched, len(devices), expected_platform,
                sorted({d.platform for d in devices}),
            )
    return result
