"""Chip enumeration (north star: the probe runs ``jax.devices()`` and
reports chip status; BASELINE.json configs[2]).

Each visible device is reported with identity, host locality, and — where
the runtime exposes it — HBM usage. A per-device trivial computation
isolates chips that enumerate but cannot execute (a failure mode a bare
``jax.devices()`` call would miss).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)


def _device_entry(device: jax.Device) -> Dict[str, Any]:
    entry: Dict[str, Any] = {
        "id": device.id,
        "platform": device.platform,
        "device_kind": device.device_kind,
        "process_index": device.process_index,
    }
    coords = getattr(device, "coords", None)
    if coords is not None:
        entry["coords"] = list(coords)
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats:
        entry["memory"] = {
            "bytes_in_use": stats.get("bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
        }
    return entry


def _device_alive(device: jax.Device) -> bool:
    """Run a one-element computation pinned to ``device``."""
    try:
        x = jax.device_put(jnp.float32(2.0), device)
        return float(jax.block_until_ready(x * x)) == 4.0
    except Exception as exc:
        logger.error("Device %s failed liveness computation: %s", device, exc)
        return False


def enumerate_devices(
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    expected_per_host: int = 0,
    check_liveness: bool = True,
    expected_platform: Optional[str] = None,
) -> Dict[str, Any]:
    """Inventory of visible chips + liveness verdicts.

    ``expected_per_host > 0`` (from ``tpu.probe.expected_chips_per_host``)
    flags hosts that enumerate fewer chips than the slice shape demands.
    ``expected_platform`` (e.g. ``"tpu"``) flags devices on the wrong
    backend — a probe that silently measures CPU "health" on a host with no
    TPUs must not report the slice healthy.
    """
    devices = list(devices if devices is not None else jax.devices())
    entries: List[Dict[str, Any]] = []
    healthy = 0
    process_index = jax.process_index()
    for device in devices:
        entry = _device_entry(device)
        if check_liveness and device.process_index == process_index:
            # only local devices are addressable; each host vouches for its
            # own chips (remote chips stay alive=None — their host's probe
            # covers them, and the collective probes cover the links)
            entry["alive"] = _device_alive(device)
        else:
            entry["alive"] = None
        if entry["alive"] is not False:
            healthy += 1
        entries.append(entry)

    local = [d for d in devices if d.process_index == process_index]
    result: Dict[str, Any] = {
        "process_index": process_index,
        "process_count": jax.process_count(),
        "visible_devices": len(devices),
        "local_devices": len(local),
        "healthy_devices": healthy,
        "devices": entries,
    }
    if expected_per_host > 0:
        result["expected_local_devices"] = expected_per_host
        result["missing_local_devices"] = max(0, expected_per_host - len(local))
    if expected_platform:
        mismatched = sum(1 for d in devices if d.platform != expected_platform)
        result["expected_platform"] = expected_platform
        result["platform_mismatch"] = mismatched
        if mismatched:
            logger.warning(
                "%d/%d devices are not %s (found: %s)",
                mismatched, len(devices), expected_platform,
                sorted({d.platform for d in devices}),
            )
    return result
