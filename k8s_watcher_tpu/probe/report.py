"""Probe report schema — the payload the probe plane sends through the
notifier (north star: "reports chip/link status through clusterapi")."""

from __future__ import annotations

import dataclasses
from datetime import datetime, timezone
from typing import Any, Dict, List, Optional

from k8s_watcher_tpu.probe.ici import IciProbeResult


@dataclasses.dataclass
class ProbeReport:
    environment: str
    devices: Dict[str, Any]
    ici: Optional[IciProbeResult] = None
    mxu: Optional[Dict[str, Any]] = None
    hbm: Optional[Dict[str, Any]] = None
    hbm_write: Optional[Dict[str, Any]] = None  # write-bw + block integrity
    links: Optional[Any] = None  # probe.links.LinkProbeResult
    multislice: Optional[Any] = None  # probe.multislice.MultiSliceProbeResult
    # sustained cross-cycle drift alerts (probe.trend.TrendAlert list):
    # every individual cycle may have passed its own checks, but a slide
    # beyond the trend factors is an actionable degradation signal
    trend_alerts: List[Any] = dataclasses.field(default_factory=list)
    # reporting process's host identity (probe/device.py:host_identity)
    host: Optional[Dict[str, Any]] = None
    # str(process_index) -> identity for EVERY slice process
    # (probe/device.py:host_identity_map) — the join that turns a suspect
    # chip's process_index into a drainable k8s node even when the suspect
    # lives on a remote host and process 0 is the one reporting
    hosts: Optional[Dict[str, Any]] = None
    rtt_warn_ms: float = 50.0
    duration_ms: float = 0.0

    @property
    def healthy(self) -> bool:
        if self.devices.get("platform_mismatch", 0) > 0:
            return False  # measuring the wrong hardware is never "healthy"
        if self.devices.get("missing_local_devices", 0) > 0:
            return False
        if self.devices.get("healthy_devices", 0) < self.devices.get("visible_devices", 0):
            return False
        if self.ici is not None and not self.ici.ok:
            return False
        if self.ici is not None and self.ici.psum_rtt_ms > self.rtt_warn_ms:
            return False
        if self.mxu is not None and not self.mxu.get("ok", False):
            return False
        if self.hbm is not None and not self.hbm.get("ok", False):
            return False
        if self.hbm_write is not None and not self.hbm_write.get("ok", False):
            return False
        if self.links is not None and not self.links.ok:
            return False
        if self.multislice is not None and not self.multislice.ok:
            return False
        if self.trend_alerts:
            return False
        return True

    def to_payload(self) -> Dict[str, Any]:
        """Notification payload (event_type TPU_PROBE, like pod payloads
        carry ADDED/MODIFIED/DELETED)."""
        return {
            "event_type": "TPU_PROBE",
            "environment": self.environment,
            "healthy": self.healthy,
            "devices": self.devices,
            "ici": self.ici.to_dict() if self.ici else None,
            "mxu": self.mxu,
            "hbm": self.hbm,
            "hbm_write": self.hbm_write,
            "links": self.links.to_dict() if self.links is not None else None,
            "multislice": self.multislice.to_dict() if self.multislice is not None else None,
            "trend_alerts": [a.to_dict() for a in self.trend_alerts],
            "host": self.host,
            "hosts": self.hosts,
            "duration_ms": self.duration_ms,
            "event_timestamp": datetime.now(timezone.utc).isoformat(),
        }
