"""HBM bandwidth probe — a Pallas streaming kernel.

Degraded HBM is a real TPU failure mode that the psum (ICI) and matmul (MXU)
probes can miss: a chip can compute and communicate correctly while its
memory system runs far below spec. This probe streams a large HBM-resident
buffer through VMEM and reports achieved read bandwidth.

Kernel design (see the Pallas TPU guide): a 1-D grid over row-blocks of a
``(rows, LANES*4)`` float32 buffer. The ``BlockSpec`` pipeline automatically
double-buffers the HBM→VMEM DMAs while the VPU reduces each block, so the
measurement is DMA-bound — exactly what we want to measure. Each grid step
accumulates a partial sum into a (1, 1) SMEM-style output (init on step 0),
which both defeats dead-code elimination and doubles as a data-integrity
check (the buffer is all-ones, so the sum must equal the element count).

On non-TPU backends the kernel runs in interpreter mode: numbers are
meaningless there, but the code path stays testable on the CPU mesh.
"""

from __future__ import annotations

import functools
import logging
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

logger = logging.getLogger(__name__)

LANES = 128
BLOCK_ROWS = 1024  # 1024 x 512 f32 = 2 MiB per block: large enough to be
WIDTH = 4 * LANES  # DMA-bound, small enough to double-buffer in ~16MB VMEM


def _reduce_kernel(in_ref, out_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[0, 0] = 0.0

    out_ref[0, 0] += jnp.sum(in_ref[:])


@functools.lru_cache(maxsize=8)
def make_hbm_read_probe(total_bytes: int, *, interpret: bool = False):
    """Jitted fn streaming ~``total_bytes`` of f32 through VMEM; returns the
    scalar sum. Also returns the actual byte count used (rounded to blocks).

    Cached: jax's compilation cache is keyed on function identity, so a fresh
    closure per probe cycle would force a full Pallas+XLA recompile every
    ``probe_interval_seconds`` — the lru_cache keeps one jitted program per
    (size, interpret) combination alive for the process lifetime.
    """
    bytes_per_block = BLOCK_ROWS * WIDTH * 4
    num_blocks = max(1, total_bytes // bytes_per_block)
    rows = num_blocks * BLOCK_ROWS

    def probe(x: jax.Array) -> jax.Array:
        return pl.pallas_call(
            _reduce_kernel,
            grid=(num_blocks,),
            in_specs=[pl.BlockSpec((BLOCK_ROWS, WIDTH), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32),
            interpret=interpret,
        )(x)

    return jax.jit(probe), rows, num_blocks * bytes_per_block


def run_hbm_probe(
    total_bytes: int = 256 * 1024 * 1024,
    *,
    iters: int = 3,
    device: Optional[jax.Device] = None,
) -> Dict[str, Any]:
    """Measure achieved HBM read bandwidth on one device."""
    try:
        device = device or jax.devices()[0]
        interpret = device.platform != "tpu"
        if interpret:
            # interpreter mode is orders of magnitude slower: shrink the
            # buffer so CPU tests stay fast; bandwidth number is meaningless
            total_bytes = min(total_bytes, BLOCK_ROWS * WIDTH * 4 * 2)

        probe, rows, actual_bytes = make_hbm_read_probe(total_bytes, interpret=interpret)
        x = jax.device_put(jnp.ones((rows, WIDTH), dtype=jnp.float32), device)

        t0 = time.perf_counter()
        out = jax.block_until_ready(probe(x))  # warmup = compile
        compile_ms = 1e3 * (time.perf_counter() - t0)

        expected = float(rows * WIDTH)
        integrity_ok = abs(float(out[0, 0]) - expected) <= 1e-6 * expected

        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(probe(x))
            times.append(time.perf_counter() - t0)
        best = min(times)

        return {
            "ok": integrity_ok,
            "integrity_ok": integrity_ok,
            "bytes": actual_bytes,
            "time_ms": 1e3 * best,
            "read_gbps": actual_bytes / best / 1e9,
            "compile_ms": compile_ms,
            "interpreted": interpret,
            "device_id": device.id,
        }
    except Exception as exc:
        logger.error("HBM probe failed: %s", exc)
        return {"ok": False, "error": str(exc)}
