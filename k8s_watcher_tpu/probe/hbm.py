"""HBM bandwidth + integrity probes — Pallas streaming kernels.

Degraded HBM is a real TPU failure mode that the psum (ICI) and matmul (MXU)
probes can miss: a chip can compute and communicate correctly while its
memory system runs far below spec. Two probes:

- **read sweep** (`run_hbm_probe`): streams a large HBM-resident buffer
  through VMEM, accumulating a vector checksum. Reports achieved read
  bandwidth + a sum integrity check.
- **write + integrity** (`run_hbm_write_probe`): streams a block-indexed
  pattern VMEM→HBM (write bandwidth), then reads every block back and
  compares per-block checksums — a mismatch localizes the bad block's HBM
  address range (stuck/flipped cells, mis-addressed DMAs), which the
  uniform all-ones read sweep cannot see (it is invariant under block
  aliasing).

Kernel design (see the Pallas TPU guide): a grid over row-blocks of a
``(rows, WIDTH)`` float32 buffer; the ``BlockSpec`` pipeline double-buffers
the HBM↔VMEM DMAs. Reductions accumulate a (1, WIDTH) VECTOR partial in
VMEM — a cross-step SMEM scalar accumulator was observed to serialize the
DMA pipeline ~100x below spec. Per-block checksums land in one resident
(1, num_blocks) SMEM row (Mosaic: scalars must live in SMEM, and a (1, 1)
block per step would violate the block-divisibility rule).

Measurement design: remote/tunneled platforms (axon) make per-execution
wall timing useless — ``block_until_ready`` can return early, every host
readback fence costs tens of ms with high variance, and device-side
profiler traces are unavailable. So each timed measurement runs ``repeats``
full passes over the buffer inside ONE kernel execution (a ``(repeats,
num_blocks)`` grid), is fenced once by a host scalar readback, and the
median fence cost is subtracted. Degradation detection needs order-of-
magnitude accuracy, which survives the residual noise; on local TPU
deployments the same path is simply accurate. The write kernel takes a
seed parameter solely so XLA cannot constant-fold a parameterless program
into a compile-time literal (observed: "writes" reporting multiple TB/s).

On non-TPU backends the kernels run in interpreter mode: numbers are
meaningless there, but the code paths stay testable on the CPU mesh.
"""

from __future__ import annotations

import functools
import logging
import statistics
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from k8s_watcher_tpu.probe.timing import fence_baseline_ms as _fence_baseline_ms
from k8s_watcher_tpu.probe.timing import fetch_scalar as _fetch_scalar

logger = logging.getLogger(__name__)

LANES = 128
BLOCK_ROWS = 1024  # 1024 x 512 f32 = 2 MiB per block: large enough to be
WIDTH = 4 * LANES  # DMA-bound, small enough to double-buffer in ~16MB VMEM
BYTES_PER_BLOCK = BLOCK_ROWS * WIDTH * 4

# The write path peaks at a SMALLER block than the read path: a v5e sweep
# (ARCHITECTURE.md) measured 512 KiB write blocks ~14% faster than the
# 2 MiB read-optimal shape (760 vs 664 GB/s median) — write DMAs pipeline
# better with more, smaller in-flight transfers, while reads prefer the
# larger block. Each probe uses its own shape.
WRITE_BLOCK_ROWS = 512
WRITE_WIDTH = 2 * LANES
WRITE_BYTES_PER_BLOCK = WRITE_BLOCK_ROWS * WRITE_WIDTH * 4


def _reduce_kernel(in_ref, out_ref):
    r, i = pl.program_id(0), pl.program_id(1)

    @pl.when((r == 0) & (i == 0))
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jnp.sum(in_ref[:], axis=0, keepdims=True)


@functools.lru_cache(maxsize=8)
def make_hbm_read_probe(total_bytes: int, *, repeats: int = 1, interpret: bool = False):
    """Jitted fn streaming ``repeats`` full passes of ~``total_bytes`` of f32
    through VMEM in one execution; returns the (1, WIDTH) checksum vector.
    Cached: a fresh closure per probe cycle would force a full Pallas+XLA
    recompile every ``probe_interval_seconds``.
    """
    num_blocks = max(1, total_bytes // BYTES_PER_BLOCK)
    rows = num_blocks * BLOCK_ROWS

    def probe(x: jax.Array) -> jax.Array:
        return pl.pallas_call(
            _reduce_kernel,
            grid=(repeats, num_blocks),
            in_specs=[pl.BlockSpec((BLOCK_ROWS, WIDTH), lambda r, i: (i, 0))],
            out_specs=pl.BlockSpec((1, WIDTH), lambda r, i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((1, WIDTH), jnp.float32),
            interpret=interpret,
        )(x)

    return jax.jit(probe), rows, num_blocks * BYTES_PER_BLOCK


def _fill_kernel(seed_ref, out_ref):
    # block i is stamped with the value i+1+seed: position-DEPENDENT (a DMA
    # landing in the wrong address range changes some block's checksum) and
    # parameter-dependent (a seedless kernel is a parameterless XLA program
    # that gets constant-folded at compile time — the "write" then takes 0s)
    i = pl.program_id(1)
    value = (i + 1).astype(jnp.float32) + seed_ref[0, 0]
    out_ref[:] = jnp.full(out_ref.shape, 1.0, jnp.float32) * value


def _blocksum_kernel(in_ref, out_ref):
    # one resident (1, num_blocks) SMEM row; step i fills its own slot
    out_ref[0, pl.program_id(0)] = jnp.sum(in_ref[:])


@functools.lru_cache(maxsize=8)
def make_hbm_write_probe(total_bytes: int, *, repeats: int = 1, interpret: bool = False):
    """(write_fn, blocksums_fn, rows, actual_bytes).

    ``write_fn(seed)`` streams the block-indexed pattern VMEM→HBM,
    ``repeats`` full passes in one execution; ``blocksums_fn(x)`` reads the
    buffer back and returns per-block checksums so a mismatch localizes the
    bad block's HBM address range.
    """
    num_blocks = max(1, total_bytes // WRITE_BYTES_PER_BLOCK)
    rows = num_blocks * WRITE_BLOCK_ROWS

    def write(seed: jax.Array) -> jax.Array:
        return pl.pallas_call(
            _fill_kernel,
            grid=(repeats, num_blocks),
            in_specs=[pl.BlockSpec((1, 1), lambda r, i: (0, 0), memory_space=pltpu.SMEM)],
            out_specs=pl.BlockSpec((WRITE_BLOCK_ROWS, WRITE_WIDTH), lambda r, i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((rows, WRITE_WIDTH), jnp.float32),
            interpret=interpret,
        )(seed)

    def blocksums(x: jax.Array) -> jax.Array:
        return pl.pallas_call(
            _blocksum_kernel,
            grid=(num_blocks,),
            in_specs=[pl.BlockSpec((WRITE_BLOCK_ROWS, WRITE_WIDTH), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((1, num_blocks), lambda i: (0, 0), memory_space=pltpu.SMEM),
            out_shape=jax.ShapeDtypeStruct((1, num_blocks), jnp.float32),
            interpret=interpret,
        )(x)

    return jax.jit(write), jax.jit(blocksums), rows, num_blocks * WRITE_BYTES_PER_BLOCK


def _pick_repeats(actual_bytes: int, target_traffic: int = 32 << 30) -> int:
    """Enough passes that device time dominates fence noise (~32 GiB of
    traffic ≈ 40 ms at spec bandwidth, seconds on a badly degraded part —
    both resolvable against a fence that costs ~70 ms ± tens of ms)."""
    return max(1, min(256, target_traffic // max(actual_bytes, 1)))


def _timed_pass_ms(run_fenced, iters: int, baseline_ms: float, repeats: int,
                   budget_ms: float = 10_000.0):
    """(per_pass_ms, per_pass_min_ms, unreliable): median-of-iters (and the
    min, for best-case visibility) minus the fence baseline.

    The median is the headline statistic — min-of-iters with a median-fence
    subtraction over-subtracts the luckiest sample and reads above physical
    peak on noisy links. When the measurement is swamped by fence noise
    (device share under a quarter of the baseline), the bandwidth number is
    flagged unreliable — integrity results are unaffected. On a badly
    degraded part each execution can take seconds, so the loop stops once
    ``budget_ms`` of wall time is spent (the degradation signal is already
    unambiguous by then) instead of stretching the whole probe cycle."""
    per_exec = []
    loop_t0 = time.perf_counter()
    for _ in range(iters):
        t0 = time.perf_counter()
        run_fenced()
        per_exec.append(1e3 * (time.perf_counter() - t0))
        if 1e3 * (time.perf_counter() - loop_t0) > budget_ms:
            break
    # statistics.median, not sorted()[n//2]: the latter picks the UPPER
    # middle for even n — a systematic high bias in the very statistic
    # that exists to de-bias the bandwidth numbers
    median = statistics.median(per_exec)
    device_ms = median - baseline_ms
    device_min_ms = min(per_exec) - baseline_ms
    unreliable = device_ms < 0.25 * baseline_ms
    return (
        max(device_ms, 1e-3) / repeats,
        max(device_min_ms, 1e-3) / repeats,
        unreliable,
    )


def run_hbm_probe(
    total_bytes: int = 256 * 1024 * 1024,
    *,
    iters: int = 4,
    device: Optional[jax.Device] = None,
) -> Dict[str, Any]:
    """Measure achieved HBM read bandwidth on one device."""
    try:
        device = device or jax.devices()[0]
        interpret = device.platform != "tpu"
        if interpret:
            # interpreter mode is orders of magnitude slower: shrink the
            # buffer so CPU tests stay fast; bandwidth number is meaningless
            total_bytes = min(total_bytes, BYTES_PER_BLOCK * 2)

        num_blocks = max(1, total_bytes // BYTES_PER_BLOCK)
        repeats = 1 if interpret else _pick_repeats(num_blocks * BYTES_PER_BLOCK)
        probe, rows, actual_bytes = make_hbm_read_probe(total_bytes, repeats=repeats, interpret=interpret)
        x = jax.device_put(jnp.ones((rows, WIDTH), dtype=jnp.float32), device)

        t0 = time.perf_counter()
        out = probe(x)
        got = float(jnp.sum(out)) / repeats  # fence doubles as integrity read
        compile_ms = 1e3 * (time.perf_counter() - t0)

        expected = float(rows * WIDTH)
        integrity_ok = abs(got - expected) <= 1e-6 * expected

        baseline_ms = _fence_baseline_ms(device)
        pass_ms, pass_min_ms, unreliable = _timed_pass_ms(
            lambda: _fetch_scalar(probe(x)), iters, baseline_ms, repeats
        )

        return {
            "ok": integrity_ok,
            "integrity_ok": integrity_ok,
            "bytes": actual_bytes,
            "repeats": repeats,
            "time_ms": pass_ms,
            "read_gbps": actual_bytes / (pass_ms / 1e3) / 1e9,  # median-based
            "read_gbps_best": actual_bytes / (pass_min_ms / 1e3) / 1e9,
            "bandwidth_unreliable": unreliable,
            "fence_baseline_ms": baseline_ms,
            "compile_ms": compile_ms,
            "interpreted": interpret,
            "device_id": device.id,
        }
    except Exception as exc:
        logger.error("HBM probe failed: %s", exc)
        return {"ok": False, "error": str(exc)}


def run_hbm_write_probe(
    total_bytes: int = 256 * 1024 * 1024,
    *,
    iters: int = 4,
    device: Optional[jax.Device] = None,
    corrupt_hook=None,  # test/chaos: Array -> Array applied between write and verify
) -> Dict[str, Any]:
    """Measure achieved HBM write bandwidth and verify pattern integrity.

    The verify pass reports WHICH blocks (→ which HBM address ranges) are
    bad, not just that something was wrong.
    """
    try:
        device = device or jax.devices()[0]
        interpret = device.platform != "tpu"
        if interpret:
            total_bytes = min(total_bytes, WRITE_BYTES_PER_BLOCK * 2)

        num_blocks = max(1, total_bytes // WRITE_BYTES_PER_BLOCK)
        repeats = 1 if interpret else _pick_repeats(num_blocks * WRITE_BYTES_PER_BLOCK)
        write, blocksums, rows, actual_bytes = make_hbm_write_probe(
            total_bytes, repeats=repeats, interpret=interpret
        )

        with jax.default_device(device):
            zero = jnp.zeros((1, 1), jnp.float32)
            t0 = time.perf_counter()
            y = write(zero)  # warmup = compile; kept for the verify pass
            _fetch_scalar(y)
            compile_ms = 1e3 * (time.perf_counter() - t0)

            baseline_ms = _fence_baseline_ms(device)
            # seeds pre-created AND pre-fenced: creating one inside the timed
            # window would add an un-subtracted host->device transfer per
            # iteration (observed ~2-3x low bandwidth on tunneled platforms).
            # A fresh seed per timed run keeps executions distinct.
            seed_arrays = [jnp.full((1, 1), float(k + 1), jnp.float32) for k in range(iters)]
            for s in seed_arrays:
                _fetch_scalar(s)
            seeds = iter(seed_arrays)

            def run_fenced():
                _fetch_scalar(write(next(seeds)))

            pass_ms, pass_min_ms, unreliable = _timed_pass_ms(
                run_fenced, iters, baseline_ms, repeats
            )

            # verify the WARMUP's buffer (every pass writes the same seed-0
            # pattern, so it equals a single pass) instead of re-running the
            # multi-pass writer — on a degraded part that re-run costs
            # seconds exactly when the probe matters most
            if corrupt_hook is not None:
                y = corrupt_hook(y)
            sums = blocksums(y)

        import numpy as np

        block_elems = WRITE_BLOCK_ROWS * WRITE_WIDTH
        expected = (np.arange(1, num_blocks + 1, dtype=np.float64)) * block_elems
        got = np.asarray(sums, dtype=np.float64).reshape(-1)
        # block sums are v * 2^17 with small integer v — exactly representable
        # in f32, so the tolerance only absorbs reduction-order effects
        bad = np.nonzero(np.abs(got - expected) > 1e-5 * expected)[0]
        bad_blocks = [
            {
                "block": int(b),
                "byte_offset": int(b) * WRITE_BYTES_PER_BLOCK,
                "expected_sum": float(expected[b]),
                "got_sum": float(got[b]),
            }
            for b in bad[:8]
        ]

        return {
            "ok": len(bad) == 0,
            "integrity_ok": len(bad) == 0,
            "bad_block_count": int(len(bad)),
            "bad_blocks": bad_blocks,
            "bytes": actual_bytes,
            "repeats": repeats,
            "time_ms": pass_ms,
            "write_gbps": actual_bytes / (pass_ms / 1e3) / 1e9,  # median-based
            "write_gbps_best": actual_bytes / (pass_min_ms / 1e3) / 1e9,
            "bandwidth_unreliable": unreliable,
            "fence_baseline_ms": baseline_ms,
            "compile_ms": compile_ms,
            "interpreted": interpret,
            "device_id": device.id,
        }
    except Exception as exc:
        logger.error("HBM write probe failed: %s", exc)
        return {"ok": False, "error": str(exc)}
