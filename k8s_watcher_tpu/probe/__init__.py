"""In-slice health probe plane (SURVEY.md §7 step 6 — the net-new TPU part).

The probe runs *inside* the slice as an SPMD job (every host runs the same
program; collectives ride ICI), while the watcher proper is a control-plane
singleton — they meet at the notifier (``clusterapi``), exactly the split
SURVEY.md §7 "hard parts (a)" calls for. ``ProbeAgent`` is the in-process
form used when watcher and chips share a host (dev, single-host v4-8);
``scripts/probe_agent.py`` is the standalone DaemonSet/JobSet form.
"""

from k8s_watcher_tpu.probe.device import enumerate_devices  # noqa: F401
from k8s_watcher_tpu.probe.ici import IciProbeResult, run_ici_probe, run_mxu_probe  # noqa: F401
from k8s_watcher_tpu.probe.report import ProbeReport  # noqa: F401
from k8s_watcher_tpu.probe.agent import ProbeAgent  # noqa: F401
# the plane's shared rolling-baseline primitive: the probe agent trends
# its own readings with it and the health detector (health/) reuses it
# for upstream/stage baselines — ONE drift implementation, not two
from k8s_watcher_tpu.probe.trend import TrendAlert, TrendTracker  # noqa: F401

__all__ = [
    "IciProbeResult",
    "ProbeAgent",
    "ProbeReport",
    "TrendAlert",
    "TrendTracker",
    "enumerate_devices",
    "run_ici_probe",
    "run_mxu_probe",
]
