"""Timed ICI + MXU probes.

Measurement discipline (see probe/timing.py): every program is jitted once
(warmup call pays the compile) and chains ``inner_iters`` ops inside one
execution; each timed execution is fenced by a host scalar readback with
the median fence cost subtracted — ``block_until_ready`` alone can return
early on tunneled platforms, and the fence itself costs tens of ms there.
The *minimum* is reported as the RTT (least-noise estimate of the hardware
path) alongside mean/max for jitter visibility.

North-star coverage (BASELINE.json): "ICI psum probe RTT" is
``IciProbeResult.psum_rtt_ms``; the bandwidth probe and MXU matmul catch
degraded-but-alive links/chips.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from k8s_watcher_tpu.parallel.collectives import (
    allreduce_bus_bandwidth_gbps,
    bandwidth_probe_input,
    make_allreduce_bandwidth_probe,
    make_psum_probe,
    psum_probe_input,
)
from k8s_watcher_tpu.parallel.mesh import host_chip_mesh
from k8s_watcher_tpu.probe.timing import fence_baseline_ms, fetch_scalar, timed_fenced

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class IciProbeResult:
    ok: bool
    n_devices: int
    n_hosts: int
    psum_rtt_ms: float  # min over iters (best case)
    psum_rtt_mean_ms: float
    psum_rtt_max_ms: float
    psum_rtt_median_ms: float  # robust headline (see probe/timing.py)
    psum_correct: bool
    bandwidth_gbps: float  # min-time-based (best case)
    bandwidth_gbps_median: float
    payload_bytes: int
    compile_ms: float
    error: Optional[str] = None
    # True when the fence-noise floor makes rtt/bandwidth untrustworthy
    # (tunneled dev links); consumers must discount derived rates
    timing_unreliable: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)




def run_ici_probe(
    mesh=None,
    *,
    payload_bytes: int = 4 * 1024 * 1024,
    iters: int = 10,
    inner_iters: int = 10,
    fault=None,  # faults.ici.IciFaultSpec — chaos testing only
) -> IciProbeResult:
    """Latency (chained tiny psums) + bandwidth (large all-reduce).

    ``inner_iters`` serialized psums run inside one jitted call so host
    dispatch overhead (large under remote-tunnel setups) is amortized out of
    the per-psum RTT.
    """
    try:
        if mesh is None:
            mesh = host_chip_mesh()
        n = mesh.size
        n_hosts = mesh.devices.shape[0]

        t0 = time.perf_counter()
        psum = make_psum_probe(mesh, inner_iters, fault)
        x = psum_probe_input(mesh)
        result = psum(x)
        fetch_scalar(result)  # warmup = compile (host-fenced)
        compile_ms = 1e3 * (time.perf_counter() - t0)

        expected = (n + 1) / 2.0  # fixed point of chained psum(x)/n
        psum_correct = bool(np.allclose(np.asarray(result)[0], expected))

        baseline_ms = fence_baseline_ms()
        rtt_stats = timed_fenced(psum, x, iters, baseline_ms)
        rtt_min, rtt_mean, rtt_max = (t / inner_iters for t in rtt_stats)
        unreliable = rtt_stats.unreliable

        bw_gbps = 0.0
        bw_gbps_median = 0.0
        if payload_bytes > 0 and n > 1:
            bw_fn = make_allreduce_bandwidth_probe(mesh, payload_bytes, fault)
            payload = bandwidth_probe_input(mesh, payload_bytes)
            fetch_scalar(bw_fn(payload))  # compile
            bw_stats = timed_fenced(bw_fn, payload, max(3, iters // 3), baseline_ms)
            bw_gbps = allreduce_bus_bandwidth_gbps(payload_bytes, n, bw_stats[0])
            bw_gbps_median = allreduce_bus_bandwidth_gbps(payload_bytes, n, bw_stats.median)
            unreliable = unreliable or bw_stats.unreliable

        return IciProbeResult(
            ok=psum_correct,
            n_devices=n,
            n_hosts=n_hosts,
            psum_rtt_ms=1e3 * rtt_min,
            psum_rtt_mean_ms=1e3 * rtt_mean,
            psum_rtt_max_ms=1e3 * rtt_max,
            psum_rtt_median_ms=1e3 * rtt_stats.median / inner_iters,
            psum_correct=psum_correct,
            bandwidth_gbps=bw_gbps,
            bandwidth_gbps_median=bw_gbps_median,
            payload_bytes=payload_bytes,
            compile_ms=compile_ms,
            timing_unreliable=unreliable,
        )
    except Exception as exc:
        logger.error("ICI probe failed: %s", exc)
        return IciProbeResult(
            ok=False, n_devices=0, n_hosts=0,
            psum_rtt_ms=-1.0, psum_rtt_mean_ms=-1.0, psum_rtt_max_ms=-1.0,
            psum_rtt_median_ms=-1.0,
            psum_correct=False, bandwidth_gbps=0.0, bandwidth_gbps_median=0.0,
            payload_bytes=payload_bytes,
            compile_ms=0.0, error=str(exc),
        )


def run_mxu_probe(
    size: int = 4096,
    *,
    iters: int = 5,
    inner_iters: int = 8,
    device: Optional[jax.Device] = None,
) -> Dict[str, Any]:
    """Chained bf16 matmuls on one device: MXU throughput + numeric sanity.

    bf16 inputs with f32 accumulation is the MXU-native regime. The jitted
    program chains ``inner_iters`` dependent matmuls, amortizing dispatch
    overhead; TFLOP/s = 2·size³·inner_iters / t. A health signal, not a
    benchmark — but tuned so a healthy chip reads ~peak (sweep data in
    ARCHITECTURE.md):

    - size 4096: operands resident in VMEM → MXU-bound (~100% of v5e
      nominal peak). 8192 streams 128 MiB operands from HBM every
      iteration and tops out ~12% lower — that measures HBM, which the
      dedicated hbm probes already do.
    - the chain renormalizes with a CONSTANT 1/sqrt(size) scale (entries of
      ``b`` are unit-normal, so a matmul scales RMS by ~sqrt(size)); the
      earlier data-dependent rsqrt(mean) renorm added a full reduction per
      step for a few % of throughput.
    """
    try:
        # first *local* device — jax.devices()[0] is remote (unaddressable)
        # on any multi-host process other than process 0
        device = device or jax.local_devices()[0]
        inv_scale = 1.0 / (size**0.5)

        @jax.jit
        def step(a, b):
            def body(_, carry):
                y = jnp.dot(carry, b, preferred_element_type=jnp.float32)
                # constant rescale keeps the chain in bf16 range (fuses
                # into the matmul epilogue, unlike a mean-reduction)
                return (y * inv_scale).astype(jnp.bfloat16)

            return jax.lax.fori_loop(0, inner_iters, body, a)

        key = jax.random.PRNGKey(0)
        a = jax.device_put(jax.random.normal(key, (size, size), dtype=jnp.bfloat16), device)
        b = jax.device_put(jax.random.normal(jax.random.fold_in(key, 1), (size, size), dtype=jnp.bfloat16), device)
        out = step(a, b)
        fetch_scalar(out)  # compile (host-fenced)
        finite = bool(jnp.isfinite(out.astype(jnp.float32)).all())
        baseline_ms = fence_baseline_ms(device)
        stats = timed_fenced(lambda ab: step(*ab), (a, b), iters, baseline_ms)
        tmin = stats[0]
        flops = 2.0 * size**3 * inner_iters
        return {
            "ok": finite,
            "size": size,
            "inner_iters": inner_iters,
            "device_id": device.id,
            "time_ms": 1e3 * tmin,
            "tflops": flops / tmin / 1e12,
            # median-based reading: the min estimator over-subtracts the
            # median fence from the luckiest sample, biasing TFLOP/s high
            # (observed >nominal-peak on tunneled platforms) — degradation
            # verdicts should compare the median
            "time_median_ms": 1e3 * stats.median,
            "tflops_median": flops / stats.median / 1e12,
            "finite": finite,
            "timing_unreliable": stats.unreliable,
        }
    except Exception as exc:
        logger.error("MXU probe failed: %s", exc)
        return {"ok": False, "size": size, "error": str(exc)}
