"""Cross-slice (DCN) aggregation probe.

SURVEY.md §2.11 / §5: the TPU substitute for the reference's absent
distributed-communication backend is XLA collectives — **ICI** inside a pod
slice, **DCN** across slices. This prober runs over the hybrid
``(slices, hosts, chips)`` mesh (parallel/mesh.py:hybrid_slice_mesh):

- a hierarchical psum (per-slice over ICI, then cross-slice over DCN)
  whose per-slice partial sums localize a bad contribution to its slice;
- chained subset-axis psums that time the ICI-only path and the full
  ICI+DCN path separately, so ``dcn_overhead_ms = t(all) - t(ici)`` is the
  cross-slice fabric's own cost — the number that blows up when DCN (not
  ICI) is degraded.

Single-slice deployments degenerate cleanly: one slice, no DCN hop,
``dcn_overhead_ms`` ~ 0.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from k8s_watcher_tpu.faults.ici import IciFaultSpec
from k8s_watcher_tpu.parallel.collectives import (
    make_hierarchical_probe,
    make_subaxis_psum_probe,
    psum_probe_input,
)
from k8s_watcher_tpu.parallel.mesh import hybrid_slice_mesh
from k8s_watcher_tpu.probe.timing import fence_baseline_ms, timed_fenced

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class MultiSliceProbeResult:
    ok: bool
    n_slices: int
    devices_per_slice: int
    per_slice_sums: List[float]
    suspect_slices: List[int]  # slice indices whose partial sum deviates
    ici_rtt_ms: float  # chained psum over (hosts, chips) only
    total_rtt_ms: float  # chained psum over all axes (ICI + DCN)
    dcn_overhead_ms: float  # total - ici, clamped at 0
    compile_ms: float
    error: Optional[str] = None
    # True when fence noise swamps the timed ops (see probe/timing.py)
    timing_unreliable: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def run_multislice_probe(
    mesh=None,
    *,
    n_slices: Optional[int] = None,
    iters: int = 5,
    inner_iters: int = 8,
    fault: Optional[IciFaultSpec] = None,
) -> MultiSliceProbeResult:
    """Correctness + localization via the hierarchical psum, ICI vs DCN
    latency via subset-axis chained psums. ``mesh`` defaults to
    :func:`hybrid_slice_mesh` (slice membership from the runtime, or
    ``n_slices`` equal groups on virtual meshes)."""
    try:
        if mesh is None:
            mesh = hybrid_slice_mesh(n_slices=n_slices)
        n_sl = mesh.shape["slices"]
        per_slice_devices = mesh.size // n_sl

        t0 = time.perf_counter()
        hier = make_hierarchical_probe(mesh, fault)
        ones = jax.device_put(
            jnp.ones((mesh.size,), dtype=jnp.float32),
            NamedSharding(mesh, P(tuple(mesh.axis_names))),
        )
        per_slice, global_sum = jax.block_until_ready(hier(ones))

        ici_fn = make_subaxis_psum_probe(mesh, tuple(mesh.axis_names[1:]), inner_iters, fault)
        all_fn = make_subaxis_psum_probe(mesh, tuple(mesh.axis_names), inner_iters, fault)
        x = psum_probe_input(mesh)
        jax.block_until_ready(ici_fn(x))
        jax.block_until_ready(all_fn(x))
        compile_ms = 1e3 * (time.perf_counter() - t0)

        per_slice = [float(v) for v in np.asarray(per_slice).ravel()]
        expected = float(per_slice_devices)
        suspect = [
            i for i, v in enumerate(per_slice)
            if abs(v - expected) > 1e-3 * max(1.0, expected)
        ]
        global_ok = abs(float(np.asarray(global_sum).ravel()[0]) - mesh.size) <= 1e-3 * mesh.size

        baseline_ms = fence_baseline_ms()
        ici_stats = timed_fenced(ici_fn, x, iters, baseline_ms)
        total_stats = timed_fenced(all_fn, x, iters, baseline_ms)
        ici_s = ici_stats[0] / inner_iters
        total_s = total_stats[0] / inner_iters

        if suspect:
            logger.warning(
                "Multi-slice probe: per-slice sums %s deviate from %.1f in slices %s",
                per_slice, expected, suspect,
            )
        return MultiSliceProbeResult(
            ok=not suspect and global_ok,
            n_slices=n_sl,
            devices_per_slice=per_slice_devices,
            per_slice_sums=per_slice,
            suspect_slices=suspect,
            ici_rtt_ms=1e3 * ici_s,
            total_rtt_ms=1e3 * total_s,
            dcn_overhead_ms=max(0.0, 1e3 * (total_s - ici_s)),
            compile_ms=compile_ms,
            timing_unreliable=ici_stats.unreliable or total_stats.unreliable,
        )
    except Exception as exc:
        logger.error("Multi-slice probe failed: %s", exc)
        return MultiSliceProbeResult(
            ok=False, n_slices=0, devices_per_slice=0, per_slice_sums=[],
            suspect_slices=[], ici_rtt_ms=-1.0, total_rtt_ms=-1.0,
            dcn_overhead_ms=-1.0, compile_ms=0.0, error=str(exc),
        )
