"""Cross-slice (DCN) aggregation probe.

SURVEY.md §2.11 / §5: the TPU substitute for the reference's absent
distributed-communication backend is XLA collectives — **ICI** inside a pod
slice, **DCN** across slices. This prober runs over the hybrid
``(slices, hosts, chips)`` mesh (parallel/mesh.py:hybrid_slice_mesh):

- a hierarchical psum (per-slice over ICI, then cross-slice over DCN)
  whose per-slice partial sums localize a bad contribution to its slice;
- chained subset-axis psums that time the ICI-only path and the full
  ICI+DCN path separately, so ``dcn_overhead_ms = t(all) - t(ici)`` is the
  cross-slice fabric's own cost — the number that blows up when DCN (not
  ICI) is degraded;
- a **per-pair DCN walk** (the slice-level analogue of probe/links.py):
  for every slice pair (i, j) a ``slices``-axis-only chained psum over the
  2-slice submesh times exactly the DCN path between those slices. A slow
  SLICE endpoint (its DCN NIC/path) stretches every pair it touches — the
  common endpoint of ≥2 suspect pairs is the suspect slice; a degraded
  single route stretches only its own pair; corruption fails the pair's
  checksum. Classification reuses the link prober's per-axis
  median/min-threshold discipline (probe/links.py:classify_links) with
  axis ``"dcn"``.

Single-slice deployments degenerate cleanly: one slice, no DCN hop,
``dcn_overhead_ms`` ~ 0, no pairs to walk.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from k8s_watcher_tpu.faults.ici import IciFaultSpec
from k8s_watcher_tpu.parallel.collectives import (
    make_hierarchical_probe,
    make_slice_pair_probe,
    make_subaxis_psum_probe,
    psum_probe_input,
)
from k8s_watcher_tpu.parallel.mesh import hybrid_slice_mesh
from k8s_watcher_tpu.probe.links import LinkResult, classify_links
from k8s_watcher_tpu.probe.timing import fence_baseline_ms, timed_fenced

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class MultiSliceProbeResult:
    ok: bool
    n_slices: int
    devices_per_slice: int
    per_slice_sums: List[float]
    suspect_slices: List[int]  # slice indices whose partial sum deviates
    ici_rtt_ms: float  # chained psum over (hosts, chips) only
    total_rtt_ms: float  # chained psum over all axes (ICI + DCN)
    dcn_overhead_ms: float  # total - ici, clamped at 0
    compile_ms: float
    error: Optional[str] = None
    # True when fence noise swamps the timed ops (see probe/timing.py)
    timing_unreliable: bool = False
    # per-pair DCN walk (module docstring): one record per slice pair,
    # classified with the link prober's outlier discipline
    pair_rtts: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    suspect_pairs: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    # slice indices implicated by >=2 suspect pairs (their DCN endpoint)
    dcn_suspect_slices: List[int] = dataclasses.field(default_factory=list)
    # slice index -> member process indices (the node-mapping join for the
    # remediation policy: slice -> processes -> hosts identity map -> nodes)
    slice_processes: List[List[int]] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _slice_pair_submesh(mesh, i: int, j: int):
    """The ``(2, hosts, chips)`` submesh of slices ``i`` and ``j``."""
    from jax.sharding import Mesh

    grid = np.asarray(mesh.devices)
    return Mesh(np.stack([grid[i], grid[j]], axis=0), mesh.axis_names)


def _walk_slice_pairs(
    mesh,
    *,
    iters: int,
    inner_iters: int,
    baseline_ms: float,
    fault: Optional[IciFaultSpec],
) -> tuple:
    """Time the DCN path between every slice pair; returns
    ``(records, compile_s, any_unreliable)``.

    The probed program is a ``slices``-axis-only chained psum over the
    2-slice submesh: each (host, chip) position exchanges with its
    counterpart in the other slice, so the traffic rides exactly the
    inter-slice DCN route — ICI never enters the timing. Per-pair
    containment mirrors the link walk: one failing pair becomes an error
    record, the walk continues.

    Multi-controller mode (one process per host, the real multi-slice
    deployment): every process walks the SAME deterministic pair order but
    participates only in pairs containing one of its own devices — the
    2-slice program is an SPMD computation all member processes must
    execute in lockstep, while non-members own no shard of it. The
    lowest-indexed member process owns the canonical record (host-level
    merge counts each pair once).

    Returns ``(records, merged, compile_s, any_unreliable)``. ``records``
    is this process's OWNED records (dedup-free to merge across hosts);
    ``merged`` is the full pair population, all-gathered across processes
    — classification MUST run over ``merged``: a process in the slow
    slice observes only its own (uniformly slow) pairs, so its local
    min-anchored baseline is itself slow and flags nothing, while a
    healthy slice's process observes exactly ONE suspect pair per faulty
    peer — below the >=2-pair endpoint threshold. Only the union has
    both the healthy anchor and the full suspect count, and every
    process classifying the same union keeps the verdict replicated (the
    policy's process-0 actor needs to see what any process saw).
    ``any_unreliable`` is likewise OR-merged across processes.
    """
    n_sl = mesh.shape["slices"]
    pid = jax.process_index()
    multi = jax.process_count() > 1
    records: List[LinkResult] = []
    compile_s = 0.0
    any_unreliable = False
    for i in range(n_sl):
        for j in range(i + 1, n_sl):
            name = f"slice{i}-slice{j}"
            owner = None  # resolved once membership is known
            try:
                sub = _slice_pair_submesh(mesh, i, j)
                member_procs = sorted({d.process_index for d in sub.devices.flat})
                if multi and pid not in member_procs:
                    continue
                owner = (not multi) or pid == member_procs[0]
                fn, expected = make_slice_pair_probe(sub, inner_iters, fault)
                x = psum_probe_input(sub)
                t0 = time.perf_counter()
                # warmup + checksum: the program's output is a REPLICATED
                # scalar, so this readback is process-local for every
                # member (see make_slice_pair_probe)
                out = float(np.asarray(jax.block_until_ready(fn(x))).ravel()[0])
                compile_s += time.perf_counter() - t0
                correct = abs(out - expected) <= 1e-3 * max(1.0, abs(expected))
                stats = timed_fenced(fn, x, iters, baseline_ms)
                any_unreliable = any_unreliable or stats.unreliable
                records.append(LinkResult(
                    axis="dcn", name=name, device_ids=(i, j),
                    rtt_ms=1e3 * stats[0] / inner_iters,
                    rtt_mean_ms=1e3 * stats[1] / inner_iters,
                    correct=correct, owner=owner,
                ))
            except Exception as exc:  # noqa: BLE001 — per-pair containment
                logger.warning("Slice-pair probe %s failed: %s", name, exc)
                if owner is None:
                    # failed before membership resolved: EVERY process is
                    # here (the computation was pure mesh math, identical
                    # everywhere), so process 0 is the fallback canonical
                    # recorder — owner=True on all N would make a merge
                    # count one failed pair N times
                    owner = (not multi) or pid == 0
                records.append(LinkResult(
                    axis="dcn", name=name, device_ids=(i, j),
                    rtt_ms=-1.0, rtt_mean_ms=-1.0, correct=False, owner=owner,
                    error=str(exc),
                ))
    merged = records
    if multi:
        # All-gather the owner-encoded rows so every process classifies
        # the FULL pair population (docstring: neither a faulty slice's
        # process nor a healthy one's can classify from its local view).
        # One row per pair in the deterministic (i, j) order; exactly one
        # process owns each pair, non-owners hold the -2 sentinel.
        # Columns: [rtt_ms, rtt_mean_ms, correct]; an owned ERROR record
        # travels as rtt_ms=-1 (its text stays local). The trailing row is
        # EVERY process's local unreliable flag — it must not ride the
        # owner rows, because a process that owns no pair (the highest
        # slice's, in one-process-per-slice deployments) would have its
        # flag silently dropped and the OR-merge would diverge across
        # processes.
        from jax.experimental import multihost_utils

        pair_order = [(i, j) for i in range(n_sl) for j in range(i + 1, n_sl)]
        pair_pos = {pair: k for k, pair in enumerate(pair_order)}
        buf = np.full((len(pair_order) + 1, 3), -2.0, dtype=np.float32)
        for r in records:
            if r.owner:
                buf[pair_pos[tuple(r.device_ids)]] = (
                    r.rtt_ms, r.rtt_mean_ms, 1.0 if r.correct else 0.0,
                )
        buf[-1] = (1.0 if any_unreliable else 0.0, 0.0, 0.0)
        gathered = np.asarray(multihost_utils.process_allgather(buf))
        any_unreliable = bool(np.any(gathered[:, -1, 0] > 0.5))
        merged = []
        for k, (i, j) in enumerate(pair_order):
            rows = gathered[:, k, :]
            owned = rows[rows[:, 0] > -1.5]
            if owned.shape[0] == 0:
                merged.append(LinkResult(
                    axis="dcn", name=f"slice{i}-slice{j}", device_ids=(i, j),
                    rtt_ms=-1.0, rtt_mean_ms=-1.0, correct=False, owner=False,
                    error="pair probe failed on its owner process",
                ))
                continue
            row = owned[0]
            merged.append(LinkResult(
                axis="dcn", name=f"slice{i}-slice{j}", device_ids=(i, j),
                rtt_ms=float(row[0]), rtt_mean_ms=float(row[1]),
                correct=bool(row[2] > 0.5), owner=False,
                error=None if row[0] >= 0.0 else "pair probe failed on its owner process",
            ))
    return records, merged, compile_s, any_unreliable


def run_multislice_probe(
    mesh=None,
    *,
    n_slices: Optional[int] = None,
    iters: int = 5,
    inner_iters: int = 8,
    fault: Optional[IciFaultSpec] = None,
    pair_localization: bool = True,
    pair_rtt_factor: float = 3.0,
    pair_rtt_floor_ms: float = 0.2,
) -> MultiSliceProbeResult:
    """Correctness + localization via the hierarchical psum, ICI vs DCN
    latency via subset-axis chained psums. ``mesh`` defaults to
    :func:`hybrid_slice_mesh` (slice membership from the runtime, or
    ``n_slices`` equal groups on virtual meshes)."""
    try:
        if mesh is None:
            mesh = hybrid_slice_mesh(n_slices=n_slices)
        n_sl = mesh.shape["slices"]
        per_slice_devices = mesh.size // n_sl
        grid = np.asarray(mesh.devices)
        slice_processes = [
            sorted({d.process_index for d in grid[i].flat}) for i in range(n_sl)
        ]

        t0 = time.perf_counter()
        hier = make_hierarchical_probe(mesh, fault)
        sharding = NamedSharding(mesh, P(tuple(mesh.axis_names)))
        if any(d.process_index != jax.process_index() for d in mesh.devices.flat):
            # multi-controller: assemble from per-process addressable shards
            ones = jax.make_array_from_callback(
                (mesh.size,), sharding, lambda idx: np.ones((1,), dtype=np.float32)
            )
        else:
            ones = jax.device_put(jnp.ones((mesh.size,), dtype=jnp.float32), sharding)
        per_slice, global_sum = jax.block_until_ready(hier(ones))

        ici_fn = make_subaxis_psum_probe(mesh, tuple(mesh.axis_names[1:]), inner_iters, fault)
        all_fn = make_subaxis_psum_probe(mesh, tuple(mesh.axis_names), inner_iters, fault)
        x = psum_probe_input(mesh)
        jax.block_until_ready(ici_fn(x))
        jax.block_until_ready(all_fn(x))
        compile_ms = 1e3 * (time.perf_counter() - t0)

        per_slice = [float(v) for v in np.asarray(per_slice).ravel()]
        expected = float(per_slice_devices)
        suspect = [
            i for i, v in enumerate(per_slice)
            if abs(v - expected) > 1e-3 * max(1.0, expected)
        ]
        global_ok = abs(float(np.asarray(global_sum).ravel()[0]) - mesh.size) <= 1e-3 * mesh.size

        baseline_ms = fence_baseline_ms()
        ici_stats = timed_fenced(ici_fn, x, iters, baseline_ms)
        total_stats = timed_fenced(all_fn, x, iters, baseline_ms)
        ici_s = ici_stats[0] / inner_iters
        total_s = total_stats[0] / inner_iters

        pair_records: List[LinkResult] = []
        suspect_pairs: List[Dict[str, Any]] = []
        dcn_suspect_slices: List[int] = []
        pairs_unreliable = False
        pair_compile_s = 0.0
        if pair_localization and n_sl >= 2:
            pair_records, merged_records, pair_compile_s, pairs_unreliable = _walk_slice_pairs(
                mesh, iters=iters, inner_iters=inner_iters,
                baseline_ms=baseline_ms, fault=fault,
            )
            # min-baseline: a bad slice endpoint taints 2/n of ALL pairs
            # (50% at n=4), which drags a median baseline past any factor —
            # the healthiest route anchors the threshold instead.
            # Classified over the MERGED population (multi-controller: the
            # local view has neither the healthy anchor nor the full
            # suspect count — _walk_slice_pairs docstring), so the verdict
            # is identical on every process.
            suspect_pairs, dcn_suspect_slices = classify_links(
                merged_records, pair_rtt_factor, pair_rtt_floor_ms, baseline_stat="min"
            )
            if suspect_pairs:
                logger.warning(
                    "Slice-pair DCN walk: %d/%d suspect pairs: %s; suspect slices: %s",
                    len(suspect_pairs), len(pair_records),
                    [s["name"] for s in suspect_pairs], dcn_suspect_slices,
                )

        if suspect:
            logger.warning(
                "Multi-slice probe: per-slice sums %s deviate from %.1f in slices %s",
                per_slice, expected, suspect,
            )
        return MultiSliceProbeResult(
            ok=not suspect and global_ok and not suspect_pairs,
            n_slices=n_sl,
            devices_per_slice=per_slice_devices,
            per_slice_sums=per_slice,
            suspect_slices=suspect,
            ici_rtt_ms=1e3 * ici_s,
            total_rtt_ms=1e3 * total_s,
            dcn_overhead_ms=max(0.0, 1e3 * (total_s - ici_s)),
            compile_ms=compile_ms + 1e3 * pair_compile_s,
            timing_unreliable=ici_stats.unreliable or total_stats.unreliable or pairs_unreliable,
            pair_rtts=[dataclasses.asdict(r) for r in pair_records],
            suspect_pairs=suspect_pairs,
            dcn_suspect_slices=dcn_suspect_slices,
            slice_processes=slice_processes,
        )
    except Exception as exc:
        logger.error("Multi-slice probe failed: %s", exc)
        return MultiSliceProbeResult(
            ok=False, n_slices=0, devices_per_slice=0, per_slice_sums=[],
            suspect_slices=[], ici_rtt_ms=-1.0, total_rtt_ms=-1.0,
            dcn_overhead_ms=-1.0, compile_ms=0.0, error=str(exc),
        )
