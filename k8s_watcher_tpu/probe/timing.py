"""Shared probe timing discipline.

Wall-clock measurement on remote/tunneled platforms (the axon dev setup)
has two failure modes that produced physically impossible numbers before
this module existed:

- ``jax.block_until_ready`` can return before the execution actually
  completes, so per-iteration timings undercount (multi-TB/s "bandwidth");
- every real completion fence (a host scalar readback) costs tens of ms
  with high variance, so per-iteration timings overcount small ops.

Discipline: amortize real work inside ONE jitted execution (chained inner
iterations / multi-pass grids), fence each timed execution with a host
scalar readback, and subtract the separately-measured median fence cost.
On local TPU deployments the fence is cheap and the same path is simply
accurate.
"""

from __future__ import annotations

import statistics
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp


def fetch_scalar(out: Any) -> float:
    """Read one element of (the first leaf of) ``out`` back to the host —
    the only reliable completion fence on tunneled platforms.

    On arrays spanning processes (multi-controller probes over sharded
    outputs) element 0 may live on a remote host; any ADDRESSABLE shard is
    an equally valid completion fence — the local device must have
    finished its part of the program before its shard is readable."""
    leaf = jax.tree_util.tree_leaves(out)[0]
    if getattr(leaf, "is_fully_addressable", True):
        return float(jnp.reshape(leaf, (-1,))[0])
    import numpy as np

    return float(np.asarray(leaf.addressable_shards[0].data).ravel()[0])


def fence_baseline_ms(device: Optional[jax.Device] = None, samples: int = 3) -> float:
    """Median cost of the completion fence itself (dispatch + readback)."""
    tiny = jnp.zeros((2,), jnp.float32)
    if device is not None:
        tiny = jax.device_put(tiny, device)
    fetch_scalar(tiny)  # warm the dispatch path
    costs = []
    for _ in range(samples):
        t0 = time.perf_counter()
        fetch_scalar(tiny)
        costs.append(1e3 * (time.perf_counter() - t0))
    return statistics.median(costs)


class TimedStats(tuple):
    """(min, mean, max) seconds — a plain 3-tuple for unpacking — plus two
    attributes: ``median`` (robust against the min-estimator's high bias on
    derived rates: subtracting a median fence from the LUCKIEST sample
    systematically over-subtracts, inflating TFLOP/s / GB/s) and
    ``unreliable``, True when the op's device time is buried in fence
    noise, so derived rates must be discounted (the same contract hbm.py's
    ``bandwidth_unreliable`` flag carries)."""

    median: float
    unreliable: bool

    def __new__(
        cls, tmin: float, tmean: float, tmax: float,
        unreliable: bool, median: float,
    ):
        # median is REQUIRED: a default that silently falls back to tmin
        # would reintroduce the min-as-median bias this type exists to fix
        obj = super().__new__(cls, (tmin, tmean, tmax))
        obj.unreliable = unreliable
        obj.median = median
        return obj


def timed_fenced(fn, x, iters: int, baseline_ms: float = 0.0) -> TimedStats:
    """(min, mean, max) SECONDS over ``iters`` host-fenced executions, each
    with the fence baseline subtracted (clamped at ~0); ``.median`` carries
    the median sample.

    The result's ``unreliable`` flag is set when the best sample's device
    share is under a quarter of the fence baseline: subtracting a noisy
    ~baseline-sized fence from a ~baseline-sized wall time leaves mostly
    noise, and the clamped-at-~0 minima turn into physically impossible
    derived rates if trusted."""
    times = []
    raw_min = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        fetch_scalar(fn(x))
        raw = time.perf_counter() - t0
        raw_min = min(raw_min, raw)
        times.append(max(raw - baseline_ms / 1e3, 1e-9))
    unreliable = baseline_ms > 0 and (raw_min - baseline_ms / 1e3) < 0.25 * baseline_ms / 1e3
    # statistics.median (not sorted()[n//2], whose upper-middle pick biases
    # even-iters runs high — the exact bias this statistic exists to remove)
    return TimedStats(
        min(times), sum(times) / len(times), max(times), unreliable,
        statistics.median(times),
    )
