"""Per-link ICI probe: localize degraded links/chips, not just detect them.

The aggregate psum probe (probe/ici.py) answers "is this slice healthy?";
when it isn't, operators need to know *which* chip or link to drain. This
prober walks every neighbor pair of the ``(hosts, chips)`` mesh — the ICI
torus's physical edges — timing a chained 2-device ``ppermute`` exchange per
link (parallel/collectives.py:make_pair_probe) and checksumming the payload
round-trip:

- a **slow chip** stretches every link probe it participates in → the
  common endpoint of the slow links is the suspect chip;
- a **degraded link** stretches only its own pair probe;
- a **corrupt chip** fails the checksum of every link it touches.

Outliers are flagged against the *median* link RTT (robust to global noise:
on a healthy mesh all links are within a small factor of each other), with
an absolute floor so microsecond-scale jitter can't trip it.

Process model: single-controller probes every link. In multi-controller
(DaemonSet) mode each host probes its own intra-host links — a 2-device
program over a remote host's devices can't be launched locally — and
inter-host paths stay covered by the aggregate psum/bandwidth probes, so
localization granularity there is per-host, not per-link.

Faults for chaos tests are injected via ``IciFaultSpec`` (faults/ici.py);
tests assert the prober fingers exactly the injected device.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from k8s_watcher_tpu.faults.ici import IciFaultSpec
from k8s_watcher_tpu.parallel.collectives import make_pair_probe, pair_probe_input
from k8s_watcher_tpu.parallel.mesh import host_chip_mesh

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class LinkResult:
    axis: str  # "chips" (intra-host) | "hosts" (inter-host)
    name: str  # e.g. "host0/chip1-chip2"
    device_ids: Tuple[int, int]
    rtt_ms: float  # min per-hop over iters
    rtt_mean_ms: float
    correct: bool


@dataclasses.dataclass
class LinkProbeResult:
    ok: bool
    n_links: int
    median_rtt_ms: float
    links: List[LinkResult]
    suspect_links: List[Dict[str, Any]]  # {name, device_ids, reason, rtt_ms}
    suspect_devices: List[int]  # device ids implicated by >1 suspect link
    compile_ms: float
    error: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)  # recursively converts nested LinkResults


def enumerate_links(mesh) -> List[Tuple[str, str, jax.Device, jax.Device]]:
    """Neighbor pairs along each mesh axis: ``(axis, name, dev_a, dev_b)``.

    Rows of the device grid are chips within one host (intra-host ICI);
    columns cross hosts (inter-host ICI / DCN). Rings longer than 2 get the
    wraparound edge — matching the physical torus topology.
    """
    grid = np.asarray(mesh.devices)
    if grid.ndim == 1:
        grid = grid.reshape(1, -1)
    hosts, chips = grid.shape
    links: List[Tuple[str, str, jax.Device, jax.Device]] = []
    for h in range(hosts):
        for c in range(chips - 1):
            links.append(("chips", f"host{h}/chip{c}-chip{c + 1}", grid[h, c], grid[h, c + 1]))
        if chips > 2:
            links.append(("chips", f"host{h}/chip{chips - 1}-chip0", grid[h, chips - 1], grid[h, 0]))
    for c in range(chips):
        for h in range(hosts - 1):
            links.append(("hosts", f"chip{c}/host{h}-host{h + 1}", grid[h, c], grid[h + 1, c]))
        if hosts > 2:
            links.append(("hosts", f"chip{c}/host{hosts - 1}-host0", grid[hosts - 1, c], grid[0, c]))
    return links


def _timed_pair(fn, x, expected: float, iters: int, inner_iters: int) -> Tuple[float, float, bool]:
    """(min_per_hop_s, mean_per_hop_s, correct) over ``iters`` fenced calls.

    The host readback (np.asarray) IS the completion fence. Its cost is
    deliberately NOT subtracted here: every link carries the same fence
    overhead, so the outlier test (factor x median across links) cancels it
    — whereas subtracting a noisy baseline can clamp fast links to ~0,
    collapse the median, and turn residual fence variance into false
    "slow" suspects. Absolute per-hop values are therefore inflated by
    fence_cost/inner_iters on tunneled platforms; comparisons are not."""
    times, correct = [], True
    for _ in range(iters):
        t0 = time.perf_counter()
        out = np.asarray(fn(x))
        times.append(time.perf_counter() - t0)
        if abs(float(out.ravel()[0]) - expected) > 1e-3 * max(1.0, abs(expected)):
            correct = False
    return min(times) / inner_iters, (sum(times) / len(times)) / inner_iters, correct


def run_link_probe(
    mesh=None,
    *,
    iters: int = 5,
    inner_iters: int = 8,
    rtt_factor: float = 3.0,
    rtt_floor_ms: float = 0.05,
    fault: Optional[IciFaultSpec] = None,
) -> LinkProbeResult:
    """Probe every mesh link; flag outliers and triangulate suspect devices.

    A link is suspect when its payload checksum fails ("corrupt") or its
    per-hop RTT exceeds ``max(rtt_floor_ms, rtt_factor * median)`` ("slow").
    A device is suspect when it is an endpoint of at least two suspect links
    (a single bad link implicates the link, not a chip).
    """
    try:
        if mesh is None:
            mesh = host_chip_mesh()
        links = enumerate_links(mesh)
        if jax.process_count() > 1:
            # Multi-controller mode: a 2-device program over another host's
            # devices cannot be launched from here (non-addressable shards),
            # so each host probes its own intra-host links; inter-host paths
            # are covered by the aggregate psum/bandwidth probes (detection
            # at host granularity rather than per-link localization).
            pid = jax.process_index()
            local = [l for l in links if l[2].process_index == pid and l[3].process_index == pid]
            if len(local) < len(links):
                logger.info(
                    "Multi-host link probe: probing %d/%d process-local links "
                    "(inter-host links covered by the aggregate probes)",
                    len(local), len(links),
                )
            links = local
        if not links:
            return LinkProbeResult(
                ok=True, n_links=0, median_rtt_ms=0.0, links=[],
                suspect_links=[], suspect_devices=[], compile_ms=0.0,
            )

        compile_s = 0.0
        results: List[LinkResult] = []
        for axis, name, dev_a, dev_b in links:
            fn, pair_mesh, expected = make_pair_probe(dev_a, dev_b, inner_iters, fault)
            x = pair_probe_input(pair_mesh)
            t0 = time.perf_counter()
            np.asarray(fn(x))  # warmup, host-fenced (compile on first cycle)
            compile_s += time.perf_counter() - t0
            rtt_min, rtt_mean, correct = _timed_pair(fn, x, expected, iters, inner_iters)
            results.append(
                LinkResult(
                    axis=axis,
                    name=name,
                    device_ids=(dev_a.id, dev_b.id),
                    rtt_ms=1e3 * rtt_min,
                    rtt_mean_ms=1e3 * rtt_mean,
                    correct=correct,
                )
            )
        compile_ms = 1e3 * compile_s

        median = float(np.median([r.rtt_ms for r in results]))
        threshold = max(rtt_floor_ms, rtt_factor * median)
        suspects: List[Dict[str, Any]] = []
        for r in results:
            if not r.correct:
                suspects.append({"name": r.name, "device_ids": list(r.device_ids), "reason": "corrupt", "rtt_ms": r.rtt_ms})
            elif r.rtt_ms > threshold:
                suspects.append({"name": r.name, "device_ids": list(r.device_ids), "reason": "slow", "rtt_ms": r.rtt_ms})

        endpoint_counts: Dict[int, int] = {}
        for s in suspects:
            for d in s["device_ids"]:
                endpoint_counts[d] = endpoint_counts.get(d, 0) + 1
        suspect_devices = sorted(d for d, c in endpoint_counts.items() if c >= 2)

        if suspects:
            logger.warning(
                "Link probe: %d/%d suspect links (median %.3f ms): %s; suspect devices: %s",
                len(suspects), len(results), median,
                [s["name"] for s in suspects], suspect_devices,
            )
        return LinkProbeResult(
            ok=not suspects,
            n_links=len(results),
            median_rtt_ms=median,
            links=results,
            suspect_links=suspects,
            suspect_devices=suspect_devices,
            compile_ms=compile_ms,
        )
    except Exception as exc:
        logger.error("Link probe failed: %s", exc)
        return LinkProbeResult(
            ok=False, n_links=0, median_rtt_ms=-1.0, links=[],
            suspect_links=[], suspect_devices=[], compile_ms=0.0, error=str(exc),
        )
