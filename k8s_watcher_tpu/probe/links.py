"""Per-link ICI probe: localize degraded links/chips, not just detect them.

The aggregate psum probe (probe/ici.py) answers "is this slice healthy?";
when it isn't, operators need to know *which* chip or link to drain. This
prober walks every neighbor pair of the ``(hosts, chips)`` mesh — the ICI
torus's physical edges — timing a chained 2-device ``ppermute`` exchange per
link (parallel/collectives.py:make_pair_probe) and checksumming the payload
round-trip:

- a **slow chip** stretches every link probe it participates in → the
  common endpoint of the slow links is the suspect chip;
- a **degraded link** stretches only its own pair probe;
- a **corrupt chip** fails the checksum of every link it touches.

Outliers are flagged against the *median* link RTT (robust to global noise:
on a healthy mesh all links are within a small factor of each other), with
an absolute floor so microsecond-scale jitter can't trip it.

Process model: single-controller probes every link. In multi-controller
(DaemonSet) mode every process walks the SAME deterministic global link
list and participates in exactly the pair programs that touch one of its
own devices: intra-host links run solo, and an inter-host link runs as a
2-process SPMD pair program — both endpoint processes execute it in
lockstep (same list order on every process, so overlapping pairs can't
deadlock), and the lower-indexed endpoint process records the result so
each edge is measured once. Inter-host edges are thereby localized
per-link, not just covered in aggregate; a host-level merge of the
per-process results yields the full edge map.

Faults for chaos tests are injected via ``IciFaultSpec`` (faults/ici.py);
tests assert the prober fingers exactly the injected device.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from k8s_watcher_tpu.faults.ici import IciFaultSpec
from k8s_watcher_tpu.parallel.collectives import (
    make_pair_probe,
    make_subaxis_psum_probe,
    pair_probe_input,
)
from k8s_watcher_tpu.parallel.mesh import host_chip_mesh

logger = logging.getLogger(__name__)

# Test hook: when set, ``_PREP_FAILURE_HOOK(link_name)`` is consulted during
# the preparation phase and a truthy return injects a preparation failure for
# that link — the only way to exercise the cross-process agreement protocol
# below without real breakage. Production leaves it None.
_PREP_FAILURE_HOOK: Optional[Callable[[str], bool]] = None


def _all_processes_ready(mesh, prep_ok: bool) -> bool:
    """Full-mesh AND of every process's "my cross-process preps succeeded".

    The agreement round of the probe's prepare/agree/execute protocol: every
    process ALWAYS joins this one psum (it is the only collective whose
    membership doesn't depend on per-link prep outcomes), contributing 1.0
    from each of its devices when its cross-process preparations all
    succeeded, else 0.0. The psum probe returns the mean of the flags, so
    every process derives the same verdict — mean == 1.0 iff nobody failed —
    without a side channel. Single-process mode has nobody to agree with.
    """
    if jax.process_count() == 1:
        return prep_ok
    axes = tuple(mesh.axis_names)
    flag = 1.0 if prep_ok else 0.0
    sharding = NamedSharding(mesh, PartitionSpec(axes))
    arr = jax.make_array_from_callback(
        (mesh.size,), sharding, lambda idx: np.full((1,), flag, dtype=np.float32)
    )
    fn = make_subaxis_psum_probe(mesh, axes)
    mean = float(np.asarray(fn(arr)).ravel()[0])
    return mean >= 1.0 - 1e-6


@dataclasses.dataclass
class LinkResult:
    axis: str  # "chips" (intra-host) | "hosts" (inter-host)
    name: str  # e.g. "host0/chip1-chip2"
    device_ids: Tuple[int, int]
    rtt_ms: float  # min per-hop over iters (-1 when the probe errored)
    rtt_mean_ms: float
    correct: bool
    # this process is the canonical recorder for the edge (lower-indexed
    # endpoint); non-owned observations still feed suspect triangulation
    owner: bool = True
    error: Optional[str] = None


@dataclasses.dataclass
class LinkProbeResult:
    ok: bool
    n_links: int  # edges this process canonically records (owner=True)
    # edges this process OBSERVED (walked), owned or not — the "did the
    # walk measure anything" signal: a process can observe links it does
    # not own (its inter-host edges record on the lower-indexed peer)
    n_observed: int
    median_rtt_ms: float
    links: List[LinkResult]  # owned records only — merge across hosts dedup-free
    suspect_links: List[Dict[str, Any]]  # {name, device_ids, reason, rtt_ms} over ALL observed
    suspect_devices: List[int]  # device ids implicated by >1 suspect link
    compile_ms: float
    error: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)  # recursively converts nested LinkResults


def enumerate_links(mesh) -> List[Tuple[str, str, jax.Device, jax.Device]]:
    """Neighbor pairs along each mesh axis: ``(axis, name, dev_a, dev_b)``.

    Rows of the device grid are chips within one host (intra-host ICI);
    columns cross hosts (inter-host ICI / DCN). Rings longer than 2 get the
    wraparound edge — matching the physical torus topology.
    """
    grid = np.asarray(mesh.devices)
    if grid.ndim == 1:
        grid = grid.reshape(1, -1)
    hosts, chips = grid.shape
    links: List[Tuple[str, str, jax.Device, jax.Device]] = []
    for h in range(hosts):
        for c in range(chips - 1):
            links.append(("chips", f"host{h}/chip{c}-chip{c + 1}", grid[h, c], grid[h, c + 1]))
        if chips > 2:
            links.append(("chips", f"host{h}/chip{chips - 1}-chip0", grid[h, chips - 1], grid[h, 0]))
    for c in range(chips):
        for h in range(hosts - 1):
            links.append(("hosts", f"chip{c}/host{h}-host{h + 1}", grid[h, c], grid[h + 1, c]))
        if hosts > 2:
            links.append(("hosts", f"chip{c}/host{hosts - 1}-host0", grid[hosts - 1, c], grid[0, c]))
    return links


def classify_links(
    observed: List[LinkResult],
    rtt_factor: float,
    rtt_floor_ms: float,
    baseline_stat: str = "median",
) -> Tuple[List[Dict[str, Any]], List[int]]:
    """Pure suspect classification: ``(suspect_links, suspect_devices)``.

    ``baseline_stat`` picks the healthy-baseline estimator for populations
    of >=3: ``"median"`` (default — robust to jitter when a bad endpoint
    taints a small FRACTION of links, as in the torus walk where a chip
    touches ~2 of O(hosts*chips) edges) or ``"min"`` (for walks where one
    bad endpoint contaminates a large fraction — the slice-pair DCN walk's
    bad slice taints 2/n of ALL pairs, 50% at n=4, which drags the median
    past any factor; the min anchors the healthiest route instead).

    A link is suspect when it errored, failed its payload checksum
    ("corrupt"), or its RTT exceeds ``max(rtt_floor_ms, rtt_factor *
    per-axis baseline)`` ("slow") — so the per-link detection floor IS
    ``rtt_factor`` (default 3x): a 2x-degraded link is deliberately below
    the default threshold (false-positive margin against scheduler/fence
    jitter) and requires ``tpu.probe.link_rtt_factor <= ~1.8`` to resolve.
    Corruption has no such floor — any magnitude, first cycle. The exact
    boundary is pinned by tests/test_links.py::TestClassifySensitivity.

    Like-for-like thresholds: intra-host ("chips") and inter-host ("hosts")
    hops have different healthy baselines (the columns can be DCN-backed),
    so one mixed median would flag every healthy inter-host link on
    asymmetric fabrics — or mask a degraded intra-host link under the
    inflated threshold. Small populations need a different statistic: the
    median of 2 samples is dragged halfway toward an outlier (a
    10x-degraded link would set its own threshold), so with <=2 samples the
    MIN anchors the healthy baseline; with one sample there is no reference
    and only the floor applies. A device is suspect when it is an endpoint
    of >=2 suspect links (one bad link implicates the link, not a chip).
    """
    if baseline_stat not in ("median", "min"):
        raise ValueError(f"baseline_stat must be 'median' or 'min', got {baseline_stat!r}")
    thresholds: Dict[str, float] = {}
    for axis in {r.axis for r in observed}:
        population = [r.rtt_ms for r in observed if r.axis == axis and r.rtt_ms >= 0]
        if not population:
            base = 0.0
        elif len(population) >= 3:
            base = float(np.median(population)) if baseline_stat == "median" else min(population)
        elif len(population) == 2:
            base = min(population)
        else:
            base = population[0]
        thresholds[axis] = max(rtt_floor_ms, rtt_factor * base)
    suspects: List[Dict[str, Any]] = []
    for r in observed:
        if r.error is not None:
            suspects.append({"name": r.name, "device_ids": list(r.device_ids), "reason": "error", "rtt_ms": r.rtt_ms})
        elif not r.correct:
            suspects.append({"name": r.name, "device_ids": list(r.device_ids), "reason": "corrupt", "rtt_ms": r.rtt_ms})
        elif r.rtt_ms > thresholds[r.axis]:
            suspects.append({"name": r.name, "device_ids": list(r.device_ids), "reason": "slow", "rtt_ms": r.rtt_ms})

    endpoint_counts: Dict[int, int] = {}
    for s in suspects:
        for d in s["device_ids"]:
            endpoint_counts[d] = endpoint_counts.get(d, 0) + 1
    suspect_devices = sorted(d for d, c in endpoint_counts.items() if c >= 2)
    return suspects, suspect_devices


def _timed_pair(fn, x, expected: float, iters: int, inner_iters: int) -> Tuple[float, float, bool]:
    """(min_per_hop_s, mean_per_hop_s, correct) over ``iters`` fenced calls.

    The host readback (np.asarray) IS the completion fence. Its cost is
    deliberately NOT subtracted here: every link carries the same fence
    overhead, so the outlier test (factor x median across links) cancels it
    — whereas subtracting a noisy baseline can clamp fast links to ~0,
    collapse the median, and turn residual fence variance into false
    "slow" suspects. Absolute per-hop values are therefore inflated by
    fence_cost/inner_iters on tunneled platforms; comparisons are not."""
    times, correct = [], True
    for _ in range(iters):
        t0 = time.perf_counter()
        out = np.asarray(fn(x))
        times.append(time.perf_counter() - t0)
        if abs(float(out.ravel()[0]) - expected) > 1e-3 * max(1.0, abs(expected)):
            correct = False
    return min(times) / inner_iters, (sum(times) / len(times)) / inner_iters, correct


def run_link_probe(
    mesh=None,
    *,
    iters: int = 5,
    inner_iters: int = 8,
    rtt_factor: float = 3.0,
    rtt_floor_ms: float = 0.05,
    fault: Optional[IciFaultSpec] = None,
) -> LinkProbeResult:
    """Probe every mesh link; flag outliers and triangulate suspect devices.

    A link is suspect when its payload checksum fails ("corrupt") or its
    per-hop RTT exceeds ``max(rtt_floor_ms, rtt_factor * median)`` ("slow").
    A device is suspect when it is an endpoint of at least two suspect links
    (a single bad link implicates the link, not a chip).
    """
    try:
        if mesh is None:
            mesh = host_chip_mesh()
        links = enumerate_links(mesh)
        pid = jax.process_index()
        if jax.process_count() > 1:
            # Multi-controller mode: participate in every pair program that
            # touches one of this process's devices. An inter-host link is
            # a 2-process SPMD program both endpoints must execute in
            # lockstep; every process walks the same global list order, so
            # overlapping pairs rendezvous deterministically.
            participating = [
                l for l in links
                if l[2].process_index == pid or l[3].process_index == pid
            ]
            if len(participating) < len(links):
                logger.info(
                    "Multi-host link probe: participating in %d/%d links "
                    "(others belong entirely to other hosts)",
                    len(participating), len(links),
                )
            links = participating
        if not links:
            return LinkProbeResult(
                ok=True, n_links=0, n_observed=0, median_rtt_ms=0.0, links=[],
                suspect_links=[], suspect_devices=[], compile_ms=0.0,
            )

        # PREPARATION phase — everything local (tracing, input building)
        # happens BEFORE any cross-process program launches. A local
        # failure here is one-sided: the peer would block forever in a
        # collective this process never joins, so prepared links are
        # reconciled across processes below before anything executes.
        prepared = []  # (axis, name, dev_a, dev_b, owner, fn, x, expected) | error LinkResult
        prep_ok = True
        observed: List[LinkResult] = []
        for axis, name, dev_a, dev_b in links:
            owner = pid == min(dev_a.process_index, dev_b.process_index)
            cross = dev_a.process_index != dev_b.process_index
            try:
                if _PREP_FAILURE_HOOK is not None and _PREP_FAILURE_HOOK(name):
                    raise RuntimeError("injected preparation failure (test hook)")
                fn, pair_mesh, expected = make_pair_probe(dev_a, dev_b, inner_iters, fault)
                x = pair_probe_input(pair_mesh)
            except Exception as exc:  # noqa: BLE001 — containment, see above
                logger.warning("Link probe %s preparation failed: %s", name, exc)
                observed.append(LinkResult(
                    axis=axis, name=name, device_ids=(dev_a.id, dev_b.id),
                    rtt_ms=-1.0, rtt_mean_ms=-1.0, correct=False,
                    owner=owner, error=f"preparation: {exc}",
                ))
                if cross:
                    prep_ok = False
                continue
            prepared.append((axis, name, dev_a, dev_b, owner, cross, fn, x, expected))

        # AGREEMENT: one full-mesh psum carries every process's "all my
        # cross-process preparations succeeded" flag. If anyone failed,
        # ALL processes skip ALL cross-process programs this cycle —
        # otherwise the failed process's peers would hang waiting for it.
        run_cross = _all_processes_ready(mesh, prep_ok)
        if not run_cross and jax.process_count() > 1:
            logger.warning(
                "Link probe: a process failed preparation; probing intra-host "
                "links only this cycle"
            )

        compile_s = 0.0
        for axis, name, dev_a, dev_b, owner, cross, fn, x, expected in prepared:
            if cross and not run_cross:
                observed.append(LinkResult(
                    axis=axis, name=name, device_ids=(dev_a.id, dev_b.id),
                    rtt_ms=-1.0, rtt_mean_ms=-1.0, correct=False, owner=owner,
                    error="skipped: a peer process failed preparation",
                ))
                continue
            # EXECUTION phase: a collective that fails mid-flight errors on
            # every participant (they are all inside the same program), so
            # per-link containment here keeps the walk in lockstep.
            try:
                t0 = time.perf_counter()
                np.asarray(fn(x))  # warmup, host-fenced (compile on first cycle)
                compile_s += time.perf_counter() - t0
                rtt_min, rtt_mean, correct = _timed_pair(fn, x, expected, iters, inner_iters)
            except Exception as exc:  # noqa: BLE001 — lockstep preservation
                logger.warning("Link probe %s failed: %s", name, exc)
                observed.append(LinkResult(
                    axis=axis, name=name, device_ids=(dev_a.id, dev_b.id),
                    rtt_ms=-1.0, rtt_mean_ms=-1.0, correct=False,
                    owner=owner, error=str(exc),
                ))
                continue
            observed.append(LinkResult(
                axis=axis, name=name, device_ids=(dev_a.id, dev_b.id),
                rtt_ms=1e3 * rtt_min, rtt_mean_ms=1e3 * rtt_mean,
                correct=correct, owner=owner,
            ))
        compile_ms = 1e3 * compile_s
        # cross-process links are executed by BOTH endpoint processes (they
        # must run in lockstep); the lower-indexed endpoint owns the
        # canonical record, so a host-level merge counts each edge once —
        # but suspect analysis below uses EVERYTHING this process observed,
        # or a slow chip whose links are owned by different processes would
        # never accumulate the >=2 suspect links triangulation needs
        results = [r for r in observed if r.owner]

        valid = [r.rtt_ms for r in observed if r.rtt_ms >= 0]
        median = float(np.median(valid)) if valid else -1.0
        suspects, suspect_devices = classify_links(observed, rtt_factor, rtt_floor_ms)

        if suspects:
            logger.warning(
                "Link probe: %d/%d suspect links (median %.3f ms): %s; suspect devices: %s",
                len(suspects), len(observed), median,
                [s["name"] for s in suspects], suspect_devices,
            )
        return LinkProbeResult(
            ok=not suspects,
            n_links=len(results),
            n_observed=len(observed),
            median_rtt_ms=median,
            links=results,
            suspect_links=suspects,
            suspect_devices=suspect_devices,
            compile_ms=compile_ms,
        )
    except Exception as exc:
        logger.error("Link probe failed: %s", exc)
        return LinkProbeResult(
            ok=False, n_links=0, n_observed=0, median_rtt_ms=-1.0, links=[],
            suspect_links=[], suspect_devices=[], compile_ms=0.0, error=str(exc),
        )
