"""Merged global fleet view: N upstream clusters folded into ONE FleetView.

The federation plane does not grow a second serving stack — it REUSES
the one that already exists. Each upstream's objects land in the local
``FleetView`` under a namespaced key, ``(kind, "<cluster>/<key>")``, so
everything built on the view comes along for free: the encode-once
broadcast fan-out serves the global view to 10k subscribers, the history
WAL persists it (global resume tokens survive federator restarts), and
``?at=`` time travel reconstructs the GLOBAL fleet as of any retained rv.

Semantics:

- **Keying**: ``(cluster, kind, key) -> (kind, "cluster/key")``. Merged
  objects carry ``cluster`` and ``origin_key`` fields; ``key`` is the
  global key (consistent with the view's objects-carry-their-key
  convention). Cluster names cannot collide with local objects because
  local producers never put ``/`` in a pod uid / slice name.
- **Global rv line**: the local view's own dense monotonic rv. It
  guarantees total order of APPLICATION (and per-(cluster,key) order,
  because one upstream subscriber applies its deltas in upstream rv
  order) — it does NOT encode cross-cluster happens-before; two
  clusters' concurrent transitions interleave in arrival order.
- **Epochs**: each upstream's ``view`` instance id is its epoch. A
  changed epoch (upstream restarted into a fresh rv space) or any 410
  resync funnels through ``reset_cluster`` — a full-snapshot reconcile:
  upsert everything current, delete what vanished. The FleetView dedups
  identical upserts (no rv burn), so a clean reconcile after a blip
  costs exactly the deltas that actually happened.
- **Stale-vs-drop** (``federation.drop_stale``): when an upstream goes
  dark past ``stale_after_seconds``, ``drop_stale: true`` deletes its
  objects from the global view (consumers see only live state; the
  subscriber is invalidated so recovery re-snapshots them back in).
  The default (``false``) KEEPS last-known state — cheap (zero rv
  churn on a blip) and usually right for fleet dashboards — with the
  staleness surfaced per upstream in /healthz and the
  ``federation_upstream_stale`` gauge, not rewritten into every object.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Set, Tuple

from k8s_watcher_tpu.federate.client import DELETE

#: separator between the cluster name and the upstream key in a global key
CLUSTER_SEP = "/"


def global_key(cluster: str, key: str) -> str:
    return f"{cluster}{CLUSTER_SEP}{key}"


def split_global_key(gkey: str) -> Tuple[str, str]:
    """``(cluster, upstream_key)`` — inverse of ``global_key``."""
    cluster, _, key = gkey.partition(CLUSTER_SEP)
    return cluster, key


def merged_equals_union(merged_objects, upstream_objects: Dict[str, Any]) -> bool:
    """The federation gates' convergence check, in ONE place (bench and
    the smoke both gate on it): the merged view's federated objects must
    equal the union of the upstream snapshots under cluster-prefixed
    keys, with the decoration the merge adds (the rewritten ``key``;
    ``cluster``/``origin_key`` are additive) excluded from the compare.

    ``merged_objects``: the federator snapshot's object list (non-
    federated local objects are ignored). ``upstream_objects``: mapping
    of cluster name -> that upstream snapshot's object list."""
    expected = {}
    for cluster, objects in upstream_objects.items():
        for obj in objects:
            expected[(obj["kind"], global_key(cluster, obj["key"]))] = obj
    merged = {
        (obj["kind"], obj["key"]): obj for obj in merged_objects if obj.get("cluster")
    }
    if merged.keys() != expected.keys():
        return False
    return all(
        all(merged[k].get(field) == v for field, v in exp.items() if field != "key")
        for k, exp in expected.items()
    )


class GlobalMerge:
    """Write-side fold of upstream events into the shared FleetView.

    One upstream subscriber thread per cluster calls in; the per-cluster
    key registry is lock-guarded so ``object_count``/health reads and the
    monitor thread's ``drop_cluster`` stay consistent with it. The
    FleetView does its own locking — per-key last-writer-wins is exactly
    the state-serving contract."""

    def __init__(self, view, *, drop_stale: bool = False, metrics=None):
        self.view = view
        self.drop_stale = drop_stale
        self._lock = threading.Lock()
        self._keys: Dict[str, Set[Tuple[str, str]]] = {}  # cluster -> {(kind, upstream key)}
        # running registry size, maintained incrementally on every
        # add/discard/reset/drop: the merged-object gauge used to
        # recompute sum(len(k)) per DELTA — O(clusters) work inside the
        # fan-in hot path for a number that only moves by what the
        # mutation itself changed
        self._count = 0
        self._merged_gauge = (
            metrics.gauge("federation_merged_objects") if metrics is not None else None
        )

    def _set_gauge_locked(self) -> None:
        if self._merged_gauge is not None:
            self._merged_gauge.set(self._count)

    def seed_from_view(self) -> int:
        """Adopt federated objects ALREADY in the view (a history-recovered
        federator restart): the per-cluster key registry must mirror the
        recovered view, or the first reconcile cannot delete objects that
        vanished upstream during the outage (ghost objects served forever),
        ``drop_cluster`` pops an empty set, and the merged-object gauge
        reads 0 against a populated view. Returns the seeded count.

        On a columnar view this reads ``federated_keys()`` — cluster
        membership answered off the int cluster column, no O(fleet)
        object reconstruction just to drop all but the ``cluster`` and
        ``key`` fields. The origin key is recovered from the global key
        (``_decorate`` mints ``origin_key == split_global_key(key)[1]``,
        so the derivation is exact for anything it decorated). The dict
        core walks objects as before."""
        seeded = 0
        if hasattr(self.view, "federated_keys"):
            with self._lock:
                for kind, gkey, cluster in self.view.federated_keys():
                    _, origin = split_global_key(gkey)
                    if not origin:
                        continue
                    keys = self._keys.setdefault(cluster, set())
                    entry = (kind or "pod", origin)
                    if entry not in keys:
                        keys.add(entry)
                        self._count += 1
                    seeded += 1
                self._set_gauge_locked()
            return seeded
        _, objects = self.view.snapshot()
        with self._lock:
            for obj in objects:
                cluster = obj.get("cluster")
                origin = obj.get("origin_key")
                if not cluster or not origin:
                    continue  # the local watcher's own (non-federated) objects
                keys = self._keys.setdefault(cluster, set())
                entry = (obj.get("kind") or "pod", origin)
                if entry not in keys:
                    keys.add(entry)
                    self._count += 1
                seeded += 1
            self._set_gauge_locked()
        return seeded

    @staticmethod
    def _decorate(cluster: str, kind: str, key: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        return {**obj, "kind": kind, "key": global_key(cluster, key),
                "cluster": cluster, "origin_key": key}

    def reset_cluster(self, cluster: str, objects) -> int:
        """Adopt a full upstream snapshot (initial connect, epoch change,
        every 410 resync): upsert all current objects, delete the global
        keys that vanished — ONE registry-lock acquisition and ONE view
        publish-lock hold for the whole reconcile. Returns the number of
        view deltas actually minted (identical upserts are free, so a
        clean reconcile after a blip costs exactly the real deltas)."""
        fresh: Set[Tuple[str, str]] = set()
        items: list = []
        for obj in objects:
            kind = obj.get("kind") or "pod"
            key = obj.get("key")
            if not key:
                continue
            fresh.add((kind, key))
            items.append((kind, global_key(cluster, key),
                          self._decorate(cluster, kind, key, obj)))
        with self._lock:
            stale = self._keys.get(cluster, set()) - fresh
            self._count += len(fresh) - len(self._keys.get(cluster, ()))
            self._keys[cluster] = fresh
            self._set_gauge_locked()
        items.extend((kind, global_key(cluster, key), None) for kind, key in stale)
        return self.view.apply_batch(items)

    @staticmethod
    def _origin_stamp(item: Dict[str, Any]):
        """The upstream frame's negotiated freshness stamp (origin wall
        time), propagated into the merged view's Delta so the global rv
        line — and any federator federating THIS one — keeps measuring
        true end-to-end age. None when the upstream didn't stamp."""
        ts = item.get("ts")
        return ts[0] if isinstance(ts, (list, tuple)) and ts else None

    @staticmethod
    def _wire_trace(item: Dict[str, Any]):
        """The upstream frame's negotiated ``trace`` field (the compact
        journey dict, already augmented by the trace collector with this
        hop's serve_wire span), propagated into the merged Delta so the
        GLOBAL view's republished frames keep the trace identity — a
        second-tier federator joins the next hop from it. None when the
        upstream didn't trace (the unsampled 255/256)."""
        trace = item.get("trace")
        return trace if isinstance(trace, dict) else None

    def apply_delta(self, cluster: str, item: Dict[str, Any]) -> bool:
        """Fold one wire delta (UPSERT/DELETE frame dict) from ``cluster``.
        Returns True when the global view actually changed. The per-delta
        shape — one publish-lock hold, one wakeup, one registry-lock
        acquisition per frame; ``apply_batch`` is the amortized path the
        subscriber loop feeds (this stays as the bench's per-delta-apply
        baseline and the one-off-mutation convenience)."""
        kind = item.get("kind") or "pod"
        key = item["key"]
        gkey = global_key(cluster, key)
        ts_wall = self._origin_stamp(item)
        trace = self._wire_trace(item)
        if item["type"] == DELETE:
            changed = self.view.apply(kind, gkey, None, ts_wall=ts_wall, trace=trace)
            with self._lock:
                keys = self._keys.setdefault(cluster, set())
                if (kind, key) in keys:
                    keys.discard((kind, key))
                    self._count -= 1
                self._set_gauge_locked()
            return changed
        changed = self.view.apply(
            kind, gkey, self._decorate(cluster, kind, key, item.get("object") or {}),
            ts_wall=ts_wall, trace=trace,
        )
        with self._lock:
            keys = self._keys.setdefault(cluster, set())
            if (kind, key) not in keys:
                keys.add((kind, key))
                self._count += 1
            self._set_gauge_locked()
        return changed

    def apply_batch(self, cluster: str, items) -> int:
        """Fold one decoded wire-frame batch (the subscriber loop hands
        over whatever one chunked read carried) under ONE registry-lock
        acquisition and ONE view publish-lock hold — the fan-in analogue
        of the pipeline's ``publish_batch``. Frames apply in wire order,
        so per-(cluster,key) last-writer-wins is preserved; the view
        dedups identical upserts exactly as the per-delta path does.
        Returns the number of global-view deltas minted."""
        view_items: list = []
        for item in items:
            kind = item.get("kind") or "pod"
            key = item["key"]
            ts_wall = self._origin_stamp(item)
            trace = self._wire_trace(item)
            if item["type"] == DELETE:
                view_items.append((kind, global_key(cluster, key), None, ts_wall, trace))
            else:
                view_items.append((kind, global_key(cluster, key),
                                   self._decorate(cluster, kind, key, item.get("object") or {}),
                                   ts_wall, trace))
        changed = self.view.apply_batch(view_items)
        with self._lock:
            keys = self._keys.setdefault(cluster, set())
            before = len(keys)
            for item, (kind, _gkey, obj, _ts, _tr) in zip(items, view_items):
                entry = (kind, item["key"])
                if obj is None:
                    keys.discard(entry)
                else:
                    keys.add(entry)
            self._count += len(keys) - before
            self._set_gauge_locked()
        return changed

    def apply_view_batch(self, cluster: str, view_items) -> int:
        """Fold a batch of ALREADY-PREPARED view items from ``cluster`` —
        the sharded fan-in's parent-side sequencer path. A merge worker
        did the per-frame work (decode, re-key, decorate, stamp/trace
        extraction, optional raw-frame passthrough) in its own process;
        items arrive as ``(kind, global_key, obj_or_None, ts_wall, trace,
        frame_bytes_or_None)`` and go straight into ONE view publish-lock
        hold. The parent keeps the ONLY key registry (it must survive
        worker respawns, or reconciles could never delete ghosts), so the
        registry fold happens here, from the global keys. Returns the
        number of global-view deltas minted."""
        changed = self.view.apply_batch(view_items)
        with self._lock:
            keys = self._keys.setdefault(cluster, set())
            before = len(keys)
            for item in view_items:
                kind, gkey, obj = item[0], item[1], item[2]
                entry = (kind, split_global_key(gkey)[1])
                if obj is None:
                    keys.discard(entry)
                else:
                    keys.add(entry)
            self._count += len(keys) - before
            self._set_gauge_locked()
        return changed

    def drop_cluster(self, cluster: str) -> int:
        """The ``drop_stale: true`` policy arm: remove a dark upstream's
        objects from the global view (one batched publish). Returns
        deltas minted."""
        with self._lock:
            keys = self._keys.pop(cluster, set())
            self._count -= len(keys)
            self._set_gauge_locked()
        return self.view.apply_batch(
            [(kind, global_key(cluster, key), None) for kind, key in keys]
        )

    def cluster_object_count(self, cluster: str) -> int:
        with self._lock:
            return len(self._keys.get(cluster, ()))

    def object_count(self) -> int:
        with self._lock:
            return self._count

    def snapshot_cluster(self, cluster: str) -> Optional[Set[Tuple[str, str]]]:
        with self._lock:
            keys = self._keys.get(cluster)
            return set(keys) if keys is not None else None
