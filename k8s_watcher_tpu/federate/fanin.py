"""Sharded federation fan-in: merge workers in supervised OS processes.

BENCH_r06 pinned the federation merge path at an ~18x gap between what
one interpreter folds (~15k merged deltas/s across 16 upstreams, GIL-
bound in decode + re-key + re-encode) and what the upstreams can emit.
This module is the ingest tier's PR-15 answer applied to the fan-in
(the shared supervision wire lives in ``parallel/procpool``):

- ``federation.processes`` merge WORKER processes each own a disjoint
  partition of the upstream list (``shard_of(cluster_name, processes)``
  — whole upstreams per worker, so per-(cluster, key) apply order is
  preserved end to end: one upstream -> one subscriber thread -> one
  FIFO pipe -> one parent fold slot);
- each worker runs full ``FleetSubscriber`` resume-protocol consumers
  for its upstreams (snapshot, streamed deltas, heartbeat staleness,
  410 resync, jittered backoff, durable per-upstream resume tokens —
  the SAME token files the in-process plane uses, so flipping the knob
  either way resumes instead of relisting) and does ALL per-frame work
  in its own interpreter: decode, re-key to ``cluster/key``, decorate,
  freshness-stamp extraction;
- **raw-frame passthrough** (the PR-14 relay idea, extended to re-keyed
  fan-in): a JSON upstream frame whose re-keying needs nothing beyond
  the cluster prefix is rewritten ON THE RAW BYTES — strip the
  negotiated ``ts`` tail, swap both ``"key"`` occurrences for the
  global key, append the ``cluster``/``origin_key`` decoration inside
  the object — and shipped beside the decoded control fields, so the
  parent view journals the worker's bytes (rv spliced in place) and
  never re-encodes: the encode-once invariant now holds ACROSS the
  process boundary (``fanin_passthrough_frames`` counts the hits; an
  ineligible frame falls back to the decoded path, never to a wrong
  frame);
- merged deltas ride the length-prefixed pipe as seq'd batches into the
  parent's thin sequencer (``ShardedFanin``), which dedups the crash-
  replay window against a per-cluster ``(epoch, upstream rv)``
  watermark and feeds ``GlobalMerge.apply_view_batch`` — ONE view
  publish-lock hold per pipe batch, in dense-rv order;
- workers are SUPERVISED (``parallel.procpool.SupervisedEndpoint``): a
  killed worker respawns with jittered exponential backoff and resumes
  every owned upstream from its durable token — at-least-once across
  the crash window on the wire, exactly-once into the view via the
  parent watermark (the bench's gapless kill/respawn gate);
- SIGTERM drains cleanly: stop the subscribers (their exit path
  persists the EXACT live token position), final stats, EOS.

Staleness ownership (explicit, so a sharded deploy never double-reports
``federation_upstream_stale``): with ``processes > 0`` the WORKER owns
the per-upstream staleness verdict and the drop-stale arm — it is the
process holding the live subscriber clocks — and ships verdicts in its
stats frames; the parent plane only MIRRORS them into gauges/health.
With ``processes: 0`` the plane's monitor tick owns both, unchanged.

Codec note: merge workers pin their upstream wire to JSON — the
passthrough currency is the serve plane's JSON line, and a worker's
decode cost is paid off the parent's interpreter either way. The
``federation.codec`` knob keeps governing the in-process path.

``federation.processes: 0`` never constructs any of this — the
in-process fan-in is untouched.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from k8s_watcher_tpu.config.schema import metric_safe_name as _metric_suffix
from k8s_watcher_tpu.federate.client import (
    CODEC_JSON,
    DELETE,
    FleetClient,
    FleetSubscriber,
    ResyncRequired,
    Snapshot,
    TokenStore,
)
from k8s_watcher_tpu.federate.merge import GlobalMerge, global_key
from k8s_watcher_tpu.parallel.procpool import SupervisedEndpoint, pack, unpack
from k8s_watcher_tpu.watch.sharded import shard_of

logger = logging.getLogger(__name__)

try:  # the serve plane's optional codec dependency, reused for the wire
    import msgpack  # type: ignore
except Exception:  # noqa: BLE001 — absence is a supported configuration
    msgpack = None


def _pack(obj: Dict[str, Any]) -> bytes:
    return pack(obj, codec=msgpack)


def _unpack(data: bytes) -> Dict[str, Any]:
    return unpack(data, codec=msgpack)


# -- raw-frame passthrough rewrite -------------------------------------------
#
# The upstream serve frame is the PR-4 golden JSON line (default
# ``json.dumps`` separators, trailing newline) with field order fixed by
# ``Delta.to_wire``: type, rv, kind, key, [object], [ts] (workers
# negotiate fresh=1, trace off — ts, when present, is the LAST field).
# What a single-process merge would encode for the same delta is the
# same line with (a) its own rv (the parent view splices that in at
# apply time — ``serve.view.splice_frame_rv``), (b) the global key at
# BOTH the frame level and inside the object, (c) no ts tail (the base
# JSON variant carries none; negotiated variants re-add it lazily from
# the journaled stamp), and (d) ``cluster``/``origin_key`` appended at
# the END of the object (``GlobalMerge._decorate`` is a dict-update:
# kind/key keep their original positions when the object already
# carries them — the eligibility condition — and the two new fields
# append). All four are byte-local rewrites; anything else falls back
# to the decoded path.

#: the negotiated freshness tail: ``, "ts": [<floats>]}\n`` at end of line
_TS_TAIL = re.compile(rb', "ts": \[[-+eE0-9., ]*\]\}\n$')


def strip_ts_tail(raw: bytes) -> Optional[bytes]:
    """Drop the negotiated ``ts`` tail from a raw JSON frame line (the
    base frame variant the view journals carries none). Returns the
    line unchanged when no tail is present, None when a ``"ts"`` field
    exists but not in the recognized tail position (unknown producer —
    fall back to the decoded path rather than guess)."""
    m = _TS_TAIL.search(raw)
    if m is not None:
        return raw[: m.start()] + b"}\n"
    if b'"ts":' in raw or b'"ts" :' in raw:
        return None
    return raw


def rewrite_passthrough(
    raw: bytes,
    *,
    cluster: str,
    kind: str,
    key: str,
    obj: Optional[Dict[str, Any]],
) -> Optional[bytes]:
    """Rewrite one upstream JSON frame line into the byte-identical
    frame a single-process merge would have encoded (modulo rv, which
    the view splices at apply time). Returns None whenever ANY
    eligibility check fails — the caller then takes the decoded
    re-encode path; passthrough is an optimization, never a different
    answer.

    What this does NOT re-validate: the frame's JSON well-formedness
    beyond the rewritten spans (the upstream's serve plane encoded it;
    the subscriber's decoder already parsed it for control fields) and
    the object's interior semantics — the bytes between the rewrite
    points pass through verbatim, which is the point.
    """
    if not raw.startswith(b"{"):
        return None  # not a JSON line (codec downgrade mid-window)
    out = strip_ts_tail(raw)
    if out is None:
        return None
    needle = b'"key": ' + json.dumps(key).encode()
    if obj is None:
        expected = 1  # DELETE: frame-level key only
    else:
        # UPSERT: the object must already carry the view convention
        # (kind/key fields matching the frame) so the decorated dict's
        # field ORDER equals a plain append, and must not already be
        # decorated (a federator federating a federator re-keys for
        # real — decoded path)
        if (
            obj.get("key") != key
            or obj.get("kind") != kind
            or "cluster" in obj
            or "origin_key" in obj
        ):
            return None
        if not out.endswith(b"}}\n"):
            return None
        expected = 2  # frame level + object level
    if out.count(needle) != expected:
        return None  # a nested value coincides with the needle — bail
    out = out.replace(needle, b'"key": ' + json.dumps(global_key(cluster, key)).encode())
    if obj is not None:
        out = (
            out[:-3]
            + b', "cluster": '
            + json.dumps(cluster).encode()
            + b', "origin_key": '
            + json.dumps(key).encode()
            + b"}}\n"
        )
    return out


# -- worker plan -------------------------------------------------------------


@dataclasses.dataclass
class FaninPlan:
    """Everything one merge-worker process needs, picklable for spawn.

    ``client_factory`` is the test seam: a MODULE-LEVEL callable
    ``factory(plan, upstream_cfg) -> FleetClient`` replacing the
    production construction (it must be picklable). Production plans
    carry ``config`` (the frozen FederationConfig) and derive clients
    from it; the bench needs no seam — its upstreams are real HTTP
    serve planes.
    """

    proc_index: int
    processes: int
    owned: Tuple[str, ...]  # upstream names this worker folds
    config: Any = None  # config.schema.FederationConfig
    token_dir: Optional[str] = None
    stats_interval_seconds: float = 0.5
    client_factory: Optional[Callable[["FaninPlan", Any], Any]] = None
    #: spawn generation, stamped by the parent at each (re)spawn and
    #: echoed on every stats frame ("g") so stale frames are discarded
    generation: int = 0
    #: ship the worker registry's sample() (+ anomaly traces) on the
    #: periodic stats frame (``metrics.process_export``)
    export_registry: bool = True


def fanin_plans(
    config,
    token_dir: Optional[str] = None,
    *,
    process_export: bool = True,
) -> List[FaninPlan]:
    """Partition the upstream list across ``federation.processes``
    workers by ``shard_of(cluster_name, processes)`` — a pure function
    of (name, processes), so a worker always finds its upstreams' token
    FILES (keyed by upstream name, shared with the in-process plane)
    even after ``processes`` changes. Workers that own no upstream are
    not spawned (processes > upstream count is a legal, wasteful
    config; an idle process would add nothing but a pipe)."""
    plans = [
        FaninPlan(
            proc_index=p,
            processes=config.processes,
            owned=tuple(
                u.name
                for u in config.upstreams
                if shard_of(u.name, config.processes) == p
            ),
            config=config,
            token_dir=token_dir,
            export_registry=process_export,
        )
        for p in range(config.processes)
    ]
    return [plan for plan in plans if plan.owned]


def token_path(token_dir: str, name: str) -> str:
    """One upstream's durable resume-token file — the SAME path the
    in-process plane's ``token_store_for`` uses, so flipping
    ``federation.processes`` either way resumes instead of relisting."""
    return os.path.join(token_dir, f"{_metric_suffix(name)}.token")


# -- worker process ----------------------------------------------------------


class _PipeShip:
    """Serialized pipe writes with a SHARED item-seq across this
    worker's upstream subscriber threads (the SupervisedEndpoint seq
    tripwire needs one monotonic line per pipe). A broken pipe (parent
    died) latches ``broken`` instead of raising into the subscriber
    loops — the main loop notices and exits; tokens are already
    durable."""

    def __init__(self, conn):
        self._conn = conn
        self._lock = threading.Lock()
        self._seq = 0
        self.broken = threading.Event()

    def _send(self, msg: Dict[str, Any]) -> None:
        try:
            self._conn.send_bytes(_pack(msg))
        except (BrokenPipeError, OSError):
            self.broken.set()

    def payload(self, msg: Dict[str, Any], items: int) -> None:
        with self._lock:
            msg["s"] = self._seq
            self._seq += items
            self._send(msg)

    def control(self, msg: Dict[str, Any]) -> None:
        with self._lock:
            self._send(msg)


class _UpstreamPump:
    """One owned upstream inside a merge worker: the full resume-
    protocol subscriber in raw mode, folding each delivered run into a
    seq'd pipe batch of prepared view items (+ passthrough bytes when
    eligible). The worker's staleness tick reads the clocks here."""

    def __init__(
        self, plan: FaninPlan, cfg, ship: _PipeShip, index: int, registry=None
    ):
        import random

        self.cfg = cfg
        self.name = cfg.name
        self.ship = ship
        self.epoch: Optional[str] = None
        self.epoch_changes = 0
        self.stale = False
        self.dropped = False
        self.lag_since: Optional[float] = None
        self.passthrough = 0  # eligible frames shipped as raw bytes
        self.deltas = 0
        # worker-registry counters under WORKER-ONLY names: the parent
        # owns federation_deltas_applied (post-dedup) and
        # fanin_passthrough_frames (ad-hoc fold of stats["passthrough"]),
        # so the exported sample must never reuse those names or the
        # unlabeled rollup would double-count
        self._deltas_shipped = (
            registry.counter("federation_worker_deltas_shipped").labels(
                cluster=self.name
            )
            if registry is not None
            else None
        )
        self._raw_passthrough = (
            registry.counter("federation_worker_passthrough_frames").labels(
                cluster=self.name
            )
            if registry is not None
            else None
        )
        # same role as the in-process plane's per-upstream drop_lock:
        # serializes the drop decision against this subscriber thread's
        # snapshot-reconcile/delta-ship, and — because every ship
        # happens INSIDE it — makes pipe order match flag order
        self.drop_lock = threading.Lock()
        fed = plan.config
        if plan.client_factory is not None:
            self.client = plan.client_factory(plan, cfg)
        else:
            # JSON pinned: the passthrough currency is the serve
            # plane's JSON line (see module docstring)
            self.client = FleetClient(
                cfg.url,
                token=cfg.token,
                timeout=max(5.0, fed.stale_after_seconds),
                codec=CODEC_JSON,
                fresh=True,
            )
        store = (
            TokenStore(token_path(plan.token_dir, self.name))
            if plan.token_dir
            else None
        )
        self.resumed = store is not None and store.load() is not None
        self.subscriber = FleetSubscriber(
            self.client,
            on_snapshot=self._on_snapshot,
            on_raw_batch=self._on_raw_batch,
            token_store=store,
            stale_after_seconds=fed.stale_after_seconds,
            backoff_seconds=fed.resync_backoff_seconds,
            rng=random.Random((os.getpid() << 8) ^ index),
            name=self.name,
        )
        self.thread = threading.Thread(
            target=self.subscriber.run, name=f"fanin-{self.name}", daemon=True
        )

    # -- subscriber callbacks (subscriber thread) ---------------------------

    def _on_snapshot(self, snap: Snapshot) -> None:
        if self.epoch is not None and snap.view != self.epoch:
            self.epoch_changes += 1
            logger.warning(
                "Fan-in upstream %s changed view epoch %s -> %s (restart); reconciling",
                self.name, self.epoch, snap.view,
            )
        self.epoch = snap.view
        with self.drop_lock:
            self.dropped = False
            # full-reconcile hand-off: raw upstream objects; the parent
            # runs reset_cluster (decorate + delete-the-vanished) with
            # its authoritative key registry
            self.ship.payload(
                {
                    "c": self.name,
                    "e": snap.view,
                    "w": snap.rv,
                    "r": 1,
                    "b": snap.objects,
                },
                len(snap.objects),
            )

    def _on_raw_batch(self, pairs) -> None:
        with self.drop_lock:
            if self.dropped:
                # objects dropped while this stream stalled but stayed
                # open: a delta-only resume would leave every untouched
                # object missing — force the full reconcile
                raise ResyncRequired(
                    "objects dropped while stale; re-snapshot to reconcile"
                )
            json_wire = (
                msgpack is not None  # JSON-fallback pipe cannot carry bytes
                and self.client.active_codec == CODEC_JSON
            )
            items = []
            for frame, raw in pairs:
                kind = frame.get("kind") or "pod"
                key = frame["key"]
                ts = frame.get("ts")
                obj = None if frame["type"] == DELETE else (frame.get("object") or {})
                rewritten = (
                    rewrite_passthrough(
                        raw, cluster=self.name, kind=kind, key=key, obj=obj
                    )
                    if json_wire
                    else None
                )
                if rewritten is not None:
                    self.passthrough += 1
                    if self._raw_passthrough is not None:
                        self._raw_passthrough.inc()
                items.append(
                    [
                        kind,
                        global_key(self.name, key),
                        None
                        if obj is None
                        else GlobalMerge._decorate(self.name, kind, key, obj),
                        ts[0] if ts else None,
                        frame.get("trace") if isinstance(frame.get("trace"), dict) else None,
                        frame["rv"],
                        rewritten,
                    ]
                )
            self.deltas += len(items)
            if self._deltas_shipped is not None and items:
                self._deltas_shipped.inc(len(items))
            self.ship.payload(
                {"c": self.name, "e": self.subscriber.view, "b": items}, len(items)
            )

    # -- worker tick (main thread) ------------------------------------------

    def drop(self) -> None:
        """The drop-stale arm, worker-owned: flag (so an in-between
        delta forces a reconcile), invalidate (so the next (re)connect
        re-snapshots the objects back in), tell the parent to delete."""
        with self.drop_lock:
            self.dropped = True
            self.subscriber.invalidate()
            self.ship.payload({"c": self.name, "drop": 1, "b": []}, 0)

    def status(self) -> Dict[str, Any]:
        sub = self.subscriber
        body = sub.status()
        now = time.monotonic()
        lag_rv = max(0, sub.wire_rv - (sub.rv or 0))
        if lag_rv > 0:
            if self.lag_since is None:
                self.lag_since = now
        else:
            self.lag_since = None
        body.update(
            {
                "url": self.cfg.url,
                "stale": self.stale,
                "epoch": self.epoch,
                "epoch_changes": self.epoch_changes,
                "dropped": self.dropped,
                "lag_rv": lag_rv,
                "oldest_unpropagated_seconds": (
                    round(now - self.lag_since, 3) if self.lag_since is not None else 0.0
                ),
                "thread_alive": self.thread.is_alive(),
                "passthrough": self.passthrough,
                "deltas": self.deltas,
            }
        )
        return body


def _fanin_worker_entry(plan: FaninPlan, conn) -> None:
    """Child-process main: owned upstream subscribers -> seq'd pipe
    batches, plus the worker-owned staleness tick. SIGTERM stops the
    subscribers (their exit path persists the exact live tokens) and
    sends EOS; an unexpected death is the parent's respawn path (the
    durable tokens make the respawn resume, not relist)."""
    logging.basicConfig(
        level=logging.INFO,
        format=(
            f"%(asctime)s [fanin-worker-{plan.proc_index}] "
            "%(levelname)s %(name)s: %(message)s"
        ),
    )
    ship = _PipeShip(conn)
    registry = None
    tracer = None
    trace_export = None
    if plan.export_registry:
        # worker-side observability: a registry whose sample() rides the
        # stats frame, plus an anomaly-only tracer (sample_rate=0 — the
        # merge path has no per-event journey to head-sample; staleness
        # and drop verdicts are the anomalies worth shipping)
        import collections

        from k8s_watcher_tpu.metrics import MetricsRegistry
        from k8s_watcher_tpu.trace.trace import Tracer

        registry = MetricsRegistry()
        trace_export = collections.deque(maxlen=64)
        tracer = Tracer(
            sample_rate=0, ring_size=64, metrics=registry,
            export_buffer=trace_export,
        )
    owned = {u.name: u for u in plan.config.upstreams}
    pumps = [
        _UpstreamPump(plan, owned[name], ship, index, registry=registry)
        for index, name in enumerate(plan.owned)
    ]
    stopping = threading.Event()

    def on_sigterm(signum, frame):  # noqa: ARG001 — signal signature
        stopping.set()

    signal.signal(signal.SIGTERM, on_sigterm)
    signal.signal(signal.SIGINT, signal.SIG_IGN)  # parent Ctrl-C drains via SIGTERM

    ship.control(
        {
            "hello": {
                "proc": plan.proc_index,
                "pid": os.getpid(),
                "clusters": [p.name for p in pumps],
                "resumed": [p.name for p in pumps if p.resumed],
            }
        }
    )
    for pump in pumps:
        pump.thread.start()

    stale_threshold = max(3.0, plan.config.stale_after_seconds)
    tick = max(0.1, min(1.0, stale_threshold / 4.0))
    started_t = time.monotonic()
    last_stats = started_t

    def stats_payload() -> Dict[str, Any]:
        stats: Dict[str, Any] = {
            "upstreams": {p.name: p.status() for p in pumps},
            "passthrough": sum(p.passthrough for p in pumps),
            "deltas": sum(p.deltas for p in pumps),
        }
        if registry is not None:
            stats["registry"] = registry.sample(include_series=True)
        if trace_export is not None:
            drained = []
            while True:
                try:
                    drained.append(trace_export.popleft())
                except IndexError:
                    break
            if drained:
                stats["traces"] = drained
        return {"stats": stats, "g": plan.generation}

    try:
        while not stopping.is_set() and not ship.broken.is_set():
            stopping.wait(tick)
            if stopping.is_set():
                break
            now = time.monotonic()
            grace_over = now - started_t > stale_threshold
            for pump in pumps:
                age = pump.subscriber.last_frame_age()
                fresh = age is not None and age <= stale_threshold
                if fresh:
                    pump.stale = False
                elif grace_over or age is not None:
                    if not pump.stale:
                        pump.stale = True
                        logger.warning(
                            "Fan-in upstream %s went stale (last frame %s ago)",
                            pump.name, f"{age:.1f}s" if age is not None else "never",
                        )
                        if tracer is not None:
                            # always-captured anomaly, queryable at the
                            # PARENT's /debug/trace?uid=<upstream name>
                            # once it rides the next stats frame
                            trace = tracer.start_anomaly(
                                uid=pump.name, name=pump.name,
                                kind="upstream", t0=now,
                            )
                            if trace is not None:
                                tracer.finish(trace, "stale")
                    if plan.config.drop_stale and not pump.dropped:
                        age_now = pump.subscriber.last_frame_age()
                        if age_now is None or age_now > stale_threshold:
                            pump.drop()
                            logger.warning(
                                "Dropped stale upstream %s from the global view",
                                pump.name,
                            )
                            if tracer is not None:
                                trace = tracer.start_anomaly(
                                    uid=pump.name, name=pump.name,
                                    kind="upstream", t0=now,
                                )
                                if trace is not None:
                                    tracer.finish(trace, "dropped")
            if now - last_stats >= plan.stats_interval_seconds:
                last_stats = now
                ship.control(stats_payload())
    finally:
        for pump in pumps:
            pump.subscriber.stop()
        for pump in pumps:
            pump.thread.join(timeout=5.0)
        if not ship.broken.is_set():
            ship.control(stats_payload())
            ship.control({"eos": True, "drained": stopping.is_set()})
        try:
            conn.close()
        except OSError:
            pass


# -- parent side -------------------------------------------------------------


class FaninEndpoint(SupervisedEndpoint):
    """One supervised merge-worker subprocess. Supervision (spawn/
    respawn/backoff/seq/hello/stats/EOS) is the shared
    ``parallel.procpool.SupervisedEndpoint``; this subclass folds the
    worker's cumulative stats — passthrough frames and the per-upstream
    subscriber counters — into parent-side totals across incarnations
    (a respawned worker's counters restart at zero; the registry's must
    not)."""

    #: per-upstream monotonic counters diff-synced into plane counters
    _SYNCED = (
        ("reconnects", "federation_reconnects"),
        ("resyncs", "federation_resyncs"),
        ("stalls", "federation_heartbeat_stalls"),
        ("snapshots", "federation_snapshots"),
    )

    def __init__(
        self,
        plan: FaninPlan,
        *,
        metrics=None,
        heartbeat=None,
        trace_ring=None,
        respawn_backoff: float = 0.5,
        respawn_backoff_max: float = 15.0,
    ):
        super().__init__(
            plan,
            target=_fanin_worker_entry,
            name=f"fanin-merge-{plan.proc_index}",
            index=plan.proc_index,
            metrics=metrics,
            heartbeat=heartbeat,
            respawn_backoff=respawn_backoff,
            respawn_backoff_max=respawn_backoff_max,
            gap_counter="fanin_wire_gaps",
            respawn_counter="fanin_worker_respawns",
            label="Merge worker",
            respawn_note="resume from per-upstream tokens",
            process_label=f"merge-worker-{plan.proc_index}",
            trace_ring=trace_ring,
        )
        self.passthrough_total = 0
        self._passthrough_seen = 0
        self.upstream_stats: Dict[str, Dict[str, Any]] = {}
        self._synced: Dict[str, Dict[str, int]] = {}

    def on_spawn(self) -> None:
        super().on_spawn()  # reset registry-fold watermarks
        self._passthrough_seen = 0  # per-incarnation cumulative counters
        self._synced = {}

    def on_stats(self, stats: Dict[str, Any]) -> None:
        super().on_stats(stats)  # fold exported registry sample + traces
        passthrough = stats.get("passthrough")
        if passthrough is not None:
            delta = passthrough - self._passthrough_seen
            if delta > 0:
                self.passthrough_total += delta
                if self.metrics is not None:
                    self.metrics.counter("fanin_passthrough_frames").inc(delta)
            self._passthrough_seen = passthrough
        upstreams = stats.get("upstreams")
        if not isinstance(upstreams, dict):
            return
        self.upstream_stats.update(upstreams)
        if self.metrics is None:
            return
        for name, body in upstreams.items():
            synced = self._synced.setdefault(name, {})
            for field, counter in self._SYNCED:
                current = body.get(field)
                if current is None:
                    continue
                delta = current - synced.get(field, 0)
                if delta > 0:
                    self.metrics.counter(counter).inc(delta)
                    synced[field] = current


class ShardedFanin:
    """The parent-side sequencer: one pump thread per merge-worker
    endpoint drains its seq'd pipe batches into
    ``GlobalMerge.apply_view_batch`` / ``reset_cluster`` /
    ``drop_cluster``, deduping each worker's crash-replay window
    against a per-cluster ``(epoch, upstream rv)`` watermark — the
    durable token can trail the last shipped delta by up to a save
    cadence, so a respawned worker REPLAYS that window (at-least-once
    on the wire) and the watermark drops it (exactly-once into the
    view: zero gaps, zero dups through a kill).

    Clusters never migrate between workers at runtime (the partition is
    a pure function of the name), so one fold slot per cluster and
    per-(cluster, key) order holds without any cross-pipe sequencing.
    """

    def __init__(
        self,
        config,
        merge: GlobalMerge,
        *,
        metrics=None,
        token_dir: Optional[str] = None,
        resume_tokens_valid: bool = True,
        respawn_backoff: float = 0.5,
        heartbeat=None,
        trace_ring=None,
        process_export: bool = True,
    ):
        self.config = config
        self.merge = merge
        self.metrics = metrics
        self.token_dir = token_dir
        self.resume_tokens_valid = resume_tokens_valid
        self.endpoints = [
            FaninEndpoint(
                plan,
                metrics=metrics,
                heartbeat=heartbeat,
                trace_ring=trace_ring,
                respawn_backoff=respawn_backoff,
            )
            for plan in fanin_plans(config, token_dir, process_export=process_export)
        ]
        # cluster -> {"epoch": str, "urv": int}; single-writer per
        # cluster (its worker's pump thread), so no lock needed
        self._watermarks: Dict[str, Dict[str, Any]] = {}
        self._threads: List[threading.Thread] = []
        self.deltas_counter = metrics.counter("federation_deltas_applied") if metrics else None
        self.batches_counter = metrics.counter("federation_batches_applied") if metrics else None
        # end-to-end propagation stays measured at the FOLD (the moment
        # the delta reaches the global view) from the shipped origin
        # stamp; the serve-wire hop histogram is per-worker territory in
        # sharded mode and is not recorded here
        self.watch_to_global = (
            metrics.histogram("watch_to_global_view_seconds") if metrics else None
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ShardedFanin":
        if not self.resume_tokens_valid and self.token_dir:
            cleared = 0
            for u in self.config.upstreams:
                store = TokenStore(token_path(self.token_dir, u.name))
                store.clear()
                cleared += 1
            logger.warning(
                "Merged view did not restart cleanly on its prior rv line; "
                "cleared %d federation resume token(s) — merge workers will "
                "re-snapshot and reconcile", cleared,
            )
        for endpoint in self.endpoints:
            thread = threading.Thread(
                target=self._pump,
                args=(endpoint,),
                name=f"fanin-pump-{endpoint.index}",
                daemon=True,
            )
            thread.start()
            self._threads.append(thread)
        logger.info(
            "Sharded fan-in started: %d merge worker(s) over %d upstream(s) [%s]",
            len(self.endpoints),
            len(self.config.upstreams),
            "; ".join(
                f"worker {e.index}: {','.join(e.plan.owned)}" for e in self.endpoints
            ),
        )
        return self

    def stop(self, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        for endpoint in self.endpoints:
            endpoint.stop()  # SIGTERM: clean drain -> EOS
        for thread in self._threads:
            thread.join(timeout=max(0.1, deadline - time.monotonic()))
        for endpoint in self.endpoints:
            if time.monotonic() > deadline:
                endpoint.kill()  # a wedged worker cannot wedge the exit
        self._threads = []

    # -- the sequencer fold --------------------------------------------------

    def _pump(self, endpoint: FaninEndpoint) -> None:
        for msg in endpoint.frames():
            self._fold(msg)

    def _fold(self, msg: Dict[str, Any]) -> None:
        cluster = msg.get("c")
        if not cluster:
            return
        if msg.get("drop"):
            dropped = self.merge.drop_cluster(cluster)
            logger.warning(
                "Dropped %d stale object(s) of upstream %s from the global view "
                "(merge-worker verdict)", dropped, cluster,
            )
            return
        epoch = msg.get("e")
        if msg.get("r"):
            self.merge.reset_cluster(cluster, msg["b"])
            self._watermarks[cluster] = {"epoch": epoch, "urv": int(msg.get("w") or 0)}
            return
        items = msg["b"]
        if not items:
            return
        wm = self._watermarks.get(cluster)
        if wm is None or wm["epoch"] != epoch:
            # cold token-resume: no reset precedes the first batch —
            # adopt the epoch; the replay window (if any) re-applies,
            # which the view dedups exactly like an in-process
            # redelivery
            wm = self._watermarks[cluster] = {"epoch": epoch, "urv": 0}
        floor = wm["urv"]
        out = [
            (item[0], item[1], item[2], item[3], item[4], item[6])
            for item in items
            if item[5] > floor
        ]
        wm["urv"] = max(floor, items[-1][5])
        if not out:
            return  # the whole batch was crash-window replay
        self.merge.apply_view_batch(cluster, out)
        if self.deltas_counter is not None:
            self.deltas_counter.inc(len(out))
        if self.batches_counter is not None:
            self.batches_counter.inc()
        if self.watch_to_global is not None:
            now_wall = time.time()
            for item in out:
                if item[3] is not None:
                    self.watch_to_global.record(max(0.0, now_wall - item[3]))

    # -- surfaces ------------------------------------------------------------

    def upstream_report(self) -> Dict[str, Dict[str, Any]]:
        """Latest worker-reported per-upstream status (the staleness
        verdicts live HERE — satellite: the parent never recomputes
        them), keyed by upstream name. An upstream whose worker has not
        reported yet (startup, respawn backoff) is absent."""
        out: Dict[str, Dict[str, Any]] = {}
        for endpoint in self.endpoints:
            out.update(endpoint.upstream_stats)
        return out

    def workers_alive(self) -> bool:
        return all(thread.is_alive() for thread in self._threads)

    def worker_pids(self) -> List[Optional[int]]:
        return [endpoint.pid for endpoint in self.endpoints]

    def worker_stats(self) -> Dict[str, Any]:
        """Aggregated supervision counters (smoke/bench/debug)."""
        return {
            "processes": len(self.endpoints),
            "spawns": sum(e.spawns for e in self.endpoints),
            "respawns": sum(e.respawns for e in self.endpoints),
            "wire_gaps": sum(e.wire_gaps for e in self.endpoints),
            "deltas_delivered": sum(e.events_delivered for e in self.endpoints),
            "passthrough": sum(e.passthrough_total for e in self.endpoints),
            "hellos": [e.last_hello for e in self.endpoints],
        }

    def process_report(self) -> List[Dict[str, Any]]:
        """Per-worker supervision rows for ``/debug/processes``."""
        return [e.report() for e in self.endpoints]
