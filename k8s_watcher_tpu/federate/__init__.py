"""Multi-cluster federation plane: a first-class consumer of the serve
wire protocol (client), a merged global fleet view (merge), and the
fan-in plane that runs N upstream subscriptions and republishes through
the existing serving plane (plane). See ARCHITECTURE.md "Federation
plane"."""

from k8s_watcher_tpu.federate.client import (
    AuthRejected,
    Batch,
    FleetClient,
    FleetSubscriber,
    ResumeLoop,
    ResyncRequired,
    SequenceChecker,
    ServeProtocolError,
    Snapshot,
    TokenStore,
    apply_wire_delta,
    apply_wire_deltas,
    model_from_objects,
)
from k8s_watcher_tpu.federate.merge import (
    GlobalMerge,
    global_key,
    merged_equals_union,
    split_global_key,
)
from k8s_watcher_tpu.federate.fanin import FaninPlan, ShardedFanin, fanin_plans
from k8s_watcher_tpu.federate.plane import FederationPlane

__all__ = [
    "AuthRejected",
    "Batch",
    "FaninPlan",
    "FederationPlane",
    "ShardedFanin",
    "fanin_plans",
    "FleetClient",
    "FleetSubscriber",
    "GlobalMerge",
    "ResumeLoop",
    "ResyncRequired",
    "SequenceChecker",
    "ServeProtocolError",
    "Snapshot",
    "TokenStore",
    "apply_wire_delta",
    "apply_wire_deltas",
    "global_key",
    "merged_equals_union",
    "model_from_objects",
    "split_global_key",
]
