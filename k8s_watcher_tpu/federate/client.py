"""First-class Python consumer of the serve wire protocol.

PRs 4-7 built the serving plane's wire contract (``serve/server.py``):
snapshot at ``GET /serve/fleet``, resumable deltas over ``?watch=1``
chunked JSON-line frames or ``&once=1`` long-polls, SYNC heartbeats,
COMPACTED lag-shedding markers, 410/GONE -> re-snapshot recovery, and a
``view`` instance id that fences resume tokens to one incarnation of the
rv space. Until this module, every consumer of that contract hand-rolled
its own loop (serve_smoke, history_smoke, bench's fan-out checkers, the
README's curl script). This is the ONE implementation they all share —
and the building block the federation plane stacks N-wide.

Three layers, lowest first:

- ``FleetClient``: one upstream's HTTP surface on a persistent-free
  stdlib ``http.client`` connection per request (the package's notify
  idiom; no external deps). ``snapshot()``, ``long_poll()``, and
  ``watch()`` — a generator of decoded frames off the chunked stream
  (``http.client`` erases the transfer chunking; frames are JSON lines).
  410 raises ``ResyncRequired`` (the documented recovery), 401 raises
  ``AuthRejected``, everything else transient raises ``OSError``-family
  for the caller's backoff.
- ``ResumeLoop``: the long-poll consumer shape (what the smokes and the
  README loop run): poll -> sequence-check -> apply -> carry ``to_rv``;
  410 re-snapshots and keeps going.
- ``FleetSubscriber``: the streaming consumer loop the federation plane
  runs per upstream: snapshot -> ``?watch=1`` windows -> reconnect with
  jittered exponential backoff, SYNC-heartbeat staleness detection (a
  stream that stops heartbeating is treated as dead and reconnected),
  in-band GONE / pre-stream 410 -> re-snapshot resync, and resume-token
  persistence (``TokenStore``) so the CONSUMER process also survives its
  own restarts — against a history-enabled upstream the persisted token
  rides PR-5's restart-surviving rv line and resumes gapless through an
  upstream restart too.

``SequenceChecker`` is the shared gap/dup accountant: the view's rv
space is dense (every applied delta is exactly one rv), so a raw
(uncompacted) batch must carry exactly ``to_rv - from_rv`` deltas and
rvs must strictly ascend; COMPACTED sanctions the jump but never a
repeat. One implementation, used by the bench fan-out checkers, both
smokes, the federation subscribers, and the tests.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import random
import select
import socket
import ssl
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, List, NamedTuple, Optional, Tuple
from urllib.parse import urlencode, urlsplit

logger = logging.getLogger(__name__)

# msgpack: the compact wire codec (the image bakes it in; a stripped
# environment downgrades to JSON — the serve protocol is negotiated, so
# a codec mismatch can never fail a request, only widen it)
try:
    import msgpack as _msgpack
except ImportError:  # pragma: no cover - the image bakes msgpack in
    _msgpack = None

#: wire frame / delta types (mirrors serve.view — kept literal here so the
#: client stays importable without dragging the serve plane in)
UPSERT = "UPSERT"
DELETE = "DELETE"
SYNC = "SYNC"
COMPACTED = "COMPACTED"
GONE = "GONE"

#: wire codec names + content types (mirrors serve.view, same reason)
CODEC_JSON = "json"
CODEC_MSGPACK = "msgpack"
CODEC_AUTO = "auto"
JSON_CONTENT_TYPE = "application/json"
MSGPACK_CONTENT_TYPE = "application/x-msgpack"
#: bytes per chunked read off a watch stream: one read's decoded frames
#: form ONE delivery batch downstream (the fan-in batching unit)
WATCH_READ_BYTES = 1 << 16


class ServeProtocolError(RuntimeError):
    """A non-transient serve-protocol answer (carries status + body)."""

    def __init__(self, message: str, *, status: int = 0, body: Optional[dict] = None):
        super().__init__(message)
        self.status = status
        self.body = body or {}


class ResyncRequired(ServeProtocolError):
    """410 (token compacted / ahead-of-view / stale view instance) or an
    in-band GONE frame: the documented recovery is re-snapshot."""


class AuthRejected(ServeProtocolError):
    """401: bearer token missing or wrong — retrying cannot help."""


class Snapshot(NamedTuple):
    rv: int
    view: str
    objects: List[Dict[str, Any]]


class Batch(NamedTuple):
    """One long-poll answer (``?watch=1&once=1``)."""

    from_rv: int
    to_rv: int
    view: str
    compacted: bool
    items: List[Dict[str, Any]]


class SequenceChecker:
    """Gap/dup accounting over one subscriber's resume stream.

    The rv space is dense, so the checks are exact, not heuristic:

    - a raw batch covering ``(from_rv, to_rv]`` with fewer than
      ``to_rv - from_rv`` items LOST a delta (gap);
    - any rv <= its predecessor is a repeat (dup) — compaction may skip
      rvs, never repeat them.
    """

    __slots__ = ("gaps", "dups", "delivered", "batches", "compacted_batches")

    def __init__(self):
        self.gaps = 0
        self.dups = 0
        self.delivered = 0
        self.batches = 0
        self.compacted_batches = 0

    @property
    def clean(self) -> bool:
        return self.gaps == 0 and self.dups == 0

    def observe(self, from_rv: int, to_rv: int, compacted: bool, rvs: Iterable[int]) -> bool:
        """Full per-delta scan of one batch. Returns True when clean."""
        bad = False
        n = 0
        prev = from_rv
        for rv in rvs:
            n += 1
            if rv <= prev:
                self.dups += 1
                bad = True
            prev = rv
        if not compacted and n != to_rv - from_rv:
            self.gaps += 1
            bad = True
        self.delivered += n
        self.batches += 1
        if compacted:
            self.compacted_batches += 1
        return not bad

    def observe_bounds(
        self,
        from_rv: int,
        to_rv: int,
        compacted: bool,
        count: int,
        first_rv: int,
        last_rv: int,
    ) -> bool:
        """O(1) endpoints-only variant for hot paths that cannot afford a
        per-delta walk (the bench's 10k unchecked subscribers): the first
        rv must be past the resume token, the last must land on ``to_rv``
        (the cursor's next token), and a raw batch must be exactly the
        dense range."""
        bad = False
        if count:
            if first_rv <= from_rv or last_rv != to_rv:
                self.dups += 1
                bad = True
            if not compacted and count != to_rv - from_rv:
                self.gaps += 1
                bad = True
        self.delivered += count
        self.batches += 1
        if compacted:
            self.compacted_batches += 1
        return not bad

    def observe_stream_rv(self, prev_rv: int, rv: int, sanctioned: bool) -> bool:
        """One streamed delta frame: ``sanctioned`` means a COMPACTED
        marker covers this range, so a skip is legal (a repeat never is)."""
        self.delivered += 1
        if rv <= prev_rv:
            self.dups += 1
            return False
        if rv != prev_rv + 1 and not sanctioned:
            self.gaps += 1
            return False
        return True

    def to_dict(self) -> Dict[str, int]:
        return {
            "gaps": self.gaps,
            "dups": self.dups,
            "delivered": self.delivered,
            "batches": self.batches,
            "compacted_batches": self.compacted_batches,
        }


def apply_wire_delta(model: Dict[Tuple[str, str], Dict[str, Any]], item: Dict[str, Any]) -> None:
    """Fold one wire delta (UPSERT/DELETE dict) into a ``(kind, key)``-
    keyed model map — the replay every sequence-checked consumer runs."""
    key = (item["kind"], item["key"])
    if item["type"] == DELETE:
        model.pop(key, None)
    else:
        model[key] = item["object"]


def apply_wire_deltas(model: Dict[Tuple[str, str], Dict[str, Any]], items: Iterable[Dict[str, Any]]) -> None:
    for item in items:
        apply_wire_delta(model, item)


def model_from_objects(objects: Iterable[Dict[str, Any]]) -> Dict[Tuple[str, str], Dict[str, Any]]:
    """A snapshot's objects as the same ``(kind, key)``-keyed map shape
    ``apply_wire_delta`` maintains — so ``model == model_from_objects(
    snapshot)`` is the end-to-end replay check."""
    return {(o["kind"], o["key"]): o for o in objects}


class FleetClient:
    """HTTP client for ONE serving plane (``/serve/fleet``).

    Stdlib ``http.client`` only (the package's hand-rolled-HTTP idiom —
    notify/client.py): one connection per request for snapshot/long-poll
    (they are rare and bounded), one connection per ``watch()`` window
    (held open for the whole chunked stream). ``retarget()`` repoints an
    existing client (an upstream that restarted onto a new address).

    Wire codec: ``codec`` is the *preference* — ``auto`` (the default)
    offers ``application/x-msgpack`` and falls back transparently to
    JSON when the peer (or this process's import) lacks it; ``msgpack``
    is the same offer with a louder posture (the downgrade is WARNING,
    not DEBUG); ``json`` never offers msgpack. The peer's Content-Type
    decides what actually rides the wire (``active_codec``); a downgrade
    is logged ONCE per client, not once per reconnect."""

    def __init__(
        self,
        base_url: str,
        *,
        token: Optional[str] = None,
        timeout: float = 10.0,
        verify_tls: bool = True,
        codec: str = CODEC_AUTO,
        fresh: bool = False,
        trace: bool = False,
    ):
        self.token = token
        self.timeout = timeout
        self.verify_tls = verify_tls
        # freshness negotiation (?fresh=1): delta frames additionally
        # carry ts=[origin_wall, publish_wall]. Negotiated like the
        # codec: an upstream that predates the field simply ignores the
        # param and serves plain frames — the decoded dicts just lack
        # "ts", so propagation metrics degrade to absent, never wrong.
        self.fresh = fresh
        # trace negotiation (?trace=1): sampled deltas additionally
        # carry their journey's compact "trace" field (implies fresh on
        # the server side). Same degradation contract: an upstream that
        # predates the field serves plain frames and the joined-trace
        # plane simply sees nothing to join.
        self.trace = trace
        if codec not in (CODEC_AUTO, CODEC_JSON, CODEC_MSGPACK):
            raise ValueError(f"unknown serve wire codec {codec!r}")
        self.codec_preference = codec
        #: what the LAST response actually used (observability + smokes)
        self.active_codec = CODEC_JSON
        self._downgrade_logged = False
        if codec == CODEC_MSGPACK and _msgpack is None:
            # the local import, not the peer, is the limiting side: say so
            # now, once, instead of per request
            logger.warning(
                "msgpack wire codec requested but msgpack is not importable; "
                "downgrading to JSON for %s", base_url,
            )
            self._downgrade_logged = True
        self.base_url = ""
        self._scheme = "http"
        self._host = ""
        self._port = 80
        self.retarget(base_url)

    def retarget(self, base_url: str) -> None:
        parts = urlsplit(base_url if "//" in base_url else f"http://{base_url}")
        if parts.scheme not in ("http", "https"):
            raise ValueError(f"unsupported scheme {parts.scheme!r} in {base_url!r}")
        self.base_url = base_url.rstrip("/")
        self._scheme = parts.scheme
        self._host = parts.hostname or "127.0.0.1"
        self._port = parts.port or (443 if parts.scheme == "https" else 80)
        # a path component is a reverse-proxy prefix: every request rides
        # under it ("http://gw/cluster-a" -> GET /cluster-a/serve/fleet);
        # silently dropping it would 404 the upstream with no hint why
        self._prefix = parts.path.rstrip("/")

    # -- plumbing ----------------------------------------------------------

    def _connect(self, timeout: float) -> http.client.HTTPConnection:
        if self._scheme == "https":
            ctx = ssl.create_default_context()
            if not self.verify_tls:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            return http.client.HTTPSConnection(self._host, self._port, timeout=timeout, context=ctx)
        return http.client.HTTPConnection(self._host, self._port, timeout=timeout)

    def _wants_msgpack(self) -> bool:
        return (
            self.codec_preference in (CODEC_AUTO, CODEC_MSGPACK)
            and _msgpack is not None
        )

    def _headers(self) -> Dict[str, str]:
        accept = JSON_CONTENT_TYPE
        if self._wants_msgpack():
            # preference order left to right; the server picks the first
            # content type it can actually encode
            accept = f"{MSGPACK_CONTENT_TYPE}, {JSON_CONTENT_TYPE}"
        headers = {"Accept": accept, "Connection": "close"}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        return headers

    def _note_codec(self, served: str) -> None:
        """Record what the peer actually served; log the msgpack->JSON
        downgrade ONCE per client (a reconnecting subscriber must not
        repeat it every backoff cycle)."""
        self.active_codec = served
        if (
            served == CODEC_JSON
            and self._wants_msgpack()
            and not self._downgrade_logged
        ):
            self._downgrade_logged = True
            log = logger.warning if self.codec_preference == CODEC_MSGPACK else logger.info
            log(
                "Upstream %s does not speak msgpack; serving JSON instead "
                "(logged once per client)", self.base_url,
            )

    @staticmethod
    def _response_codec(resp: http.client.HTTPResponse) -> str:
        ctype = (resp.getheader("Content-Type") or "").lower()
        return CODEC_MSGPACK if MSGPACK_CONTENT_TYPE in ctype else CODEC_JSON

    def _decode_body(self, resp: http.client.HTTPResponse) -> dict:
        """Decode one bounded response body by its Content-Type (the
        negotiation's answer), tracking the active codec."""
        data = resp.read()
        codec = self._response_codec(resp)
        self._note_codec(codec)
        if codec == CODEC_MSGPACK:
            return _msgpack.unpackb(data, raw=False, strict_map_key=False)
        return json.loads(data)

    def _body_json(self, resp: http.client.HTTPResponse) -> dict:
        """Best-effort body decode for error paths (either codec; a
        non-body answer decodes to {})."""
        try:
            data = resp.read() or b"{}"
        except OSError:
            return {}
        if self._response_codec(resp) == CODEC_MSGPACK:
            try:
                body = _msgpack.unpackb(data, raw=False, strict_map_key=False)
                return body if isinstance(body, dict) else {}
            except Exception:  # noqa: BLE001 - error bodies are advisory
                return {}
        try:
            return json.loads(data)
        except ValueError:
            return {}

    def _raise_for_status(self, resp: http.client.HTTPResponse) -> None:
        if resp.status == 200:
            return
        body = self._body_json(resp)
        message = body.get("error") or f"HTTP {resp.status}"
        if resp.status == 410:
            raise ResyncRequired(message, status=410, body=body)
        if resp.status == 401:
            raise AuthRejected(message, status=401, body=body)
        # 503 (admission full) and everything else transient: OSError so
        # callers' one except-arm handles "back off and retry"
        raise ConnectionError(f"{self.base_url}: {message} (HTTP {resp.status})")

    def _get_json(self, path: str, timeout: float) -> dict:
        conn = self._connect(timeout)
        try:
            conn.request("GET", self._prefix + path, headers=self._headers())
            resp = conn.getresponse()
            self._raise_for_status(resp)
            return self._decode_body(resp)
        finally:
            conn.close()

    # -- protocol ----------------------------------------------------------

    def snapshot(self) -> Snapshot:
        body = self._get_json("/serve/fleet", self.timeout)
        return Snapshot(body["rv"], body.get("view", ""), body.get("objects", []))

    def snapshot_at(self, rv: int) -> Snapshot:
        """Time-travel read (``?at=rv``; needs the upstream's history plane)."""
        body = self._get_json(f"/serve/fleet?at={int(rv)}", self.timeout)
        return Snapshot(body["rv"], body.get("view", ""), body.get("objects", []))

    def debug_trace(self, uid: str, *, n: int = 50) -> List[Dict[str, Any]]:
        """One upstream's local traces for a pod — ``GET /debug/trace``
        on the SERVE port (serve/server.py routes it when tracing is on).
        The federation plane's lazy-stitch path: called only on a
        stitched query that needs spans not forwarded in-band. Raises the
        client's usual error family; the collector degrades any failure
        to a partial answer."""
        query = urlencode({"uid": uid, "n": int(n)})
        body = self._get_json(f"/debug/trace?{query}", self.timeout)
        return body.get("traces", [])

    def healthz(self) -> dict:
        """``/serve/healthz`` (open route; also tolerates non-200 — the
        body is the point)."""
        conn = self._connect(self.timeout)
        try:
            conn.request("GET", self._prefix + "/serve/healthz", headers={"Accept": "application/json"})
            return self._body_json(conn.getresponse())
        finally:
            conn.close()

    def long_poll(
        self,
        rv: int,
        *,
        view: Optional[str] = None,
        timeout: float = 1.0,
        limit: Optional[int] = None,
    ) -> Batch:
        """One ``?watch=1&once=1`` long-poll. Raises ``ResyncRequired``
        on 410 (token expired / view instance changed / rv ahead)."""
        params = {"watch": "1", "once": "1", "rv": rv, "timeout": timeout}
        if view:
            params["view"] = view
        if limit:
            params["limit"] = limit
        if self.fresh:
            params["fresh"] = "1"
        if self.trace:
            params["trace"] = "1"
        body = self._get_json(
            f"/serve/fleet?{urlencode(params)}",
            # the HTTP read must outlive the server-side poll window
            timeout + self.timeout,
        )
        return Batch(
            body["from_rv"], body["to_rv"], body.get("view", ""),
            bool(body.get("compacted")), body.get("items", []),
        )

    def watch_batches(
        self,
        rv: int,
        *,
        view: Optional[str] = None,
        window_seconds: float = 30.0,
        read_timeout: Optional[float] = None,
        limit: Optional[int] = None,
        on_conn: Optional[Callable[[http.client.HTTPConnection], None]] = None,
        raw: bool = False,
    ) -> Iterator[List[Any]]:
        """One ``?watch=1`` stream window, yielding frame BATCHES: every
        chunked read off the socket (``read1``, up to ``WATCH_READ_BYTES``)
        decodes into one list of frames (SYNC / UPSERT / DELETE /
        COMPACTED / GONE dicts) — the fan-in batching unit. A publisher
        batch the server wrote in one pass arrives in one read and is
        handed downstream in one call, so the consumer amortizes its own
        apply cost the same way the server amortized its encode cost.

        The serve wire frames each delta as its own chunked-transfer
        chunk (the encode-once frame bytes INCLUDE the chunk framing, so
        the server cannot coalesce them without re-encoding), and
        ``http.client``'s ``read1`` returns at most ONE chunk — so one
        blocking read is followed by a zero-timeout drain of every chunk
        already queued on the socket (up to ``WATCH_READ_BYTES``). Under
        a trickle each batch is ~1 frame; when the consumer falls behind
        a churn storm the backlog arrives queued and batches grow to
        exactly the size the amortization needs.

        ``read_timeout`` bounds the wait for EACH blocking read — the
        SYNC heartbeat cadence is 2 s, so a read that outwaits
        ``read_timeout`` means the upstream stalled (socket.timeout
        propagates; the caller reconnects). Pre-stream 410 raises
        ``ResyncRequired`` before any frame is yielded. ``on_conn``
        receives the live connection before the request is sent — a
        stopper can close it to abort a blocked read immediately.

        Codec: negotiated per the client preference; msgpack frames are
        self-delimiting (fed through a streaming unpacker), JSON frames
        are newline-delimited lines — either way one read yields one
        batch, and the decoded dicts are identical across codecs.

        ``raw=True`` is the relay tier's zero-re-encode passthrough:
        each batch item becomes a ``(frame, raw_bytes)`` pair, where
        ``raw_bytes`` is the frame's codec payload EXACTLY as the
        upstream encoded it (the JSON line including its trailing
        newline; the msgpack ``packb`` span) — the decoded dict carries
        the control metadata (type/rv/ts/...) while the untouched bytes
        ride beside it, so a relay can re-broadcast the same bytes
        without ever re-serializing. Spans are exact under partial-tail
        carry too: a frame split across reads is delivered once,
        complete, with its original bytes."""
        params = {"watch": "1", "rv": rv, "timeout": window_seconds}
        if view:
            params["view"] = view
        if limit:
            params["limit"] = limit
        if self.fresh:
            params["fresh"] = "1"
        if self.trace:
            params["trace"] = "1"
        conn = self._connect(read_timeout if read_timeout is not None else self.timeout)
        if on_conn is not None:
            on_conn(conn)
        try:
            conn.request("GET", f"{self._prefix}/serve/fleet?{urlencode(params)}", headers=self._headers())
            resp = conn.getresponse()
            self._raise_for_status(resp)
            # http.client strips the chunked-transfer framing; what is
            # left is the codec's raw frame stream
            codec = self._response_codec(resp)
            self._note_codec(codec)
            if codec == CODEC_MSGPACK:
                unpacker = _msgpack.Unpacker(raw=False, strict_map_key=False)
                # raw mode keeps a sliding copy of the fed bytes; each
                # unpacked frame's span is cut by Unpacker.tell() (the
                # cumulative stream position), so the raw bytes are the
                # upstream's packb output verbatim — a partial tail just
                # stays in `fed` until the next read completes the frame
                fed = bytearray()
                consumed = 0  # stream offset of fed[0]
                pos = 0  # stream position of the last unpacked frame end
                while True:
                    chunks, eof = self._drain_chunks(resp, conn.sock)
                    for data in chunks:
                        unpacker.feed(data)
                        if raw:
                            fed += data
                    if raw:
                        batch = []
                        for frame in unpacker:
                            end = unpacker.tell()
                            batch.append(
                                (frame, bytes(fed[pos - consumed:end - consumed]))
                            )
                            pos = end
                        del fed[: pos - consumed]
                        consumed = pos
                    else:
                        batch = [frame for frame in unpacker]
                    if batch:
                        yield batch
                    if eof:
                        return  # clean window end (terminal chunk)
            else:
                buf = b""
                while True:
                    chunks, eof = self._drain_chunks(resp, conn.sock)
                    data = b"".join(chunks)
                    buf += data
                    if b"\n" in data:
                        lines = buf.split(b"\n")
                        buf = lines.pop()  # partial tail carries over
                        if raw:
                            # the upstream frames one JSON line + "\n"
                            # per delta: line + b"\n" IS the original
                            # payload byte-for-byte
                            batch = [
                                (json.loads(line), line + b"\n")
                                for line in lines
                                if line.strip()
                            ]
                        else:
                            batch = [json.loads(line) for line in lines if line.strip()]
                        if batch:
                            yield batch
                    if eof:
                        # leftover partial line = the peer died mid-frame;
                        # there is nothing decodable left to deliver
                        return
        finally:
            conn.close()

    @staticmethod
    def _drain_chunks(resp, sock) -> Tuple[List[bytes], bool]:
        """One blocking ``read1`` (bounded by the socket timeout), then a
        zero-timeout drain of every further chunk the kernel already has
        — up to ``WATCH_READ_BYTES`` total, so a deep backlog paces into
        bounded batches instead of one giant buffer. Returns
        ``(chunks, eof)``. A chunk sitting in the response's own buffered
        reader when the socket shows nothing new just lands at the head
        of the NEXT batch (the following blocking read returns it without
        waiting) — fragmentation, never a stall."""
        data = resp.read1(WATCH_READ_BYTES)
        if not data:
            return [], True
        chunks = [data]
        total = len(data)
        while total < WATCH_READ_BYTES:
            if sock is None:
                break
            try:
                if not select.select([sock], [], [], 0)[0]:
                    break
            except (OSError, ValueError):
                break  # racing close (stop()); the next read raises
            more = resp.read1(WATCH_READ_BYTES - total)
            if not more:
                return chunks, True
            chunks.append(more)
            total += len(more)
        return chunks, False

    def watch(
        self,
        rv: int,
        *,
        view: Optional[str] = None,
        window_seconds: float = 30.0,
        read_timeout: Optional[float] = None,
        limit: Optional[int] = None,
        on_conn: Optional[Callable[[http.client.HTTPConnection], None]] = None,
    ) -> Iterator[Dict[str, Any]]:
        """``watch_batches`` flattened to one frame per yield — the
        per-frame shape for consumers that don't batch."""
        for batch in self.watch_batches(
            rv,
            view=view,
            window_seconds=window_seconds,
            read_timeout=read_timeout,
            limit=limit,
            on_conn=on_conn,
        ):
            yield from batch


class TokenStore:
    """Durable resume token: ``{rv, view}``, written atomically (tmp +
    rename) so a crash never leaves a torn token. This is the consumer-
    side half of PR-5's restart story: the upstream's WAL keeps the rv
    line alive across ITS restarts; this file keeps the cursor alive
    across OURS."""

    def __init__(self, path: str):
        self.path = str(path)

    def load(self) -> Optional[Tuple[int, str]]:
        try:
            with open(self.path) as f:
                body = json.load(f)
            return int(body["rv"]), str(body["view"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def save(self, rv: int, view: str) -> None:
        tmp = f"{self.path}.tmp"
        directory = os.path.dirname(self.path)
        if directory:
            os.makedirs(directory, exist_ok=True)
        with open(tmp, "w") as f:
            json.dump({"rv": int(rv), "view": view}, f)
        os.replace(tmp, self.path)

    def clear(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


class ResumeLoop:
    """The long-poll resume-protocol consumer (the README loop, now as
    code): snapshot -> poll -> sequence-check -> apply -> carry ``to_rv``;
    a 410 runs the documented recovery (re-snapshot) and keeps going.
    Both smokes drive their consumers through this."""

    def __init__(self, client: FleetClient, *, checker: Optional[SequenceChecker] = None):
        self.client = client
        self.checker = checker or SequenceChecker()
        self.model: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.rv = 0
        self.view = ""
        self.polls = 0
        self.resyncs = 0

    def start(self) -> Snapshot:
        snap = self.client.snapshot()
        self.rv, self.view = snap.rv, snap.view
        self.model = model_from_objects(snap.objects)
        return snap

    def poll(self, *, timeout: float = 1.0, limit: Optional[int] = None) -> bool:
        """One long-poll; False when a 410 forced a re-snapshot."""
        self.polls += 1
        try:
            batch = self.client.long_poll(self.rv, view=self.view, timeout=timeout, limit=limit)
        except ResyncRequired:
            self.start()
            self.resyncs += 1
            return False
        self.checker.observe(
            batch.from_rv, batch.to_rv, batch.compacted, (i["rv"] for i in batch.items)
        )
        apply_wire_deltas(self.model, batch.items)
        self.rv = batch.to_rv
        return True

    def drain(self, *, polls: int = 30, timeout: float = 0.3) -> None:
        """Poll with short windows until a poll delivers nothing (or the
        budget runs out) — the catch-up tail after churn stops."""
        for _ in range(polls):
            before = self.rv
            self.poll(timeout=timeout)
            if self.rv == before:
                break


class FleetSubscriber:
    """The streaming consumer loop one federation upstream runs.

    ``run()`` blocks until ``stop()``: it snapshots (or resumes from the
    persisted token), streams ``?watch=1`` windows, and survives every
    documented failure mode —

    - transient errors / refused connections / heartbeat stalls (no
      frame within ``stale_after_seconds``): reconnect with jittered
      exponential backoff, resume from the carried token;
    - pre-stream 410 or in-band GONE: re-snapshot (``on_snapshot`` gets
      the full state; the resync counter ticks);
    - a clean window end: reconnect immediately (the resume protocol).

    Callbacks run on the subscriber's thread: ``on_snapshot(Snapshot)``
    replaces downstream state wholesale; ``on_batch(frames)`` folds one
    wire-read's worth of UPSERT/DELETE frames in one call (the fan-in
    batching unit — the federation plane folds it under one lock), or
    ``on_delta(frame)`` folds them one at a time when no batch handler
    is given. ``on_raw_batch(pairs)`` is the relay tier's handler: the
    stream runs in raw-passthrough mode and each delivered run is a list
    of ``(frame, raw_bytes)`` pairs — decoded control metadata beside
    the upstream's untouched frame bytes (see ``watch_batches(raw=)``).
    The ``SequenceChecker`` rides every delivery either way."""

    def __init__(
        self,
        client: FleetClient,
        *,
        on_snapshot: Optional[Callable[[Snapshot], None]] = None,
        on_delta: Optional[Callable[[Dict[str, Any]], None]] = None,
        on_batch: Optional[Callable[[List[Dict[str, Any]]], None]] = None,
        on_raw_batch: Optional[Callable[[List[Tuple[Dict[str, Any], bytes]]], None]] = None,
        token_store: Optional[TokenStore] = None,
        stale_after_seconds: float = 10.0,
        backoff_seconds: float = 1.0,
        max_backoff_seconds: float = 30.0,
        window_seconds: float = 30.0,
        checker: Optional[SequenceChecker] = None,
        rng: Optional[random.Random] = None,
        name: str = "",
    ):
        self.client = client
        self.on_snapshot = on_snapshot
        self.on_delta = on_delta
        self.on_batch = on_batch
        self.on_raw_batch = on_raw_batch
        self.token_store = token_store
        # the stream heartbeats every 2 s when idle; anything sub-3s
        # would call a healthy idle stream dead
        self.stale_after_seconds = max(3.0, stale_after_seconds)
        self.backoff_seconds = max(0.05, backoff_seconds)
        self.max_backoff_seconds = max(self.backoff_seconds, max_backoff_seconds)
        self.window_seconds = window_seconds
        self.checker = checker or SequenceChecker()
        self.rng = rng or random.Random()
        self.name = name
        self.rv: Optional[int] = None
        self.view: Optional[str] = None
        # wire_rv: the newest rv SEEN on the wire (SYNC included) even if
        # not yet folded downstream — feeds the per-upstream lag-rv gauge
        self.wire_rv = 0
        self.reconnects = 0
        self.resyncs = 0
        self.snapshots = 0
        self.stalls = 0
        self.frames = 0
        self.batches = 0  # wire-read batches delivered (frames/batches = fan-in batch size)
        self.connected = False
        self.last_error: Optional[str] = None
        self._last_frame_t = 0.0  # 0 = never
        # freshness watermark: the origin wall stamp of the NEWEST delta
        # applied downstream (frame ts when the upstream stamps, local
        # receive wall otherwise; a snapshot reconcile resets it to now —
        # a full state hand-off is by definition fresh). Advances under
        # churn, ages while the upstream is paused or dark.
        self.watermark_wall: Optional[float] = None
        self._last_delta_mono = 0.0  # local monotonic of the last applied delta
        self._saved_token: Optional[Tuple[int, str]] = None  # last persisted (rv, view)
        self._stop = threading.Event()
        self._invalidate = threading.Event()
        # the live watch connection, so stop() can abort a read blocked
        # up to stale_after_seconds instead of outwaiting it — the
        # plane's join must reliably finish BEFORE the history WAL
        # writes its terminal snapshot
        self._conn_lock = threading.Lock()
        self._conn: Optional[http.client.HTTPConnection] = None

    # -- external surface --------------------------------------------------

    def stop(self) -> None:
        self._stop.set()
        # abort an in-flight blocked read NOW: without this the run loop
        # can sit in readline() up to stale_after_seconds, outliving the
        # caller's join and racing whatever shutdown step follows it
        with self._conn_lock:
            conn = self._conn
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def invalidate(self) -> None:
        """Force a re-snapshot on the next (re)connect — the drop-stale
        policy uses this after it deleted a dark upstream's objects, so a
        later token-resume can't skip re-materializing them."""
        self._invalidate.set()

    def last_frame_age(self) -> Optional[float]:
        """Seconds since the last frame (None before the first)."""
        t = self._last_frame_t
        return None if t == 0.0 else time.monotonic() - t

    def last_delta_age(self) -> Optional[float]:
        """Seconds since the last DELTA applied downstream (SYNC
        heartbeats don't count — an idle-but-alive upstream ages here
        while staying fresh on ``last_frame_age``)."""
        t = self._last_delta_mono
        return None if t == 0.0 else time.monotonic() - t

    def watermark_age(self) -> Optional[float]:
        """Age of the freshness watermark: wall-now minus the origin
        stamp of the newest applied delta. Wall clocks (the origin is a
        REMOTE host) — subject to cross-host skew, clamped at 0."""
        w = self.watermark_wall
        return None if w is None else max(0.0, time.time() - w)

    # -- the loop ----------------------------------------------------------

    def _save_token(self, rv: int, view: str) -> None:
        """Persist (rv, view) iff it changed — an idle upstream's SYNC
        heartbeats must not rewrite the token file every 2 s forever."""
        if self.token_store is None or self._saved_token == (rv, view):
            return
        self.token_store.save(rv, view)
        self._saved_token = (rv, view)

    def run(self) -> None:
        if self.rv is None and self.token_store is not None:
            token = self.token_store.load()
            if token is not None:
                self.rv, self.view = token
                self._saved_token = token
        try:
            self._run_loop()
        finally:
            # persist the EXACT live position on the way out: the periodic
            # save cadence (SYNC / every 256 deltas / window end) can leave
            # the durable token up to a window behind, which a stopped-and-
            # respawned consumer (a drained merge worker) would replay —
            # harmless but not free. Never on an invalidated line (that
            # must re-snapshot) and never let a disk error mask the exit.
            if (
                self.rv is not None
                and self.view is not None
                and not self._invalidate.is_set()
            ):
                try:
                    self._save_token(self.rv, self.view)
                except OSError:
                    pass

    def _run_loop(self) -> None:
        backoff = self.backoff_seconds
        while not self._stop.is_set():
            try:
                if self._invalidate.is_set():
                    self._invalidate.clear()
                    self.rv = None
                if self.rv is None or self.view is None:
                    self._resnapshot()
                self._watch_window()
                self.connected = False
                backoff = self.backoff_seconds  # a completed window resets it
            except ResyncRequired as exc:
                self.connected = False
                self.resyncs += 1
                self.last_error = str(exc)
                self.rv = None  # next iteration re-snapshots
                # the documented resync backoff (jittered, escalating): a
                # GONE storm — this consumer slower than the upstream's
                # churn — must not hot-loop O(fleet) snapshot reads
                # against an already-overloaded upstream, and N federators
                # losing the same horizon must not herd their re-snapshots
                if self._sleep(backoff):
                    return
                backoff = min(backoff * 2, self.max_backoff_seconds)
            except AuthRejected as exc:
                # wrong credentials never fix themselves by retrying fast:
                # surface via health (connected=False + last_error) and
                # retry at the MAX backoff in case the token gets rotated
                self.connected = False
                self.last_error = f"auth rejected: {exc}"
                if self._sleep(self.max_backoff_seconds):
                    return
            except (socket.timeout, TimeoutError) as exc:
                self.connected = False
                self.stalls += 1
                self.reconnects += 1
                self.last_error = f"heartbeat stall: {exc!r}"
                # a stall is not a refused connection: retry promptly
                if self._sleep(self.backoff_seconds):
                    return
            except (OSError, http.client.HTTPException, ValueError) as exc:
                self.connected = False
                if self._stop.is_set():
                    return  # stop()'s connection abort, not a real fault
                self.reconnects += 1
                self.last_error = f"{type(exc).__name__}: {exc}"
                if self._sleep(backoff):
                    return
                backoff = min(backoff * 2, self.max_backoff_seconds)
            except Exception:
                # stop() closes the live connection from another thread;
                # http.client then fails at whatever it was doing (e.g.
                # AttributeError reading a None fp) — exit quietly when
                # stopping, re-raise genuine bugs
                self.connected = False
                if self._stop.is_set():
                    return
                raise

    def _sleep(self, seconds: float) -> bool:
        """Jittered wait (0.5x..1.5x) — N federation subscribers losing
        the same upstream must not reconnect in lockstep. True = stopped."""
        return self._stop.wait(seconds * (0.5 + self.rng.random()))

    def _resnapshot(self) -> None:
        snap = self.client.snapshot()
        self.rv, self.view = snap.rv, snap.view
        self.wire_rv = max(self.wire_rv, snap.rv)
        self.snapshots += 1
        self._last_frame_t = time.monotonic()
        # a full state hand-off is by definition fresh as of now
        self.watermark_wall = time.time()
        self._last_delta_mono = time.monotonic()
        self._save_token(snap.rv, snap.view)
        if self.on_snapshot is not None:
            self.on_snapshot(snap)

    def _register_conn(self, conn) -> None:
        with self._conn_lock:
            self._conn = conn
        if self._stop.is_set():
            # stop() may have read a stale None just before we registered:
            # close here so the abort can never be missed
            try:
                conn.close()
            except OSError:
                pass

    def _deliver(self, run: List[Any]) -> None:
        """Hand one contiguous UPSERT/DELETE run downstream: one
        ``on_raw_batch`` call (raw-passthrough mode — items are
        ``(frame, raw_bytes)`` pairs), one ``on_batch`` call (the
        batched fan-in path), or per-frame ``on_delta`` fallback.
        Sequence checking and cursor advance already happened —
        delivery is pure application."""
        if not run:
            return
        if self.on_raw_batch is not None:
            self.on_raw_batch(run)
        elif self.on_batch is not None:
            self.on_batch(run)
        elif self.on_delta is not None:
            for frame in run:
                self.on_delta(frame)

    def _watch_window(self) -> None:
        assert self.rv is not None
        compacted_until = -1  # COMPACTED sanctions skips up to this rv
        deltas_since_save = 0
        raw_mode = self.on_raw_batch is not None
        for batch in self.client.watch_batches(
            self.rv,
            view=self.view,
            window_seconds=self.window_seconds,
            read_timeout=self.stale_after_seconds,
            on_conn=self._register_conn,
            raw=raw_mode,
        ):
            if self._stop.is_set():
                # BEFORE applying: a batch racing stop() must not reach
                # the downstream view after the caller's join returned
                # (e.g. after the history WAL's terminal snapshot)
                return
            self._last_frame_t = time.monotonic()
            self.connected = True
            self.frames += len(batch)
            self.batches += 1
            # one wire read = one delivery batch; control frames split a
            # batch into contiguous delta runs so apply order matches
            # wire order exactly. The resume cursor (self.rv) advances
            # only AFTER a run is delivered: if a downstream callback
            # raises a retried exception class mid-apply, the reconnect
            # resumes from the last delivered rv and the run is simply
            # redelivered — never silently skipped.
            run: List[Any] = []
            run_watermark: Optional[float] = None
            prev_rv = self.rv or 0

            def commit_run() -> None:
                nonlocal run, run_watermark
                if run:
                    self._deliver(run)
                    # watermark semantics: the newest APPLIED delta's
                    # origin stamp — advanced only AFTER the run reached
                    # downstream, so a slow apply never reads as fresh
                    if run_watermark is not None:
                        self.watermark_wall = run_watermark
                    self._last_delta_mono = time.monotonic()
                    run = []
                    run_watermark = None
                self.rv = max(self.rv, prev_rv)

            for item in batch:
                # raw mode delivers (frame, raw_bytes) pairs; the decoded
                # dict drives all control/sequence logic either way
                frame = item[0] if raw_mode else item
                ftype = frame.get("type")
                if ftype in (UPSERT, DELETE):
                    rv = frame["rv"]
                    self.checker.observe_stream_rv(prev_rv, rv, rv <= compacted_until)
                    self.wire_rv = max(self.wire_rv, rv)
                    run.append(item)
                    prev_rv = max(prev_rv, rv)
                    deltas_since_save += 1
                    # watermark candidate: the negotiated origin stamp
                    # when the upstream sent one, local receive wall
                    # otherwise (adopted by commit_run AFTER delivery)
                    ts = frame.get("ts")
                    run_watermark = ts[0] if ts else time.time()
                    continue
                commit_run()
                if ftype == SYNC:
                    rv = frame.get("rv", self.rv)
                    self.wire_rv = max(self.wire_rv, rv)
                    if rv > self.rv:
                        self.rv = rv  # idle SYNC advances the resume token
                    prev_rv = max(prev_rv, self.rv)
                    self._save_token(self.rv, frame.get("view") or self.view or "")
                    deltas_since_save = 0
                elif ftype == COMPACTED:
                    compacted_until = max(compacted_until, frame.get("to_rv", -1))
                    self.checker.compacted_batches += 1
                elif ftype == GONE:
                    raise ResyncRequired(
                        "in-band GONE (fell behind the horizon mid-stream)",
                        status=410, body=frame,
                    )
            commit_run()
            if deltas_since_save >= 256:
                # periodic persistence bounds replay-after-crash; the
                # per-SYNC save above covers the idle/stream-end cases
                self._save_token(self.rv, self.view or "")
                deltas_since_save = 0
        if deltas_since_save:
            self._save_token(self.rv, self.view or "")

    def status(self) -> Dict[str, Any]:
        age = self.last_frame_age()
        delta_age = self.last_delta_age()
        watermark = self.watermark_age()
        return {
            "name": self.name,
            "connected": self.connected,
            "rv": self.rv,
            "wire_rv": self.wire_rv,
            "view": self.view,
            "last_frame_age_seconds": round(age, 3) if age is not None else None,
            "last_delta_age_seconds": round(delta_age, 3) if delta_age is not None else None,
            "watermark_age_seconds": round(watermark, 3) if watermark is not None else None,
            "frames": self.frames,
            "batches": self.batches,
            "codec": self.client.active_codec,
            "snapshots": self.snapshots,
            "reconnects": self.reconnects,
            "resyncs": self.resyncs,
            "stalls": self.stalls,
            "gaps": self.checker.gaps,
            "dups": self.checker.dups,
            "delivered": self.checker.delivered,
            "last_error": self.last_error,
        }
