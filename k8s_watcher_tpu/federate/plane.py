"""FederationPlane: N upstream watchers fanned into one global view.

Guard (arxiv 2605.17879) argues fleet-level health management needs one
aggregated control plane over per-cluster collectors; Podracer (arxiv
2104.06272) shows the scale shape — many single-responsibility workers
behind one fan-in tier. This module is that tier for k8s-watcher-tpu:
one ``FleetSubscriber`` thread per upstream serving plane (each a full
resume-protocol consumer: snapshot, streamed deltas, heartbeat staleness,
410 resync, jittered backoff, durable resume tokens), all folding through
``GlobalMerge`` into the LOCAL FleetView — so the existing serving plane
republishes the merged fleet with encode-once fan-out, the history WAL
makes global resume tokens restart-surviving, and ``?at=`` time travel
works on the global view, all for free.

A monitor thread (one tick per ~second) owns the cross-cutting
bookkeeping no single subscriber can: per-upstream staleness verdicts
(and the drop-stale policy arm), the lag gauges, and syncing subscriber
counts into the metrics registry. ``health()`` folds per-upstream
liveness into the status plane's /healthz — a federator serving a
half-dark global view must say so.

``federation.processes > 0`` swaps the in-process subscriber fleet for
the SHARDED fan-in (federate/fanin.py): supervised merge-worker
processes own the subscribers and ship prepared deltas over pipes, and
this plane becomes the thin parent — sequencer fold into the view plus
MIRRORING worker-reported state into the same gauges/health/freshness
surfaces. Staleness ownership is explicit (``staleness_owner``): the
monitor tick computes per-upstream staleness verdicts ONLY in the
in-process mode; in sharded mode the workers own the verdict (they
hold the live subscriber clocks) and the tick only mirrors it — so a
sharded deploy never double-reports ``federation_upstream_stale``.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional

from k8s_watcher_tpu.config.schema import metric_safe_name as _metric_suffix
from k8s_watcher_tpu.metrics.metrics import MAX_LABEL_SETS
from k8s_watcher_tpu.federate.client import (
    FleetClient,
    FleetSubscriber,
    ResyncRequired,
    Snapshot,
    TokenStore,
)
from k8s_watcher_tpu.federate.merge import GlobalMerge

logger = logging.getLogger(__name__)


class _Upstream:
    """One upstream's subscriber + bookkeeping the monitor reads."""

    def __init__(self, plane: "FederationPlane", cfg, index: int):
        self.cfg = cfg
        self.name = cfg.name
        self.epoch: Optional[str] = None
        self.epoch_changes = 0
        self.stale = False
        self.dropped = False  # drop_stale already removed our objects
        # serializes the monitor's drop against the subscriber thread's
        # snapshot-reconcile/delta-apply: without it a drop could land
        # just after a reconcile repopulated the cluster (or a delta
        # could slip in between flag and drop), leaving untouched
        # objects missing for up to a watch window
        self.drop_lock = threading.Lock()
        self._synced: Dict[str, int] = {}  # counter diff-sync state
        # oldest-unpropagated tracking: monotonic stamp of when this
        # upstream FIRST fell behind (wire_rv ahead of the applied rv);
        # None while caught up. The monitor tick maintains it.
        self.lag_since: Optional[float] = None
        # request timeout floored well above the staleness knob: a tight
        # stale_after must not shrink the snapshot-read budget with it
        self.client = FleetClient(
            cfg.url, token=cfg.token,
            timeout=max(5.0, plane.config.stale_after_seconds),
            codec=plane.config.codec,
            # always negotiate freshness stamps: the propagation
            # histograms and watermarks are this plane's telemetry; an
            # upstream that predates the field just serves plain frames
            fresh=True,
            # negotiate in-band trace forwarding only when the joined-
            # trace plane is on — unjoined trace fields would be dead
            # wire bytes on every sampled delta
            trace=plane.trace_collector is not None,
        )
        if plane.trace_collector is not None:
            # lazy-stitch fetcher: the collector queries THIS upstream's
            # serve-port /debug/trace for spans not forwarded in-band
            # (each call opens its own connection — safe alongside the
            # subscriber thread's watch stream)
            plane.trace_collector.register_fetcher(self.name, self.client.debug_trace)
        self.subscriber = FleetSubscriber(
            self.client,
            on_snapshot=self._on_snapshot,
            on_batch=self._on_batch,
            token_store=plane.token_store_for(self.name),
            stale_after_seconds=plane.config.stale_after_seconds,
            backoff_seconds=plane.config.resync_backoff_seconds,
            # deterministic jitter spread across upstreams; reseeded per
            # process via the index + pid mix
            rng=random.Random((os.getpid() << 8) ^ index),
            name=self.name,
        )
        self.thread = threading.Thread(
            target=self.subscriber.run, name=f"federate-{self.name}", daemon=True
        )
        self._plane = plane
        metrics = plane.metrics
        # per-upstream series as REAL labels (`...{upstream="a"}`)
        if metrics is not None:
            label = {"upstream": self.name}
            self.lag_rv_gauge = metrics.gauge("federation_upstream_lag_rv").labels(**label)
            self.lag_seconds_gauge = metrics.gauge("federation_upstream_lag_seconds").labels(**label)
            self.stale_gauge = metrics.gauge("federation_upstream_stale").labels(**label)
            # freshness watermarks (the /debug/freshness surface):
            # watermark age = wall-now minus the newest applied delta's
            # ORIGIN stamp (ages while the upstream is paused/dark);
            # last-delta age = local monotonic since the last applied
            # delta; oldest-unpropagated = how long the subscriber has
            # been behind the newest rv it has SEEN on the wire
            self.watermark_age_gauge = metrics.gauge(
                "federation_upstream_watermark_age_seconds"
            ).labels(**label)
            self.last_delta_age_gauge = metrics.gauge(
                "federation_upstream_last_delta_age_seconds"
            ).labels(**label)
            self.oldest_unpropagated_gauge = metrics.gauge(
                "federation_upstream_oldest_unpropagated_seconds"
            ).labels(**label)
        else:
            self.lag_rv_gauge = None
            self.lag_seconds_gauge = None
            self.stale_gauge = None
            self.watermark_age_gauge = None
            self.last_delta_age_gauge = None
            self.oldest_unpropagated_gauge = None

    def _on_snapshot(self, snap: Snapshot) -> None:
        if self.epoch is not None and snap.view != self.epoch:
            # the upstream restarted into a fresh rv space (unclean end,
            # or history off): epochs fence its resume tokens; the full
            # reconcile below re-bases our copy of its state
            self.epoch_changes += 1
            logger.warning(
                "Federation upstream %s changed view epoch %s -> %s (restart); reconciling",
                self.name, self.epoch, snap.view,
            )
        self.epoch = snap.view
        with self.drop_lock:
            self.dropped = False
            self._plane.merge.reset_cluster(self.name, snap.objects)
        if self._plane.snapshots_counter is not None:
            self._plane.snapshots_counter.inc()

    def _on_batch(self, frames: List[Dict[str, Any]]) -> None:
        """One wire-read's worth of deltas, folded in ONE merge call:
        one registry-lock acquisition, one view publish-lock hold, one
        subscriber wakeup — however many frames the read carried. This
        is the fan-in batching the bench's ≥3x gate measures against
        the per-delta ``apply_delta`` baseline."""
        if not frames:
            return
        collector = self._plane.trace_collector
        # ONE cheap membership walk finds the sampled 1/N; the collector
        # then pays per TRACED frame only — the unsampled fan-in hot
        # path's whole trace bill is this `in` check (bench-gated <3%)
        traced = (
            [f for f in frames if "trace" in f] if collector is not None else ()
        )
        t_recv = time.time() if traced else 0.0
        if traced:
            # rewrite traced frames' in-band trace field with this hop's
            # serve_wire span BEFORE the fold — the merged deltas journal
            # the rewritten dict, so the global view's republished frames
            # carry the joined identity to any second-tier federator
            collector.note_receive(self.name, traced, t_recv)
        with self.drop_lock:
            if self.dropped:
                # drop_stale removed our objects while this stream was
                # stalled but still open; a delta-only resume would leave
                # every untouched object missing — force the full
                # reconcile instead
                raise ResyncRequired("objects dropped while stale; re-snapshot to reconcile")
            t_pub = time.time() if traced else 0.0
            self._plane.merge.apply_batch(self.name, frames)
        if traced:
            # close the journeys: federate_merge (receive -> merged
            # publish) + global_serve (merged publish -> fan-out
            # hand-off, i.e. apply_batch's wakeup returned) and record
            # the JOINED traces + attribution histograms
            collector.adopt(self.name, traced, t_recv, t_pub, time.time())
        if self._plane.deltas_counter is not None:
            self._plane.deltas_counter.inc(len(frames))
        if self._plane.batches_counter is not None:
            self._plane.batches_counter.inc()
        # propagation telemetry off the negotiated per-frame stamps
        # (ts = [origin_wall, upstream_publish_wall]): end-to-end
        # watch->global-view age and the serve-wire hop. Wall clocks —
        # origin is a REMOTE host — so readings are clamped at 0 and
        # carry the documented cross-host skew caveat.
        w2g = self._plane.watch_to_global
        wire = self._plane.serve_wire
        if w2g is not None or wire is not None:
            now_wall = time.time()
            for frame in frames:
                ts = frame.get("ts")
                if not ts:
                    continue
                if w2g is not None:
                    w2g.record(max(0.0, now_wall - ts[0]))
                if wire is not None:
                    wire.record(max(0.0, now_wall - ts[1]))

    def sync_counters(self, plane: "FederationPlane") -> None:
        """Diff-sync the subscriber's monotonic counts into the registry
        (counters only move forward, so diffing is exact)."""
        sub = self.subscriber
        for field, counter in (
            ("reconnects", plane.reconnects_counter),
            ("resyncs", plane.resyncs_counter),
            ("stalls", plane.stalls_counter),
        ):
            if counter is None:
                continue
            current = getattr(sub, field)
            delta = current - self._synced.get(field, 0)
            if delta > 0:
                counter.inc(delta)
                self._synced[field] = current

    def update_gauges(self) -> None:
        sub = self.subscriber
        now = time.monotonic()
        lag_rv = max(0, sub.wire_rv - (sub.rv or 0))
        # oldest-unpropagated: how long the oldest wire-seen-but-unapplied
        # event has been pending (0 while caught up). The true per-event
        # stamp is unknowable without applying it, so this measures from
        # when the lag BEGAN — a lower bound on the oldest event's age.
        if lag_rv > 0:
            if self.lag_since is None:
                self.lag_since = now
        else:
            self.lag_since = None
        oldest_unpropagated = (now - self.lag_since) if self.lag_since is not None else 0.0
        age = sub.last_frame_age()
        if self.lag_rv_gauge is not None:
            self.lag_rv_gauge.set(lag_rv)
            if age is not None:
                self.lag_seconds_gauge.set(age)
            self.stale_gauge.set(1.0 if self.stale else 0.0)
            watermark = sub.watermark_age()
            if watermark is not None:
                self.watermark_age_gauge.set(watermark)
            delta_age = sub.last_delta_age()
            if delta_age is not None:
                self.last_delta_age_gauge.set(delta_age)
            self.oldest_unpropagated_gauge.set(oldest_unpropagated)

    def freshness(self) -> Dict[str, Any]:
        """This upstream's watermark block for /debug/freshness."""
        sub = self.subscriber
        age = sub.last_frame_age()
        delta_age = sub.last_delta_age()
        watermark = sub.watermark_age()
        now = time.monotonic()
        return {
            "connected": sub.connected,
            "stale": self.stale,
            "rv": sub.rv,
            "wire_rv": sub.wire_rv,
            "lag_rv": max(0, sub.wire_rv - (sub.rv or 0)),
            "last_frame_age_seconds": round(age, 3) if age is not None else None,
            "last_delta_age_seconds": round(delta_age, 3) if delta_age is not None else None,
            "watermark_age_seconds": round(watermark, 3) if watermark is not None else None,
            "oldest_unpropagated_seconds": (
                round(now - self.lag_since, 3) if self.lag_since is not None else 0.0
            ),
        }

    def status(self) -> Dict[str, Any]:
        body = self.subscriber.status()
        body.update(
            {
                "url": self.cfg.url,
                "stale": self.stale,
                "epoch": self.epoch,
                "epoch_changes": self.epoch_changes,
                "objects": self._plane.merge.cluster_object_count(self.name),
                "thread_alive": self.thread.is_alive(),
            }
        )
        return body


class _UpstreamMirror:
    """Sharded mode's parent-side stand-in for ``_Upstream``: no
    subscriber lives here (a merge worker owns it, clocks and all); the
    monitor tick folds the worker-REPORTED status into the same labeled
    gauges, health fields and the stale-transition counter. The
    staleness verdict is MIRRORED, never recomputed — the plane's
    ``staleness_owner`` is ``"merge-workers"`` and exactly one
    component may ever flip ``federation_upstream_stale`` per upstream.
    """

    def __init__(self, plane: "FederationPlane", cfg):
        self.cfg = cfg
        self.name = cfg.name
        self.stale = False  # last mirrored verdict (transition edge detect)
        self.last: Dict[str, Any] = {}
        metrics = plane.metrics
        if metrics is not None:
            label = {"upstream": self.name}
            self.lag_rv_gauge = metrics.gauge("federation_upstream_lag_rv").labels(**label)
            self.lag_seconds_gauge = metrics.gauge("federation_upstream_lag_seconds").labels(**label)
            self.stale_gauge = metrics.gauge("federation_upstream_stale").labels(**label)
            self.watermark_age_gauge = metrics.gauge(
                "federation_upstream_watermark_age_seconds"
            ).labels(**label)
            self.last_delta_age_gauge = metrics.gauge(
                "federation_upstream_last_delta_age_seconds"
            ).labels(**label)
            self.oldest_unpropagated_gauge = metrics.gauge(
                "federation_upstream_oldest_unpropagated_seconds"
            ).labels(**label)
        else:
            self.lag_rv_gauge = None
            self.lag_seconds_gauge = None
            self.stale_gauge = None
            self.watermark_age_gauge = None
            self.last_delta_age_gauge = None
            self.oldest_unpropagated_gauge = None

    def fold(self, body: Dict[str, Any], plane: "FederationPlane") -> None:
        self.last = body
        stale = bool(body.get("stale"))
        if stale and not self.stale and plane.stale_transitions_counter is not None:
            plane.stale_transitions_counter.inc()
        self.stale = stale
        if self.lag_rv_gauge is not None:
            self.lag_rv_gauge.set(body.get("lag_rv") or 0)
            age = body.get("last_frame_age_seconds")
            if age is not None:
                self.lag_seconds_gauge.set(age)
            self.stale_gauge.set(1.0 if stale else 0.0)
            watermark = body.get("watermark_age_seconds")
            if watermark is not None:
                self.watermark_age_gauge.set(watermark)
            delta_age = body.get("last_delta_age_seconds")
            if delta_age is not None:
                self.last_delta_age_gauge.set(delta_age)
            self.oldest_unpropagated_gauge.set(
                body.get("oldest_unpropagated_seconds") or 0.0
            )

    def freshness(self) -> Dict[str, Any]:
        """The ``_Upstream.freshness()`` block, from the last worker
        report (readings age by at most one stats interval)."""
        body = self.last
        return {
            "connected": bool(body.get("connected")),
            "stale": self.stale,
            "rv": body.get("rv"),
            "wire_rv": body.get("wire_rv", 0),
            "lag_rv": body.get("lag_rv", 0),
            "last_frame_age_seconds": body.get("last_frame_age_seconds"),
            "last_delta_age_seconds": body.get("last_delta_age_seconds"),
            "watermark_age_seconds": body.get("watermark_age_seconds"),
            "oldest_unpropagated_seconds": body.get("oldest_unpropagated_seconds", 0.0),
        }

    def status(self, plane: "FederationPlane") -> Dict[str, Any]:
        body = dict(self.last) if self.last else {"name": self.name, "connected": False}
        body.update(
            {
                "url": self.cfg.url,
                "stale": self.stale,
                "objects": plane.merge.cluster_object_count(self.name),
                "mirrored": True,  # worker-reported, not locally measured
            }
        )
        return body


class FederationPlane:
    """Runs the upstream subscriber fleet against the app's FleetView.

    Built when ``federation.enabled``; the app starts it after the serve
    plane (the view exists from construction, so ordering is about log
    hygiene, not correctness) and stops it before the history WAL closes
    (the plane is a view producer)."""

    def __init__(
        self,
        config,
        view,
        *,
        metrics=None,
        token_dir: Optional[str] = None,
        resume_tokens_valid: bool = True,
        trace_collector=None,  # trace.federation.FleetTraceCollector
        trace_ring=None,  # trace.TraceRing: worker anomaly traces land here
        process_export: bool = True,  # metrics.process_export
    ):
        self.config = config
        self.metrics = metrics
        self.token_dir = token_dir
        # joined-trace plane (trace.federation.enabled): upstream
        # subscribers negotiate ?trace=1 and feed it per batch — set
        # BEFORE the upstreams are built (they read it at construction)
        self.trace_collector = trace_collector
        # False when the merged view did NOT restart as a clean
        # continuation of the rv line the tokens were minted against
        # (unclean WAL end, cold/wiped WAL dir): a persisted token would
        # then resume delta-only AHEAD of the recovered state and the
        # lost window's objects would serve stale forever. start()
        # clears the stale tokens so every subscriber re-snapshots and
        # reconciles instead.
        self.resume_tokens_valid = resume_tokens_valid
        self.merge = GlobalMerge(view, drop_stale=config.drop_stale, metrics=metrics)
        # a history-recovered view already holds federated objects: the
        # registry must mirror them or the first reconcile can't delete
        # what vanished upstream while we were down (the app constructs
        # the serve plane — and runs WAL recovery — before this plane)
        seeded = self.merge.seed_from_view()
        if seeded:
            logger.info(
                "Federation registry seeded with %d recovered merged object(s)", seeded
            )
        self.reconnects_counter = metrics.counter("federation_reconnects") if metrics else None
        self.resyncs_counter = metrics.counter("federation_resyncs") if metrics else None
        self.stalls_counter = metrics.counter("federation_heartbeat_stalls") if metrics else None
        self.snapshots_counter = metrics.counter("federation_snapshots") if metrics else None
        self.deltas_counter = metrics.counter("federation_deltas_applied") if metrics else None
        # fan-in batching visibility: deltas/batches = the realized batch
        # size (1.0 means the wire is delivering per-delta — the thing
        # this plane exists to avoid under churn)
        self.batches_counter = metrics.counter("federation_batches_applied") if metrics else None
        self.stale_transitions_counter = (
            metrics.counter("federation_stale_transitions") if metrics else None
        )
        self.connected_gauge = (
            metrics.gauge("federation_upstreams_connected") if metrics else None
        )
        # the freshness plane's cross-cluster histograms, fed by the
        # negotiated per-frame stamps in _on_batch: end-to-end
        # watch->global-view propagation and the serve-wire hop alone
        # (upstream publish -> federator receive). Wall-clock spans
        # across hosts — see ARCHITECTURE "Freshness & SLO plane".
        self.watch_to_global = (
            metrics.histogram("watch_to_global_view_seconds") if metrics else None
        )
        self.serve_wire = (
            metrics.histogram("serve_wire_seconds") if metrics else None
        )
        if metrics is not None:
            # the per-upstream label dimension is bounded by CONFIG, not
            # by the registry's generic 64-set default: widen each
            # family's cardinality cap to fit the declared upstream list
            # (a 100-upstream federation is a legitimate bounded
            # dimension; a pod-uid label still is not)
            cap = max(MAX_LABEL_SETS, len(config.upstreams) + 8)
            for family_name in (
                "federation_upstream_lag_rv",
                "federation_upstream_lag_seconds",
                "federation_upstream_stale",
                "federation_upstream_watermark_age_seconds",
                "federation_upstream_last_delta_age_seconds",
                "federation_upstream_oldest_unpropagated_seconds",
            ):
                metrics.gauge(family_name).max_label_sets = cap
        # sharded fan-in (federation.processes > 0): merge workers own
        # the subscribers AND the staleness verdicts; this plane is the
        # sequencer + mirror. Exactly one staleness owner, ever — the
        # field makes the split greppable and testable instead of a
        # tick-time accident (a sharded deploy must never double-report
        # federation_upstream_stale from two clocks).
        self.processes = int(getattr(config, "processes", 0) or 0)
        self.staleness_owner = "merge-workers" if self.processes > 0 else "monitor"
        self.fanin = None
        self.mirrors: List[_UpstreamMirror] = []
        if self.processes > 0:
            from k8s_watcher_tpu.federate.fanin import ShardedFanin

            if trace_collector is not None:
                # schema forbids the pairing (trace.federation requires
                # processes: 0); guard direct constructions too — merge
                # workers negotiate trace off, so the collector would
                # silently join nothing
                logger.warning(
                    "Joined-trace collection is not available with the sharded "
                    "fan-in (federation.processes > 0); ignoring the collector"
                )
                self.trace_collector = None
            self.fanin = ShardedFanin(
                config,
                self.merge,
                metrics=metrics,
                token_dir=token_dir,
                resume_tokens_valid=resume_tokens_valid,
                trace_ring=trace_ring,
                process_export=process_export,
            )
            self.upstreams: List[_Upstream] = []
            self.mirrors = [_UpstreamMirror(self, u) for u in config.upstreams]
        else:
            self.upstreams = [
                _Upstream(self, u, i) for i, u in enumerate(config.upstreams)
            ]
        # staleness floor mirrors FleetSubscriber's: the wire heartbeats
        # every 2 s when idle, so a sub-3s threshold would call every
        # healthy idle upstream dead between SYNCs
        self.stale_threshold = max(3.0, config.stale_after_seconds)
        self._started = False
        self._started_t = 0.0
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None

    def token_store_for(self, name: str) -> Optional[TokenStore]:
        if not self.token_dir:
            return None
        return TokenStore(os.path.join(self.token_dir, f"{_metric_suffix(name)}.token"))

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FederationPlane":
        self._stop.clear()
        self._started = True
        self._started_t = time.monotonic()
        if self.fanin is not None:
            # token clearing on an invalid resume line happens inside
            # the fan-in (same files, same warning shape)
            self.fanin.start()
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="federate-monitor", daemon=True
            )
            self._monitor.start()
            logger.info(
                "Federation plane started (sharded fan-in): %d merge worker(s) "
                "over %d upstream(s) (stale_after=%.1fs, drop_stale=%s, "
                "staleness_owner=%s)",
                len(self.fanin.endpoints), len(self.config.upstreams),
                self.config.stale_after_seconds, self.config.drop_stale,
                self.staleness_owner,
            )
            return self
        if not self.resume_tokens_valid:
            for upstream in self.upstreams:
                store = upstream.subscriber.token_store
                if store is not None:
                    store.clear()
            if self.token_dir:
                logger.warning(
                    "Merged view did not restart cleanly on its prior rv line; "
                    "cleared %d federation resume token(s) — upstream subscribers "
                    "will re-snapshot and reconcile", len(self.upstreams),
                )
        for upstream in self.upstreams:
            upstream.thread.start()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="federate-monitor", daemon=True
        )
        self._monitor.start()
        logger.info(
            "Federation plane started: %d upstream(s) [%s] (stale_after=%.1fs, drop_stale=%s)",
            len(self.upstreams),
            ", ".join(u.name for u in self.upstreams),
            self.config.stale_after_seconds,
            self.config.drop_stale,
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.fanin is not None:
            self.fanin.stop()
            if self._monitor is not None:
                self._monitor.join(timeout=2.0)
                self._monitor = None
            self._started = False
            return
        for upstream in self.upstreams:
            upstream.subscriber.stop()
        for upstream in self.upstreams:
            if upstream.thread.is_alive():
                upstream.thread.join(timeout=5.0)
                if upstream.thread.is_alive():
                    # subscriber.stop() aborts the blocking read, so this
                    # should never fire; if it does, the caller's next
                    # shutdown step (e.g. the WAL's terminal snapshot)
                    # may race a late delta — say so loudly
                    logger.warning(
                        "Federation subscriber %s did not stop within the join budget",
                        upstream.name,
                    )
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
            self._monitor = None
        for upstream in self.upstreams:
            upstream.sync_counters(self)
        self._started = False

    # -- the monitor tick --------------------------------------------------

    def _monitor_loop(self) -> None:
        interval = max(0.1, min(1.0, self.stale_threshold / 4.0))
        while not self._stop.wait(interval):
            self._tick()

    def _tick(self) -> None:
        if self.fanin is not None:
            self._tick_sharded()
            return
        now = time.monotonic()
        grace_over = now - self._started_t > self.stale_threshold
        connected = 0
        for upstream in self.upstreams:
            sub = upstream.subscriber
            age = sub.last_frame_age()
            if sub.connected:
                connected += 1
            fresh = age is not None and age <= self.stale_threshold
            if fresh:
                upstream.stale = False
            elif grace_over or age is not None:
                # dark past the threshold (or never reached at all once
                # the startup grace lapses)
                if not upstream.stale:
                    upstream.stale = True
                    if self.stale_transitions_counter is not None:
                        self.stale_transitions_counter.inc()
                    logger.warning(
                        "Federation upstream %s went stale (last frame %s ago)",
                        upstream.name, f"{age:.1f}s" if age is not None else "never",
                    )
                if self.config.drop_stale and not upstream.dropped:
                    # under the per-upstream lock (serialized against the
                    # subscriber's apply/reconcile) and with staleness
                    # RE-validated inside it: a reconcile racing this tick
                    # refreshes last_frame_age, so the drop backs off
                    # instead of deleting a just-repopulated cluster.
                    # Flagging before the delete makes any in-between
                    # delta raise ResyncRequired into a full reconcile;
                    # invalidate() makes the next (re)connect re-snapshot
                    # the objects back in — a token-resume must not skip
                    # re-materializing them.
                    with upstream.drop_lock:
                        age_now = sub.last_frame_age()
                        if age_now is None or age_now > self.stale_threshold:
                            upstream.dropped = True
                            sub.invalidate()
                            dropped = self.merge.drop_cluster(upstream.name)
                            logger.warning(
                                "Dropped %d stale object(s) of upstream %s from the global view",
                                dropped, upstream.name,
                            )
            upstream.sync_counters(self)
            upstream.update_gauges()
        if self.connected_gauge is not None:
            self.connected_gauge.set(connected)

    def _tick_sharded(self) -> None:
        """Mirror-only tick (``staleness_owner == "merge-workers"``):
        fold worker-reported per-upstream status into the gauges and
        health state. The staleness verdicts — and the drop-stale arm —
        are computed in the workers, never recomputed here; an upstream
        whose worker is mid-respawn simply keeps its last report."""
        report = self.fanin.upstream_report()
        connected = 0
        for mirror in self.mirrors:
            body = report.get(mirror.name)
            if body:
                mirror.fold(body, self)
            if mirror.last.get("connected"):
                connected += 1
        if self.connected_gauge is not None:
            self.connected_gauge.set(connected)

    # -- freshness ---------------------------------------------------------

    def freshness(self) -> Dict[str, Any]:
        """Per-upstream freshness watermarks + the propagation histogram
        summaries — the federation half of ``GET /debug/freshness``.

        What a watermark does and does NOT guarantee: it is the origin
        stamp of the newest APPLIED delta per upstream — it bounds how
        stale the merged copy of that cluster can be, but encodes no
        cross-cluster happens-before (two clusters' concurrent events
        interleave in arrival order), and cross-host spans compare wall
        clocks (skew shifts readings; the monotonic-local/wall-remote
        split is documented in ARCHITECTURE.md)."""
        out: Dict[str, Any] = {
            "upstreams": (
                {m.name: m.freshness() for m in self.mirrors}
                if self.fanin is not None
                else {u.name: u.freshness() for u in self.upstreams}
            ),
        }
        if self.watch_to_global is not None:
            out["watch_to_global_view_seconds"] = self.watch_to_global.summary()
            out["serve_wire_seconds"] = self.serve_wire.summary()
        return out

    # -- health ------------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        """Per-upstream liveness folded into the /healthz BODY: the plane
        is unhealthy while any upstream is stale (its slice of the global
        view is dark) or any subscriber thread died. The status server
        deliberately keeps this out of the liveness verdict (no 503) —
        restarting the federator cannot revive a dark remote cluster, and
        a liveness kill would wipe the last-known state the keep policy
        serves. Readiness probes and alerts key off ``healthy`` here."""
        if self.fanin is not None:
            upstreams = {m.name: m.status(self) for m in self.mirrors}
            healthy = not self._started or (
                self.fanin.workers_alive()
                and not any(m.stale for m in self.mirrors)
            )
            return {
                "healthy": healthy,
                "started": self._started,
                "upstreams": upstreams,
                "merged_objects": self.merge.object_count(),
                "drop_stale": self.config.drop_stale,
                "stale_after_seconds": self.stale_threshold,
                "staleness_owner": self.staleness_owner,
                "workers": self.fanin.worker_stats(),
            }
        upstreams = {u.name: u.status() for u in self.upstreams}
        healthy = not self._started or all(
            not u.stale and u.thread.is_alive() for u in self.upstreams
        )
        return {
            "healthy": healthy,
            "started": self._started,
            "upstreams": upstreams,
            "merged_objects": self.merge.object_count(),
            "drop_stale": self.config.drop_stale,
            "stale_after_seconds": self.stale_threshold,
            "staleness_owner": self.staleness_owner,
        }

    def process_report(self) -> List[Dict[str, Any]]:
        """Per-merge-worker supervision rows for ``/debug/processes``
        (empty in in-process mode — there are no worker processes)."""
        return self.fanin.process_report() if self.fanin is not None else []
