"""Collective probe kernels.

Two jitted SPMD programs, both built with ``jax.shard_map`` over a
``(hosts, chips)`` mesh so XLA lowers them to ICI collectives:

- ``make_psum_probe``: a minimal-latency ``lax.psum`` of a tiny vector over
  every device — the round-trip time is the ICI *latency* health signal
  (BASELINE.md: "ICI psum probe RTT" is a tracked metric).
- ``make_allreduce_bandwidth_probe``: a large bf16 all-reduce; the achieved
  bus bandwidth (2·(n-1)/n · bytes / t) is the ICI *bandwidth* health
  signal, which catches degraded links that still pass the latency probe.
- ``make_pair_probe``: a 2-device chained ``lax.ppermute`` exchange — the
  per-*link* latency primitive the link prober (probe/links.py) runs over
  every neighbor pair to localize a degraded link/chip.

Static shapes, no data-dependent control flow — each program is traced once
and cached; steady-state probe iterations are pure device execution. Every
builder takes an optional ``IciFaultSpec`` (faults/ici.py) that gates
injected slow/corrupt behavior onto one device for chaos testing.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_watcher_tpu.faults.ici import IciFaultSpec, apply_fault


def _mesh_axes(mesh: Mesh) -> Tuple[str, ...]:
    return tuple(mesh.axis_names)


def _linear_index(mesh: Mesh) -> jax.Array:
    """This device's traced position in ``mesh.devices.flatten()`` order."""
    idx = jax.lax.axis_index(mesh.axis_names[0])
    for name in mesh.axis_names[1:]:
        idx = idx * mesh.shape[name] + jax.lax.axis_index(name)
    return idx


def mesh_device_ids(mesh: Mesh) -> Tuple[int, ...]:
    """Static ``Device.id`` tuple in the same linear order as ``_linear_index``."""
    return tuple(d.id for d in mesh.devices.flatten())


def make_psum_probe(
    mesh: Mesh, inner_iters: int = 1, fault: Optional[IciFaultSpec] = None
) -> Callable[[jax.Array], jax.Array]:
    """Jitted chained ``psum`` of a per-device scalar vector over the mesh.

    One call runs ``inner_iters`` serialized psums (each feeds the next, so
    XLA cannot overlap them) — amortizing host dispatch overhead out of the
    RTT measurement; per-psum latency = call time / inner_iters. Each round
    computes ``psum(x)/n``, so for any ``inner_iters >= 1`` the replicated
    output equals ``sum(x)/n`` — a fixed point that doubles as the
    correctness check. The all-axes special case of
    :func:`make_subaxis_psum_probe`.
    """
    return make_subaxis_psum_probe(mesh, _mesh_axes(mesh), inner_iters, fault)


def make_allreduce_bandwidth_probe(
    mesh: Mesh, payload_bytes: int, fault: Optional[IciFaultSpec] = None
) -> Callable[[jax.Array], jax.Array]:
    """Jitted large all-reduce; input is a ``(n_devices, chunk)`` bf16 array
    sharded along the device axes, output the replicated reduced chunk."""
    axes = _mesh_axes(mesh)
    device_ids = mesh_device_ids(mesh)

    def probe(x: jax.Array) -> jax.Array:
        # x arrives as this device's (1, chunk) shard; reduce across devices
        x = apply_fault(x, fault, device_ids, _linear_index(mesh))
        return jax.lax.psum(x, axes)

    shard = jax.shard_map(probe, mesh=mesh, in_specs=P(axes), out_specs=P())
    return jax.jit(shard)


def psum_probe_input(mesh: Mesh) -> jax.Array:
    """A tiny per-device vector laid out for ``make_psum_probe``.

    On a mesh spanning processes (multi-controller: the global (hosts,
    chips) mesh, or a 2-slice pair submesh) the global array is assembled
    from per-process addressable shards — the explicitly supported
    construction — rather than relying on ``device_put`` accepting a
    partially-addressable sharding."""
    n = mesh.size
    axes = _mesh_axes(mesh)
    sharding = NamedSharding(mesh, P(axes))
    if any(d.process_index != jax.process_index() for d in mesh.devices.flat):
        x = np.arange(1.0, n + 1.0, dtype=np.float32)
        return jax.make_array_from_callback((n,), sharding, lambda idx: x[idx])
    return jax.device_put(jnp.arange(1.0, n + 1.0, dtype=jnp.float32), sharding)


def bandwidth_probe_input(mesh: Mesh, payload_bytes: int) -> jax.Array:
    """A bf16 payload of ~``payload_bytes`` per device for the bandwidth probe."""
    n = mesh.size
    axes = _mesh_axes(mesh)
    chunk = max(128, payload_bytes // 2)  # bf16 = 2 bytes
    x = jnp.ones((n, chunk), dtype=jnp.bfloat16)
    return jax.device_put(x, NamedSharding(mesh, P(axes, None)))


@functools.lru_cache(maxsize=1024)
def make_subaxis_psum_probe(
    mesh: Mesh,
    reduce_axes: Tuple[str, ...],
    inner_iters: int = 1,
    fault: Optional[IciFaultSpec] = None,
) -> Callable[[jax.Array], jax.Array]:
    """Chained ``psum`` over a *subset* of mesh axes.

    Cached (``Mesh`` hashes structurally, ``IciFaultSpec`` is frozen) so
    per-cycle probe loops reuse one jitted program instead of re-tracing —
    a fresh closure each cycle would defeat the jit cache.

    On a hybrid ``(slices, hosts, chips)`` mesh this scopes the collective
    to one fabric: ``("hosts", "chips")`` rides ICI only, all three axes
    add the DCN hop — so ``t(all) - t(ici)`` isolates the cross-slice DCN
    cost. Output is varying over the non-reduced axes (one value per
    group); the fixed-point normalization matches ``make_psum_probe``.
    """
    all_axes = _mesh_axes(mesh)
    if not reduce_axes or any(a not in all_axes for a in reduce_axes):
        raise ValueError(f"reduce_axes {reduce_axes} not a subset of {all_axes}")
    keep = tuple(a for a in all_axes if a not in reduce_axes)
    k = 1
    for a in reduce_axes:
        k *= mesh.shape[a]
    if inner_iters < 1:
        raise ValueError("inner_iters must be >= 1")

    _to_varying = (
        (lambda v: jax.lax.pcast(v, reduce_axes, to="varying")) if hasattr(jax.lax, "pcast")
        else (lambda v: jax.lax.pvary(v, reduce_axes))
    )
    device_ids = mesh_device_ids(mesh)

    def probe(x: jax.Array) -> jax.Array:
        x = apply_fault(x, fault, device_ids, _linear_index(mesh))

        def body(_, carry):
            return _to_varying(jax.lax.psum(carry, reduce_axes) / k)

        y = jax.lax.fori_loop(0, inner_iters - 1, body, x) if inner_iters > 1 else x
        return jax.lax.psum(y, reduce_axes) / k

    shard = jax.shard_map(
        probe, mesh=mesh, in_specs=P(all_axes), out_specs=P(keep) if keep else P()
    )
    return jax.jit(shard)


@functools.lru_cache(maxsize=1024)
def make_hierarchical_probe(
    mesh: Mesh, fault: Optional[IciFaultSpec] = None
) -> Callable[[jax.Array], Tuple[jax.Array, jax.Array]]:
    """Per-slice psum over ICI, then cross-slice psum over DCN. Cached like
    :func:`make_subaxis_psum_probe` — one jitted program per (mesh, fault).

    For a ``(slices, hosts, chips)`` mesh (parallel/mesh.py:
    hybrid_slice_mesh) returns ``(per_slice_sums, global_sum)`` of the
    per-device inputs. Per-slice sums localize a deviating contribution to
    its slice; the global sum is the DCN-aggregated health scalar.

    BOTH outputs are fully replicated: every process must be able to read
    the whole per-slice vector locally (multi-controller mode — one
    process per host — cannot fetch a slices-sharded array, and every
    process's suspect classification needs every slice's sum). The
    replication itself rides the same DCN hop being probed: the per-slice
    scalars are scattered into one-hot vectors and psum'd over ``slices``.
    """
    all_axes = _mesh_axes(mesh)
    if all_axes[0] != "slices" or len(all_axes) < 2:
        raise ValueError(f"hierarchical probe wants ('slices', ...) axes, got {all_axes}")
    ici_axes = all_axes[1:]
    n_slices = mesh.shape["slices"]
    device_ids = mesh_device_ids(mesh)

    def probe(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = apply_fault(x, fault, device_ids, _linear_index(mesh))
        per_slice = jax.lax.psum(x, ici_axes)  # ICI: invariant within a slice
        # scatter my slice's sum into a one-hot vector; the slices-psum
        # assembles the replicated full vector (the DCN hop)
        slice_idx = jax.lax.axis_index("slices")
        vec = jnp.zeros((n_slices,), dtype=x.dtype).at[slice_idx].set(per_slice[0])
        all_sums = jax.lax.psum(vec, "slices")  # the ONE DCN hop
        # the global sum is a free local reduction of the replicated vector
        # — a second slices-psum would add a whole DCN round-trip per cycle
        return all_sums, jnp.sum(all_sums)

    shard = jax.shard_map(
        probe, mesh=mesh, in_specs=P(all_axes), out_specs=(P(), P())
    )
    return jax.jit(shard)


@functools.lru_cache(maxsize=1024)
def make_slice_pair_probe(
    mesh: Mesh, inner_iters: int = 1, fault: Optional[IciFaultSpec] = None
) -> Tuple[Callable[[jax.Array], jax.Array], float]:
    """Chained ``slices``-axis psum over a 2-slice pair submesh, closed
    with ONE full-mesh psum so the output is a replicated scalar.

    The slices-only chain is the timed quantity — each round exchanges
    every (host, chip) position with its counterpart in the other slice,
    pure inter-slice DCN traffic. The single trailing full-mesh reduction
    exists so every member process holds the result locally: in
    multi-controller mode the completion fence (host scalar readback)
    must not require a remote shard, and its constant cost cancels in the
    pair-vs-pair outlier comparison.

    Returns ``(jitted_fn, expected)``: with input ``psum_probe_input``
    (1..n), each position's chained value converges to its cross-slice
    mean, and the closing sum counts every device's copy — so the scalar
    equals ``n(n+1)/2`` exactly; any deviation means a member corrupted
    the payload. Cached like the other builders (per-cycle re-walks must
    not re-trace).
    """
    all_axes = _mesh_axes(mesh)
    if all_axes[0] != "slices" or mesh.shape["slices"] != 2:
        raise ValueError(f"slice-pair probe wants a ('slices'=2, ...) mesh, got {dict(mesh.shape)}")
    if inner_iters < 1:
        raise ValueError("inner_iters must be >= 1")
    device_ids = mesh_device_ids(mesh)
    _to_varying = (
        (lambda v: jax.lax.pcast(v, ("slices",), to="varying")) if hasattr(jax.lax, "pcast")
        else (lambda v: jax.lax.pvary(v, ("slices",)))
    )

    def probe(x: jax.Array) -> jax.Array:
        x = apply_fault(x, fault, device_ids, _linear_index(mesh))

        def body(_, carry):
            return _to_varying(jax.lax.psum(carry, ("slices",)) / 2.0)

        y = jax.lax.fori_loop(0, inner_iters - 1, body, x) if inner_iters > 1 else x
        # cast back to varying: the closing all-axes psum reduces over
        # 'slices' too, and a slices-invariant operand would be rejected
        y = _to_varying(jax.lax.psum(y, ("slices",)) / 2.0)
        return jax.lax.psum(y, all_axes)

    shard = jax.shard_map(probe, mesh=mesh, in_specs=P(all_axes), out_specs=P())
    n = mesh.size
    return jax.jit(shard), n * (n + 1) / 2.0


@functools.lru_cache(maxsize=4096)
def make_pair_probe(
    dev_a: jax.Device,
    dev_b: jax.Device,
    inner_iters: int = 8,
    fault: Optional[IciFaultSpec] = None,
) -> Tuple[Callable[[jax.Array], jax.Array], Mesh, float]:
    """A chained 2-device ``ppermute`` exchange over the (a, b) link.

    Cached on ``(devices, inner_iters, fault)``: the link prober re-probes
    every mesh edge each cycle, and a fresh closure per cycle would defeat
    the jit cache (keyed on function identity) — O(links) recompiles per
    probe interval. ``jax.Device`` and the frozen ``IciFaultSpec`` are both
    hashable; after a backend restart new Device objects simply miss.

    Returns ``(jitted_fn, pair_mesh, expected)``: the fn takes the pair
    input from :func:`pair_probe_input`, runs ``inner_iters`` serialized
    exchanges (each feeds the next — XLA cannot overlap them), and returns
    the replicated psum of the final values. With an even ``inner_iters``
    every value is back home, so the output equals ``expected`` (= 1+2);
    any deviation means a member corrupted the payload in flight.
    Per-hop latency = call time / inner_iters.
    """
    if inner_iters < 2 or inner_iters % 2:
        raise ValueError("inner_iters must be an even integer >= 2")
    mesh = Mesh(np.array([dev_a, dev_b]), ("pair",))
    ids = (dev_a.id, dev_b.id)

    def probe(x: jax.Array) -> jax.Array:
        x = apply_fault(x, fault, ids, jax.lax.axis_index("pair"))

        def body(_, carry):
            return jax.lax.ppermute(carry, "pair", [(0, 1), (1, 0)])

        y = jax.lax.fori_loop(0, inner_iters, body, x)
        return jax.lax.psum(y, "pair")

    shard = jax.shard_map(probe, mesh=mesh, in_specs=P("pair"), out_specs=P())
    return jax.jit(shard), mesh, 3.0


def pair_probe_input(mesh: Mesh) -> jax.Array:
    """Per-member scalars (1.0, 2.0) laid out over the pair mesh.

    When the pair spans processes (an inter-host link in multi-controller
    mode), ``device_put`` can't place the remote shard — build the global
    array from per-process addressable shards instead."""
    sharding = NamedSharding(mesh, P("pair"))
    if any(d.process_index != jax.process_index() for d in mesh.devices.flat):
        x = np.arange(1.0, 3.0, dtype=np.float32)
        return jax.make_array_from_callback((2,), sharding, lambda idx: x[idx])
    return jax.device_put(jnp.arange(1.0, 3.0, dtype=jnp.float32), sharding)


def allreduce_bus_bandwidth_gbps(payload_bytes: int, n_devices: int, seconds: float) -> float:
    """Standard all-reduce bus-bandwidth formula: 2·(n-1)/n · S / t."""
    if seconds <= 0 or n_devices <= 0:
        return 0.0
    moved = 2.0 * (n_devices - 1) / n_devices * payload_bytes
    return moved / seconds / 1e9
