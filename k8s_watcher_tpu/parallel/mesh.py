"""Device-mesh construction.

The probe runs over a 2-D ``(hosts, chips)`` mesh so collectives can be
scoped per axis: the ``chips`` axis rides intra-host ICI, the ``hosts`` axis
rides inter-host ICI (same pod slice) or DCN (cross-slice). On a single
host the mesh degenerates to ``(1, n)`` and everything still compiles — the
same code path covers acceptance configs #3 (v4-8 single host) and #4
(v5e-16, 4 hosts) from BASELINE.md.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """``jax.distributed.initialize`` for multi-host probes.

    Args default from the standard JAX env vars / GKE JobSet injection;
    returns False (no-op) when running single-process. Safe to call twice.
    """
    # NB: must not touch jax.process_count() (or any device API) here — that
    # would initialize the backend and make distributed.initialize fail.
    is_init = getattr(jax.distributed, "is_initialized", None)  # absent on old jax
    if is_init is not None and is_init():
        return True
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return False
    num_processes = num_processes or int(os.environ.get("JAX_NUM_PROCESSES", "0")) or None
    process_id = process_id if process_id is not None else int(os.environ.get("JAX_PROCESS_ID", "0"))
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except Exception as exc:  # already-initialized or misconfigured env
        logger.warning("jax.distributed.initialize failed: %s", exc)
        return False


def host_chip_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A ``(hosts, chips)`` mesh over ``devices`` (default: all devices).

    Devices are grouped by ``process_index`` — JAX's unit of host locality —
    so the ``chips`` axis only ever crosses intra-host links.
    """
    devices = list(devices if devices is not None else jax.devices())
    by_host: dict = {}
    for d in devices:
        by_host.setdefault(d.process_index, []).append(d)
    counts = {len(v) for v in by_host.values()}
    if len(counts) != 1:
        # ragged host sizes (unhealthy slice): fall back to a 1×N mesh so the
        # probe can still run and report the asymmetry
        logger.warning("Ragged devices-per-host %s; using flat mesh", sorted(counts))
        return flat_mesh(devices)
    per_host = counts.pop()
    grid = np.array(
        [dev for host in sorted(by_host) for dev in sorted(by_host[host], key=lambda d: d.id)]
    ).reshape(len(by_host), per_host)
    return Mesh(grid, ("hosts", "chips"))


def flat_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D ``(chips,)`` mesh (single-host or ragged fallback)."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices).reshape(1, len(devices)), ("hosts", "chips"))


def hybrid_slice_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    n_slices: Optional[int] = None,
) -> Mesh:
    """A 3-D ``(slices, hosts, chips)`` mesh for multi-slice deployments.

    The ``slices`` axis crosses pod-slice boundaries and therefore rides
    **DCN**; ``hosts``/``chips`` stay inside a slice on **ICI** — so
    collectives scoped per axis measure exactly the fabric they name
    (SURVEY.md §2.11: ICI for in-slice probes, DCN for cross-slice
    aggregation). Slice membership comes from ``Device.slice_index`` where
    the runtime exposes it (real multi-slice TPU); otherwise devices are
    split into ``n_slices`` equal contiguous groups (virtual/test meshes).
    """
    devices = list(devices if devices is not None else jax.devices())
    by_slice: dict = {}
    for d in devices:
        by_slice.setdefault(getattr(d, "slice_index", None), []).append(d)
    if None in by_slice:
        # no runtime slice info (CPU/virtual meshes): carve equal groups
        n_slices = n_slices or 1
        if len(devices) % n_slices:
            raise ValueError(f"{len(devices)} devices do not split into {n_slices} slices")
        per = len(devices) // n_slices
        groups = [devices[i * per:(i + 1) * per] for i in range(n_slices)]
    else:
        # the runtime knows the real slice boundaries — config must agree,
        # even for a single slice: carving one physical slice into fake
        # "slices" would report DCN numbers measured over ICI links
        if n_slices is not None and n_slices != len(by_slice):
            raise ValueError(f"runtime reports {len(by_slice)} slices, config says {n_slices}")
        groups = [by_slice[s] for s in sorted(by_slice)]
        sizes = {len(g) for g in groups}
        if len(sizes) != 1:
            raise ValueError(f"ragged slice sizes {sorted(len(g) for g in groups)}")

    # each slice group becomes a (hosts, chips) submesh, stacked on axis 0
    subgrids = []
    for group in groups:
        sub = host_chip_mesh(group)
        subgrids.append(np.asarray(sub.devices))
    shapes = {g.shape for g in subgrids}
    if len(shapes) != 1:
        raise ValueError(f"slices have differing (hosts, chips) shapes: {sorted(shapes)}")
    grid = np.stack(subgrids, axis=0)
    return Mesh(grid, ("slices", "hosts", "chips"))
