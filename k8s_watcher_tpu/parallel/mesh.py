"""Device-mesh construction.

The probe runs over a 2-D ``(hosts, chips)`` mesh so collectives can be
scoped per axis: the ``chips`` axis rides intra-host ICI, the ``hosts`` axis
rides inter-host ICI (same pod slice) or DCN (cross-slice). On a single
host the mesh degenerates to ``(1, n)`` and everything still compiles — the
same code path covers acceptance configs #3 (v4-8 single host) and #4
(v5e-16, 4 hosts) from BASELINE.md.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """``jax.distributed.initialize`` for multi-host probes.

    Args default from the standard JAX env vars / GKE JobSet injection;
    returns False (no-op) when running single-process. Safe to call twice.
    """
    if jax.process_count() > 1:
        return True  # already initialized
    coordinator_address = coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return False
    num_processes = num_processes or int(os.environ.get("JAX_NUM_PROCESSES", "0")) or None
    process_id = process_id if process_id is not None else int(os.environ.get("JAX_PROCESS_ID", "0"))
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except Exception as exc:  # already-initialized or misconfigured env
        logger.warning("jax.distributed.initialize failed: %s", exc)
        return False


def host_chip_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A ``(hosts, chips)`` mesh over ``devices`` (default: all devices).

    Devices are grouped by ``process_index`` — JAX's unit of host locality —
    so the ``chips`` axis only ever crosses intra-host links.
    """
    devices = list(devices if devices is not None else jax.devices())
    by_host: dict = {}
    for d in devices:
        by_host.setdefault(d.process_index, []).append(d)
    counts = {len(v) for v in by_host.values()}
    if len(counts) != 1:
        # ragged host sizes (unhealthy slice): fall back to a 1×N mesh so the
        # probe can still run and report the asymmetry
        logger.warning("Ragged devices-per-host %s; using flat mesh", sorted(counts))
        return flat_mesh(devices)
    per_host = counts.pop()
    grid = np.array(
        [dev for host in sorted(by_host) for dev in sorted(by_host[host], key=lambda d: d.id)]
    ).reshape(len(by_host), per_host)
    return Mesh(grid, ("hosts", "chips"))


def flat_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-D ``(chips,)`` mesh (single-host or ragged fallback)."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices).reshape(1, len(devices)), ("hosts", "chips"))
