"""Mesh/collective helpers for the probe plane (SURVEY.md §2.10-2.11).

The reference had no parallelism or comm backend at all; the TPU build's
SPMD surface is the in-slice health probe — JAX/XLA collectives over ICI
(in-slice) and DCN (cross-slice), never NCCL/MPI.
"""

from k8s_watcher_tpu.parallel.mesh import (  # noqa: F401
    host_chip_mesh,
    flat_mesh,
    initialize_multihost,
)
from k8s_watcher_tpu.parallel.collectives import make_psum_probe, make_allreduce_bandwidth_probe  # noqa: F401
