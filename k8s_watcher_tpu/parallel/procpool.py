"""Generic supervised worker-pool wire: the ONE process-pool implementation
the ingest tier (PR 15, ``watch/procpool.py``) and the federation fan-in
tier (PR 16, ``federate/fanin.py``) share.

What lives here is everything about a supervised child process that is
NOT specific to what the child streams:

- the length-prefixed pipe wire: one ``multiprocessing.Connection`` frame
  per message, payload a dict packed msgpack-first (JSON fallback), the
  first byte tagging the codec so a mixed pair still interoperates;
- the parent-side ``SupervisedEndpoint``: spawn (spawn start method —
  never fork a threaded parent), per-spawn sequence accounting (pipes
  cannot reorder, so a seq mismatch is a counted codec/framing tripwire,
  never a silent hole), hello/stats/eos control frames, cumulative
  counters across incarnations, and the respawn loop — jittered
  exponential backoff, reset after a spawn that delivered work (the
  federate-client idiom);
- the worker-side contract (documented, enforced by the two callers):
  hello first, then ``{"s": seq, "b": [...]}`` payload messages with
  ``seq`` counting ITEMS (not messages), ``{"stats": {...}}`` at a
  bounded interval, and ``{"eos": True}`` exactly once on a clean
  SIGTERM drain. An unexpected EOF (no EOS) is the respawn path.

The two tiers differ only in what a payload item IS (a watch event
6-tuple vs a merged-delta 7-tuple) and what the child runs (shard watch
streams vs upstream fleet subscribers) — both stay in their own modules.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import multiprocessing
import random
import threading
import time
from typing import Any, Dict, Iterator, Optional

from ..trace.trace import trace_from_wire

logger = logging.getLogger(__name__)

try:  # the serve plane's optional codec dependency, reused for the wire
    import msgpack  # type: ignore
except Exception:  # noqa: BLE001 — absence is a supported configuration
    msgpack = None

TAG_MSGPACK = b"M"
TAG_JSON = b"J"

#: sentinel: "use this module's own msgpack import" (callers pass their
#: OWN module global instead so tests can strip one side's codec)
_DEFAULT_CODEC = object()


def pack(obj: Dict[str, Any], codec: Any = _DEFAULT_CODEC) -> bytes:
    """Dict -> tagged wire bytes. ``codec`` is the msgpack module to use
    (or None to force the JSON fallback); defaults to this module's."""
    mp = msgpack if codec is _DEFAULT_CODEC else codec
    if mp is not None:
        return TAG_MSGPACK + mp.packb(obj, use_bin_type=True)
    return TAG_JSON + json.dumps(obj).encode()


def unpack(data: bytes, codec: Any = _DEFAULT_CODEC) -> Dict[str, Any]:
    mp = msgpack if codec is _DEFAULT_CODEC else codec
    tag, payload = data[:1], data[1:]
    if tag == TAG_MSGPACK:
        if mp is None:
            raise ValueError("msgpack frame received but msgpack is unavailable")
        return mp.unpackb(payload, raw=False)
    if tag == TAG_JSON:
        return json.loads(payload)
    raise ValueError(f"unknown wire codec tag {tag!r}")


class SupervisedEndpoint:
    """One supervised worker subprocess, presented as a message stream.

    ``frames()`` is the parent-side generator: it spawns the worker,
    yields each payload message dict (anything carrying ``"b"``) in pipe
    order, folds hello/stats via overridable hooks, and on an unexpected
    death (EOF without EOS) respawns with jittered exponential backoff.
    Subclasses provide the child ``target`` and interpret the payload.

    Counter names are injected so each tier keeps its established
    metrics vocabulary (``ingest_wire_gaps`` vs ``fanin_wire_gaps``).
    """

    def __init__(
        self,
        plan: Any,
        *,
        target,
        name: str,
        index: int,
        metrics=None,
        heartbeat=None,
        respawn_backoff: float = 0.5,
        respawn_backoff_max: float = 15.0,
        gap_counter: Optional[str] = None,
        respawn_counter: Optional[str] = None,
        label: str = "worker",
        respawn_note: str = "",
        process_label: Optional[str] = None,
        trace_ring=None,
        rollup_exclude=frozenset(),
    ):
        self.plan = plan
        self.target = target
        self.name = name
        self.index = index
        self.metrics = metrics
        self.heartbeat = heartbeat or (lambda: None)
        self.respawn_backoff = respawn_backoff
        self.respawn_backoff_max = respawn_backoff_max
        self.gap_counter = gap_counter
        self.respawn_counter = respawn_counter
        self.label = label
        self.respawn_note = respawn_note
        #: the worker's ``process`` label value on every folded series
        #: (``ingest-shard-2``, ``merge-worker-0``); also the
        #: /debug/processes key
        self.process_label = process_label or name
        #: parent TraceRing imported worker traces land in (the shared
        #: /debug/trace ring when tracing is wired)
        self.trace_ring = trace_ring
        #: counter names whose UNLABELED parent totals another fold path
        #: already owns (ad-hoc stats fields) — fold_sample skips the
        #: unlabeled rollup for these so nothing double-counts
        self.rollup_exclude = frozenset(rollup_exclude)
        self.last_hello: Optional[Dict[str, Any]] = None
        self.last_stats: Dict[str, Any] = {}
        self.last_stats_at: Optional[float] = None
        self.spawns = 0
        self.respawns = 0
        self.wire_gaps = 0
        self.stats_frames = 0
        self.stale_stats_discarded = 0
        self.traces_imported = 0
        # cumulative payload ITEMS delivered across incarnations (the
        # seq unit): watch events for ingest, merged deltas for fan-in
        self.events_delivered = 0
        # per-spawn-generation registry-fold watermarks: swapped for a
        # fresh dict in on_spawn so a respawned worker's from-zero
        # counters fold as new deltas, never as a backwards step
        self._sample_watermarks: Dict[str, Any] = {}
        self._fold_errors = 0
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._proc: Optional[multiprocessing.process.BaseProcess] = None
        self._conn = None
        self._ctx = multiprocessing.get_context("spawn")

    # -- subclass hooks ------------------------------------------------------

    def on_spawn(self) -> None:
        """Called after each (re)spawn, before any frame is read — reset
        per-incarnation fold state (cumulative in-child counters restart
        at zero; parent-side totals must not). Subclasses overriding this
        must call ``super().on_spawn()``."""
        self._sample_watermarks = {}

    def on_hello(self, hello: Dict[str, Any]) -> None:
        self.last_hello = hello

    def on_stats(self, stats: Dict[str, Any]) -> None:
        """Fold one stats frame: the generic registry/trace export first
        (when the frame carries them), then whatever the tier subclass
        adds. Subclasses must call ``super().on_stats(stats)``."""
        self.last_stats = stats
        self.last_stats_at = time.monotonic()
        self.stats_frames += 1
        self._fold_exported(stats)

    def on_eos(self, msg: Dict[str, Any]) -> None:
        """A clean drain's terminal message (stats already folded)."""

    def _fold_exported(self, stats: Dict[str, Any]) -> None:
        """Fold the worker's exported registry sample + completed traces
        off one stats frame. Defensive by contract: the fold runs on the
        pump thread, so a malformed frame must count and continue, never
        kill the event stream."""
        registry = stats.get("registry")
        if registry is not None and self.metrics is not None:
            try:
                self.metrics.fold_sample(
                    registry,
                    process=self.process_label,
                    watermarks=self._sample_watermarks,
                    rollup_exclude=self.rollup_exclude,
                )
            except Exception:
                self._fold_errors += 1
                self.metrics.counter("process_sample_fold_errors").inc()
                if self._fold_errors == 1:  # first failure tells the story
                    logger.warning(
                        "%s %d: registry sample fold failed (counted from now on)",
                        self.label, self.index, exc_info=True,
                    )
        traces = stats.get("traces")
        if traces and self.trace_ring is not None:
            imported = 0
            for wire in traces:
                try:
                    self.trace_ring.record(
                        trace_from_wire(wire, process=self.process_label)
                    )
                    imported += 1
                except Exception:  # noqa: BLE001 — same never-kill contract
                    continue
            self.traces_imported += imported
            if imported and self.metrics is not None:
                self.metrics.counter("process_traces_imported").inc(imported)

    def report(self) -> Dict[str, Any]:
        """One worker's /debug/processes row: liveness, spawn generation,
        stats freshness and the supervision counters."""
        proc = self._proc
        last = self.last_stats_at
        return {
            "process": self.process_label,
            "alive": bool(proc is not None and proc.is_alive()),
            "pid": proc.pid if proc is not None else None,
            "generation": self.spawns,
            "respawns": self.respawns,
            "wire_gaps": self.wire_gaps,
            "events_delivered": self.events_delivered,
            "stats_frames": self.stats_frames,
            "stale_stats_discarded": self.stale_stats_discarded,
            "traces_imported": self.traces_imported,
            "last_stats_age_seconds": (
                round(time.monotonic() - last, 3) if last is not None else None
            ),
        }

    # -- lifecycle -----------------------------------------------------------

    def _spawn(self):
        with self._lock:
            if self._stop.is_set():
                return None
            generation = self.spawns + 1
            plan = self.plan
            if dataclasses.is_dataclass(plan) and hasattr(plan, "generation"):
                # stamp the spawn generation into the child's plan: the
                # worker echoes it on every stats frame ("g"), and the
                # parent discards any frame whose generation is not the
                # CURRENT incarnation's — a stale frame drained off a
                # killed worker's pipe must never fold into fresh
                # watermarks (it would double-count the old incarnation)
                plan = dataclasses.replace(plan, generation=generation)
            recv_conn, send_conn = self._ctx.Pipe(duplex=False)
            proc = self._ctx.Process(
                target=self.target,
                args=(plan, send_conn),
                name=self.name,
                daemon=True,  # safety net only; stop() drains via SIGTERM
            )
            proc.start()
            send_conn.close()  # child holds the write end now; EOF tracks it
            self._proc, self._conn = proc, recv_conn
            self.spawns = generation
            return recv_conn

    def _reap(self) -> None:
        with self._lock:
            proc, conn = self._proc, self._conn
            self._proc = self._conn = None
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
        if proc is not None:
            proc.join(timeout=5.0)
            if proc.is_alive():
                proc.kill()
                proc.join(timeout=5.0)

    @property
    def pid(self) -> Optional[int]:
        proc = self._proc
        return proc.pid if proc is not None else None

    def stop(self) -> None:
        """SIGTERM the worker (clean drain: it flushes durable state,
        sends EOS, closes the pipe — which unblocks the parent reader)."""
        self._stop.set()
        proc = self._proc
        if proc is not None and proc.is_alive():
            try:
                proc.terminate()
            except OSError:
                pass

    def kill(self) -> None:
        """Hard-stop a worker that ignored the drain grace."""
        self._stop.set()
        proc = self._proc
        if proc is not None and proc.is_alive():
            proc.kill()
        conn = self._conn
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    # -- stream --------------------------------------------------------------

    def frames(self) -> Iterator[Dict[str, Any]]:
        backoff = self.respawn_backoff
        while not self._stop.is_set():
            conn = self._spawn()
            if conn is None:
                return
            self.on_spawn()
            clean_eos = False
            delivered_this_spawn = 0
            expected_seq = 0
            try:
                while True:
                    try:
                        data = conn.recv_bytes()
                    except (EOFError, OSError):
                        break  # worker died (or drained and closed)
                    self.heartbeat()  # any frame = a live worker process
                    msg = unpack(data)
                    batch = msg.get("b")
                    if batch is not None:
                        seq = msg.get("s", expected_seq)
                        if seq != expected_seq:
                            # pipes cannot reorder; this is a tripwire for
                            # codec/framing bugs, counted, never silent
                            self.wire_gaps += 1
                            if self.metrics is not None and self.gap_counter:
                                self.metrics.counter(self.gap_counter).inc()
                        expected_seq = seq + len(batch)
                        delivered_this_spawn += len(batch)
                        self.events_delivered += len(batch)
                        yield msg
                        continue
                    if "stats" in msg:
                        gen = msg.get("g")
                        if gen is not None and gen != self.spawns:
                            # a frame from a previous incarnation (stale
                            # pipe drain after a kill->respawn): folding
                            # it against the fresh watermarks would
                            # double-count — discard, visibly
                            self.stale_stats_discarded += 1
                            if self.metrics is not None:
                                self.metrics.counter(
                                    "procpool_stale_stats_discarded"
                                ).inc()
                            continue
                        self.on_stats(msg["stats"])
                        continue
                    if "hello" in msg:
                        self.on_hello(msg["hello"])
                        continue
                    if msg.get("eos"):
                        self.on_eos(msg)
                        clean_eos = True
                        break
            finally:
                self._reap()
            if clean_eos or self._stop.is_set():
                return
            # unexpected death: respawn and resume from durable state. A
            # spawn that delivered work was healthy — reset the escalation
            # so one crash after hours of service doesn't pay the
            # accumulated backoff.
            if delivered_this_spawn > 0:
                backoff = self.respawn_backoff
            self.respawns += 1
            if self.metrics is not None and self.respawn_counter:
                self.metrics.counter(self.respawn_counter).inc()
            logger.warning(
                "%s %d died (spawn %d); respawning in <=%.1fs%s",
                self.label, self.index, self.spawns, backoff * 1.5,
                f" ({self.respawn_note})" if self.respawn_note else "",
            )
            if self._stop.wait(backoff * (0.5 + random.random())):
                return
            backoff = min(backoff * 2.0, self.respawn_backoff_max)
