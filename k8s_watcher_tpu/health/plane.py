"""HealthPlane: signal collection + the detector tick loop.

The detector (``health/detector.py``) is pure fusion/verdict logic; this
plane feeds it from the signal planes the platform already runs — no new
probes, per ARGUS (PAPERS.md):

- **phase** (``serve/view.py``): per tick the plane scans the FleetView's
  pod objects and tracks phase transitions itself. A node's reading is
  ``max(median of its last few Pending→Running latencies, age of its
  oldest still-Pending pod)`` — the in-flight term is what catches the
  host whose pods never finish starting (a completed-latency-only signal
  would arrive exactly as late as the straggle it measures). Peer group =
  the node's slice, joined through the view's slice objects
  (``workers[].node``); nodes in no slice form one shared "unsliced"
  group. On a federated view the merged objects carry cluster-prefixed
  keys, so one federator scores the whole fleet.
- **probe** (``probe/``): completed probe reports are pushed in via
  ``observe_report`` (chained after the remediation policy's observer).
  Suspect-device triangulation reuses ``remediate/policy.py``'s
  extraction verbatim — one implication algorithm, not two — and becomes
  direct evidence; per-node link-RTT medians become a graded peer signal
  (all nodes of one report are slice peers). Each report is consumed by
  exactly one tick, so hysteresis counts *reports* for this source.
- **freshness** (``federate/plane.py``): per-upstream watermark age and
  oldest-unpropagated backlog, peers = the upstream set. An idle-but-
  healthy cluster and one behind a lagging apiserver look identical from
  stamps alone; peers disambiguate (the fleet churns, the laggard ages).
  Below three upstreams the TrendTracker fallback judges each upstream
  against its own healthy baseline instead (documented caveat: a cluster
  idle since boot can eventually trip it).
- **trace** (``trace/``): per-stage mean latency over the tick window
  (cumulative histogram differencing), trend-judged. Stage subjects
  surface pipeline pathology in /debug/health; they never reach the
  actuator.

The tick's own cost is measured into ``health_tick_seconds`` and gated in
``bench --smoke`` (a detector that stalls the process is itself a
straggler source).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from k8s_watcher_tpu.config.schema import HealthConfig
from k8s_watcher_tpu.health.detector import HealthDetector, Observation

logger = logging.getLogger(__name__)

#: per-metric absolute z-denominator floors (see Observation.floor)
PHASE_LATENCY_FLOOR_S = 0.25
WATERMARK_FLOOR_S = 0.5
UNPROPAGATED_FLOOR_S = 1.0
LINK_RTT_FLOOR_MS = 0.05

#: completed Pending->Running latencies remembered per node (median of
#: these is the "recent startup cost"; small so recovery is quick)
RECENT_LATENCIES = 3


class HealthPlane:
    """Runs the detector against the app's live planes."""

    def __init__(
        self,
        config: HealthConfig,
        *,
        metrics=None,
        view=None,  # serve.FleetView (phase source)
        federation=None,  # federate.FederationPlane (freshness source)
        sink=None,  # notification sink (TPU_HEALTH payloads)
        environment: str = "",
    ):
        self.config = config
        self.metrics = metrics
        self.view = view
        self.federation = federation
        self.environment = environment
        self.detector = HealthDetector(
            suspect_z=config.suspect_z,
            confirm_cycles=config.confirm_cycles,
            decay_cycles=config.decay_cycles,
            metrics=metrics,
            sink=sink,
        )
        self._tick_seconds = (
            metrics.histogram("health_tick_seconds") if metrics is not None else None
        )
        # phase-source state: pod key -> (phase, monotonic since)
        self._pods: Dict[str, Tuple[str, float]] = {}
        self._node_latency: Dict[str, collections.deque] = {}
        # probe-source state: reports pushed from the agent thread (or a
        # drill), drained once per tick
        self._report_lock = threading.Lock()
        self._pending_reports: collections.deque = collections.deque(maxlen=8)
        # trace-source state: per-stage (count, sum) at the previous tick
        self._stage_prev: Dict[str, Tuple[int, float]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    def arm_actuator(self, actuator) -> None:
        """Attach the (shared or dedicated) budgeted NodeActuator —
        called post-campaign so standbys never multiply the fences."""
        self.detector.actuator = actuator

    def start(self) -> "HealthPlane":
        self._stop.clear()
        self._started = True
        self._thread = threading.Thread(
            target=self._loop, name="health-plane", daemon=True
        )
        self._thread.start()
        sources = [
            name for name, on in (
                ("probe", self.config.source_probe),
                ("phase", self.config.source_phase),
                ("freshness", self.config.source_freshness),
                ("trace", self.config.source_trace),
            ) if on
        ]
        logger.info(
            "Health plane started (tick=%.1fs, suspect_z=%.1f, confirm=%d, decay=%d, "
            "sources=%s, actuator=%s)",
            self.config.tick_seconds, self.config.suspect_z,
            self.config.confirm_cycles, self.config.decay_cycles,
            "+".join(sources), "armed" if self.detector.actuator else "none",
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self._started = False

    def _loop(self) -> None:
        while not self._stop.wait(self.config.tick_seconds):
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 — a bad tick must not kill the plane
                logger.error("Health tick failed: %s", exc)
                if self.metrics is not None:
                    self.metrics.counter("health_tick_errors").inc()

    # -- signal intake -----------------------------------------------------

    def observe_report(self, report) -> None:
        """Queue one completed probe report for the next tick (called on
        the probe agent's thread; also the chaos-drill injection point)."""
        if not self.config.source_probe:
            return
        with self._report_lock:
            self._pending_reports.append(report)

    # -- the tick ----------------------------------------------------------

    def tick(self) -> Dict[str, Any]:
        t0 = time.perf_counter()
        observations: List[Observation] = []
        evidence: Dict[Tuple[str, str], List[str]] = {}
        if self.config.source_phase and self.view is not None:
            self._collect_phase(observations)
        if self.config.source_freshness and self.federation is not None:
            self._collect_freshness(observations)
        if self.config.source_probe:
            self._collect_probe(observations, evidence)
        if self.config.source_trace and self.metrics is not None:
            self._collect_trace(observations)
        summary = self.detector.tick(observations, evidence)
        if self._tick_seconds is not None:
            self._tick_seconds.record(time.perf_counter() - t0)
        if self._ticks_counter is not None:
            self._ticks_counter.inc()
        return summary

    @property
    def _ticks_counter(self):
        return self.detector._ticks_counter

    def _collect_phase(self, observations: List[Observation]) -> None:
        """Per-node phase-transition latencies off the view's fleet
        state. Columnar core: the zero-copy ``fleet_handle`` — per-pod
        key/phase/node sequences decoded from the int columns at most
        once per dirty generation, no per-kind object tables built at
        all (phases normalized to the fixed POD_PHASES vocabulary).
        Dict core: the bulk per-kind ``snapshot_tables`` walk (one
        object walk per rv, cached on the view). Identical transition
        logic either way — the columnar smoke gates verdict identity."""
        now = time.monotonic()
        view = self.view
        if getattr(view, "columnar", False) and hasattr(view, "fleet_handle"):
            _rv, handle = view.fleet_handle()
            slice_objs = handle.slices
            live_keys = set(handle.keys)
            pod_triples = zip(handle.keys, handle.phases, handle.nodes)
        else:
            _rv, tables = view.snapshot_tables()
            slice_objs = tables.get("slice", ())
            pods = tables.get("pod", ())
            live_keys = {obj.get("key") for obj in pods}
            pod_triples = (
                (obj.get("key"), obj.get("phase") or "Unknown", obj.get("node"))
                for obj in pods
            )
        node_slice: Dict[str, str] = {}
        for obj in slice_objs:
            for worker in obj.get("workers") or ():
                node = worker.get("node")
                if node:
                    node_slice[node] = str(obj.get("key") or obj.get("slice") or "")
        pending_age: Dict[str, float] = {}
        live_nodes = set()
        for key, phase, node in pod_triples:
            if node:
                live_nodes.add(node)
            prev = self._pods.get(key)
            if prev is None:
                self._pods[key] = (phase, now)
            elif prev[0] != phase:
                if prev[0] == "Pending" and phase == "Running" and node:
                    self._node_latency.setdefault(
                        node, collections.deque(maxlen=RECENT_LATENCIES)
                    ).append(now - prev[1])
                self._pods[key] = (phase, now)
            if phase == "Pending" and node:
                since = self._pods[key][1]
                pending_age[node] = max(pending_age.get(node, 0.0), now - since)
        for key in list(self._pods):
            if key not in live_keys:
                del self._pods[key]
        # a node with no live pods has no phase signal: drop its latency
        # memory so a drained/autoscaled-away host stops emitting frozen
        # stale observations into its peer group forever (its detector
        # subject freezes, which is the no-signal contract; the memory
        # must not keep "observing" on its behalf)
        for node in list(self._node_latency):
            if node not in live_nodes:
                del self._node_latency[node]
        import statistics as _st

        for node in set(self._node_latency) | set(pending_age):
            recent = self._node_latency.get(node)
            completed = _st.median(recent) if recent else 0.0
            value = max(completed, pending_age.get(node, 0.0))
            observations.append(Observation(
                kind="node", name=node, metric="phase_latency_seconds",
                value=value,
                group=f"slice:{node_slice[node]}" if node in node_slice else "unsliced",
                floor=PHASE_LATENCY_FLOOR_S, source="phase",
            ))

    def _collect_freshness(self, observations: List[Observation]) -> None:
        upstreams = (self.federation.freshness() or {}).get("upstreams") or {}
        for name, u in upstreams.items():
            age = u.get("watermark_age_seconds")
            if age is None:
                age = u.get("last_delta_age_seconds")
            if age is not None:
                observations.append(Observation(
                    kind="upstream", name=name, metric="watermark_age_seconds",
                    value=float(age), group="upstreams", floor=WATERMARK_FLOOR_S,
                    source="freshness",
                ))
            unpropagated = u.get("oldest_unpropagated_seconds")
            if unpropagated is not None:
                observations.append(Observation(
                    kind="upstream", name=name,
                    metric="oldest_unpropagated_seconds",
                    value=float(unpropagated), group="upstreams_backlog",
                    floor=UNPROPAGATED_FLOOR_S, source="freshness",
                ))

    def _collect_probe(
        self,
        observations: List[Observation],
        evidence: Dict[Tuple[str, str], List[str]],
    ) -> None:
        with self._report_lock:
            reports = list(self._pending_reports)
            self._pending_reports.clear()
        if not reports:
            return
        from k8s_watcher_tpu.remediate.policy import ProbeRemediationPolicy

        import statistics as _st

        for report_index, report in enumerate(reports):
            # the ONE implication algorithm (measured-defect-only
            # triangulation, node mapping through the hosts identity map)
            scoped = ProbeRemediationPolicy._implicated(report)
            for node, entries in scoped.items():
                if node == "__unmapped__":
                    continue
                evidence.setdefault(("node", node), []).extend(
                    e[1] for e in entries
                )
            # graded peer signal: per-node median link RTT (each link's
            # reading attributed to both endpoint nodes). All nodes of one
            # report share a fabric == are slice peers.
            links = getattr(report, "links", None)
            if links is None or getattr(links, "error", None) is not None:
                continue
            devices = (report.devices or {}).get("devices") or []
            id_to_process = {d.get("id"): d.get("process_index") for d in devices}
            hosts = report.hosts or {}

            def node_of(pidx):
                return (hosts.get(str(pidx)) or {}).get("node_name")

            per_node: Dict[str, List[float]] = {}
            for link in getattr(links, "links", None) or ():
                rtt = link.get("rtt_ms") if isinstance(link, dict) else getattr(link, "rtt_ms", None)
                ids = link.get("device_ids") if isinstance(link, dict) else getattr(link, "device_ids", ())
                if rtt is None or rtt <= 0:
                    continue
                for device_id in ids or ():
                    node = node_of(id_to_process.get(device_id))
                    if node:
                        per_node.setdefault(node, []).append(float(rtt))
            # peer group = THIS report's nodes only (they share a fabric);
            # keyed by the drain index so two slices' reports landing in
            # the same tick never z-score against each other's RTT floor
            group = f"probe:{report_index}"
            for node, rtts in per_node.items():
                observations.append(Observation(
                    kind="node", name=node, metric="link_rtt_ms",
                    value=_st.median(rtts), group=group, floor=LINK_RTT_FLOOR_MS,
                    source="probe",
                ))

    def _collect_trace(self, observations: List[Observation]) -> None:
        """Per-stage mean latency over this tick's new samples (cumulative
        count/sum differencing — the cheap windowed reading; the SLO plane
        owns exact bucket math). Only stages that already exist in the
        registry are read: the health plane must not mint empty series."""
        from k8s_watcher_tpu.trace import ALL_STAGES

        for stage in ALL_STAGES:
            hist = self.metrics.peek_histogram(f"trace_stage_{stage}")
            if hist is None:
                continue
            _pairs, count, total = hist.buckets()
            prev_count, prev_sum = self._stage_prev.get(stage, (0, 0.0))
            self._stage_prev[stage] = (count, total)
            new = count - prev_count
            if new <= 0:
                continue
            observations.append(Observation(
                kind="stage", name=stage, metric="stage_mean_seconds",
                value=max(0.0, (total - prev_sum) / new),
                group=None, floor=0.0, source="trace",
            ))

    # -- surfaces ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        body = self.detector.snapshot()
        body["enabled"] = True
        body["started"] = self._started
        body["tick_seconds"] = self.config.tick_seconds
        body["sources"] = {
            "probe": self.config.source_probe,
            "phase": self.config.source_phase,
            "freshness": self.config.source_freshness,
            "trace": self.config.source_trace,
        }
        return body

    def health(self) -> Dict[str, Any]:
        body = self.detector.health()
        body["thread_alive"] = self._thread.is_alive() if self._thread else False
        return body

    def release(self, node: str, reason: str = "operator release") -> Dict[str, Any]:
        return self.detector.release(node, reason)
