"""Synthetic probe-report builders for chaos drills and benches.

The health plane consumes probe reports through the same shape
``remediate/policy.py`` parses (``devices``/``hosts``/``links``...).
Real reports come from ``probe/agent.py`` on TPU hosts; the chaos drill
(``scripts/health_smoke.py``), the unit tests and ``bench_health`` need
the same shape WITHOUT chips — scripted, deterministic, and wrong in
exactly one place. These builders produce that: a slice of N hosts, one
device per host, a ring of links with healthy RTTs, and optionally one
degraded device whose links are slow enough to triangulate.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Any, Dict, List, Optional, Sequence


def synthetic_link_report(
    nodes: Sequence[str],
    *,
    degraded_node: Optional[str] = None,
    healthy_rtt_ms: float = 0.2,
    degraded_rtt_ms: float = 6.0,
):
    """A probe-report-shaped object for a slice of ``nodes`` (one device
    per host, devices linked in a ring). ``degraded_node`` makes BOTH of
    that node's device's links measured-suspect ("slow"), which is the
    >=2-links triangulation ``ProbeRemediationPolicy._implicated`` turns
    into a node implication — the "one degraded ICI link pair localizes
    to its common endpoint" scenario, scripted."""
    nodes = list(nodes)
    devices = [
        {"id": i, "process_index": i, "alive": True} for i in range(len(nodes))
    ]
    hosts = {str(i): {"node_name": node} for i, node in enumerate(nodes)}
    degraded_id = nodes.index(degraded_node) if degraded_node else None
    links: List[Dict[str, Any]] = []
    suspect_links: List[Dict[str, Any]] = []
    n = len(nodes)
    for i in range(n if n > 2 else n - 1):  # ring; 2 nodes = one edge
        a, b = i, (i + 1) % n
        rtt = healthy_rtt_ms
        if degraded_id is not None and degraded_id in (a, b):
            rtt = degraded_rtt_ms
        link = {
            "name": f"link-{a}-{b}",
            "device_ids": [a, b],
            "rtt_ms": rtt,
            "axis": "x",
        }
        links.append(link)
        if rtt >= degraded_rtt_ms:
            suspect_links.append({**link, "reason": "slow"})
    return SimpleNamespace(
        devices={"devices": devices, "process_index": 0},
        hosts=hosts,
        links=SimpleNamespace(
            error=None,
            ok=not suspect_links,
            links=links,
            suspect_links=suspect_links,
            suspect_devices=(
                [degraded_id] if degraded_id is not None and suspect_links else []
            ),
        ),
        multislice=None,
        mxu=None,
        hbm=None,
        hbm_write=None,
    )
