"""Straggler & node-health detection: peer-relative signal fusion plus a
config-declared escalation state machine.

Guard (PAPERS.md) makes the production case: in a large training fleet the
failure mode that silently eats goodput is the *slow-but-not-dead* machine
— every absolute threshold either misses it (set loose for fabric jitter)
or cordons healthy nodes during fleet-wide events (set tight). The answer
is PEER-RELATIVE scoring: a node is a straggler relative to its slice
peers *right now*, so a fleet-wide slowdown (congestion, a shared-storage
hiccup) moves the whole peer group together and implicates nobody, while
one lagging host sticks out however the baseline drifts. ARGUS (PAPERS.md)
supplies the second principle: attribute "where is the slowness" from
signals the platform already collects, rather than new probes.

This module is the fusion + verdict core; ``health/plane.py`` owns signal
collection and the tick thread. Per tick the detector receives:

- ``Observation``\\ s — one numeric reading per (subject, metric), each
  carrying an optional *peer group* (nodes of one slice, the upstream
  set). Within a group the reading becomes a robust z-score: deviation
  from the group median in MAD units (median absolute deviation — one
  outlier cannot inflate its own denominator the way a stddev would).
  Groups smaller than three members score nothing: a single-node slice
  has no peers and is NEVER a straggler, and with two members the
  deviation *is* the scale, so neither side can be told from the other.
- direct **evidence** — already-attributed findings (the probe plane's
  suspect-link triangulation via ``remediate/policy.py``'s extraction),
  which are suspicious on their own.
- For subjects that legitimately lack a peer group (a two-upstream
  federation; trace stages), ``probe/trend.py``'s ``TrendTracker``
  provides the rolling self-baseline: a frozen healthy anchor vs the
  recent median. Node/slice subjects deliberately never use the trend
  fallback — a lone node judged against its own past re-creates exactly
  the absolute-threshold failure mode peers exist to avoid.

Verdicts walk ``healthy → suspect → confirmed → remediating`` with the
same hysteresis discipline as ``remediate/policy.py``: ``confirm_cycles``
CONSECUTIVE suspicious ticks escalate, ONE clean tick resets a suspect,
and ``decay_cycles`` consecutive clean ticks de-escalate a confirmed
subject. **Absence of signal is not cleanliness**: a subject nobody
measured this tick keeps its state frozen — silence from a dead signal
plane must never launder a confirmed straggler back to healthy.

Sources tick at different cadences (the probe reports every 30 s, the
phase scan every tick), so suspicion is **latched per source**: a
source's last verdict for a subject stands until that SAME source
observes the subject again. A latched-suspicious subject holds its state
(no decay — the probe's implication is not answered by a fast clean
phase reading) but also does not advance its streak (only a source
actually re-observing the fault counts toward confirmation, mirroring
the remediation policy's per-report counting). Clean observation from
the implicating source clears its latch.

Confirmed NODE verdicts feed the existing budgeted ``NodeActuator``
(dry-run by default; cooldown, hourly rate limit, quarantine budget all
apply). Other subject kinds (slice, upstream, stage) stop at ``confirmed``
— there is nothing to cordon — and surface via /debug/health, metrics and
the /healthz body fold.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import statistics
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from k8s_watcher_tpu.probe.trend import TrendTracker

logger = logging.getLogger(__name__)

HEALTHY = "healthy"
SUSPECT = "suspect"
CONFIRMED = "confirmed"
REMEDIATING = "remediating"
HEALTH_STATES = (HEALTHY, SUSPECT, CONFIRMED, REMEDIATING)

#: subject kinds whose suspicion may come from the TrendTracker fallback
#: when no peer group of >= MIN_PEER_GROUP exists (see module docstring:
#: nodes/slices are peer-relative ONLY)
TREND_FALLBACK_KINDS = ("upstream", "stage")

#: smallest peer group that can score: below this there is no "peer
#: consensus" to deviate from (1 member: no peers at all; 2 members: the
#: deviation is the scale, so the z-score is a constant ~0.67 for both)
MIN_PEER_GROUP = 3


@dataclasses.dataclass
class Observation:
    """One numeric reading for one subject this tick."""

    kind: str  # "node" | "slice" | "upstream" | "stage"
    name: str
    metric: str  # e.g. "phase_latency_seconds"
    value: float
    # peer-group id; subjects sharing (group, metric) are scored against
    # each other. None = no peer group (trend fallback where allowed).
    group: Optional[str] = None
    # absolute floor on the z denominator: keeps trivial absolute spreads
    # (every peer within 50 ms) from minting huge z-scores out of noise
    floor: float = 0.0
    # which signal plane produced this reading — the per-source suspicion
    # latch keys off it (see module docstring)
    source: str = "default"

    @property
    def subject(self) -> Tuple[str, str]:
        return (self.kind, self.name)


def robust_peer_z(
    values: Dict[Any, float], *, floor: float = 0.0
) -> Dict[Any, float]:
    """Peer-relative robust z-scores: ``(x - median) / scale`` where scale
    is the MAD (scaled to stddev-equivalence by 1.4826), floored by 10% of
    the median magnitude and by ``floor`` so identical-peer groups (MAD 0)
    and trivially-small absolute spreads stay un-alarmable. Groups with
    fewer than ``MIN_PEER_GROUP`` members return ``{}`` (no peers, no
    straggler — see module docstring)."""
    if len(values) < MIN_PEER_GROUP:
        return {}
    vals = list(values.values())
    med = statistics.median(vals)
    mad = statistics.median([abs(v - med) for v in vals])
    scale = max(1.4826 * mad, 0.1 * abs(med), floor, 1e-9)
    return {name: (v - med) / scale for name, v in values.items()}


class _SubjectState:
    __slots__ = (
        "state", "streak", "clean", "severity", "score", "reasons",
        "signals", "last_observed_tick", "escalations", "latches",
    )

    def __init__(self):
        self.state = HEALTHY
        self.streak = 0  # consecutive suspicious ticks
        self.clean = 0  # consecutive clean ticks
        self.severity = 0.0
        self.score = 1.0
        self.reasons: List[str] = []
        self.signals: Dict[str, Dict[str, Any]] = {}
        self.last_observed_tick = 0
        self.escalations = 0
        # per-source suspicion latch: source -> last severity that source
        # reported for this subject (>= 1.0 = latched suspicious). Stands
        # until the SAME source observes the subject again.
        self.latches: Dict[str, float] = {}


class HealthDetector:
    """The fusion + escalation core (see module docstring).

    Thread-contract: ``tick`` is called from one thread (the plane's tick
    loop); ``snapshot``/``health`` may race it from HTTP handlers — the
    subject table is guarded by one lock.
    """

    #: default cap on distinct node label values emitted to the
    #: node_health_score / health_state gauge families — past it, new
    #: nodes still get verdicts but no labeled series (bounded
    #: cardinality; the snapshot carries everything)
    MAX_LABELED_NODES = 64

    #: HEALTHY subjects unobserved for this many ticks are forgotten —
    #: nodes leave fleets (drain, autoscale); without a TTL the subject
    #: table and /debug/health grow one ghost per departed machine
    #: forever. Non-healthy subjects are deliberately immortal: a
    #: confirmed straggler must never be garbage-collected to healthy.
    SUBJECT_TTL_TICKS = 720

    def __init__(
        self,
        *,
        suspect_z: float = 4.0,
        confirm_cycles: int = 3,
        decay_cycles: int = 2,
        actuator=None,  # remediate.NodeActuator (dry-run fences apply)
        metrics=None,
        sink: Optional[Callable[[Dict[str, Any]], None]] = None,
        trend: Optional[TrendTracker] = None,
        max_labeled_nodes: Optional[int] = None,
    ):
        if suspect_z <= 0:
            raise ValueError("suspect_z must be > 0")
        if confirm_cycles < 1 or decay_cycles < 1:
            raise ValueError("confirm_cycles and decay_cycles must be >= 1")
        self.suspect_z = suspect_z
        self.confirm_cycles = confirm_cycles
        self.decay_cycles = decay_cycles
        self.actuator = actuator
        self.metrics = metrics
        self.sink = sink
        # the ONE rolling-baseline implementation (satellite: reuse
        # probe/trend.py instead of a second EWMA): frozen healthy anchor
        # vs recent median, alert on sustained rise/drop
        self.trend = trend or TrendTracker(
            window=12, recent=3, drop_factor=0.6, rise_factor=2.5, min_history=5
        )
        self.max_labeled_nodes = (
            max_labeled_nodes if max_labeled_nodes is not None else self.MAX_LABELED_NODES
        )
        self._lock = threading.Lock()
        self._subjects: Dict[Tuple[str, str], _SubjectState] = {}
        self._ticks = 0
        self._actions: collections.deque = collections.deque(maxlen=32)
        self._labeled_nodes: set = set()
        self._label_overflow_logged = False
        if metrics is not None:
            from k8s_watcher_tpu.metrics.metrics import MAX_LABEL_SETS

            self._score_gauge = metrics.gauge("node_health_score")
            self._score_gauge.max_label_sets = max(
                MAX_LABEL_SETS, self.max_labeled_nodes + 8
            )
            self._state_gauge = metrics.gauge("health_state")
            # one child per (node, state) pair
            self._state_gauge.max_label_sets = max(
                MAX_LABEL_SETS, (self.max_labeled_nodes + 8) * len(HEALTH_STATES)
            )
            self._suspect_gauge = metrics.gauge("health_suspect_subjects")
            self._confirmed_gauge = metrics.gauge("health_confirmed_subjects")
            self._ticks_counter = metrics.counter("health_ticks")
            self._escalations_counter = metrics.counter("health_escalations")
            self._deescalations_counter = metrics.counter("health_deescalations")
        else:
            self._score_gauge = self._state_gauge = None
            self._suspect_gauge = self._confirmed_gauge = None
            self._ticks_counter = self._escalations_counter = None
            self._deescalations_counter = None

    # -- scoring -----------------------------------------------------------

    def _fold_signals(
        self,
        observations: List[Observation],
        evidence: Dict[Tuple[str, str], List[str]],
        evidence_source: str,
    ) -> Tuple[Dict[Tuple[str, str], Dict[str, float]], Dict[Tuple[str, str], List[str]],
               Dict[Tuple[str, str], Dict[str, Dict[str, Any]]]]:
        """``(per-source severity, reasons, signals)`` per subject.
        Severity >= 1.0 means suspicious (z at/over suspect_z, a trend
        alert where the fallback applies, or direct evidence)."""
        groups: Dict[Tuple[Optional[str], str], Dict[Tuple[str, str], Observation]] = {}
        for obs in observations:
            if obs.group is not None:
                groups.setdefault((obs.group, obs.metric), {})[obs.subject] = obs
        z_scores: Dict[Tuple[Tuple[str, str], str], float] = {}
        peer_scored: set = set()  # (subject, metric) pairs with a real peer group
        for (_group, metric), members in groups.items():
            floor = max(m.floor for m in members.values())
            zs = robust_peer_z(
                {subj: m.value for subj, m in members.items()}, floor=floor
            )
            for subj, z in zs.items():
                z_scores[(subj, metric)] = z
                peer_scored.add((subj, metric))
        severity: Dict[Tuple[str, str], Dict[str, float]] = {}
        reasons: Dict[Tuple[str, str], List[str]] = {}
        signals: Dict[Tuple[str, str], Dict[str, Dict[str, Any]]] = {}

        def bump(subj, source, sev, reason=None):
            by_source = severity.setdefault(subj, {})
            by_source[source] = max(by_source.get(source, 0.0), sev)
            if reason is not None and sev >= 1.0:
                reasons.setdefault(subj, []).append(reason)

        for obs in observations:
            subj = obs.subject
            severity.setdefault(subj, {}).setdefault(obs.source, 0.0)
            detail: Dict[str, Any] = {"value": round(obs.value, 4)}
            z = z_scores.get((subj, obs.metric))
            if z is not None:
                detail["peer_z"] = round(z, 2)
                if z > 0:
                    bump(
                        subj, obs.source, z / self.suspect_z,
                        f"{obs.metric}: peer z={z:.1f} (suspect_z={self.suspect_z:g}, "
                        f"value={obs.value:.3g})",
                    )
            # trend fold: every reading shapes/judges the rolling baseline,
            # but suspicion from a trend alert is restricted to kinds with
            # no peer alternative — and a peer-suspicious reading must not
            # poison its own anchor (contribute only while clean)
            alert = self.trend.observe(
                f"{obs.kind}/{obs.name}/{obs.metric}", obs.value,
                higher_is_better=False,
                contribute_baseline=(z is None or z < self.suspect_z),
            )
            if alert is not None:
                detail["trend_ratio"] = round(alert.ratio, 2)
                if (
                    obs.kind in TREND_FALLBACK_KINDS
                    and (subj, obs.metric) not in peer_scored
                ):
                    bump(
                        subj, obs.source, alert.ratio / self.trend.rise_factor,
                        f"{obs.metric}: {alert.ratio:.1f}x its healthy baseline "
                        f"({alert.recent:.3g} vs anchor {alert.baseline:.3g})",
                    )
            signals.setdefault(subj, {})[obs.metric] = detail
        for subj, items in evidence.items():
            bump(subj, evidence_source, 1.0)
            reasons.setdefault(subj, []).extend(items)
            signals.setdefault(subj, {}).setdefault("evidence", {})["count"] = len(items)
        return severity, reasons, signals

    # -- the tick ----------------------------------------------------------

    def tick(
        self,
        observations: List[Observation],
        evidence: Optional[Dict[Tuple[str, str], List[str]]] = None,
        evidence_source: str = "probe",
    ) -> Dict[str, Any]:
        """Fold one tick's signals; advance every OBSERVED subject's state
        (unobserved subjects freeze — no signal is not healthy). Returns a
        summary of transitions and actions taken."""
        evidence = evidence or {}
        severity, reasons, signals = self._fold_signals(
            observations, evidence, evidence_source
        )
        escalated: List[Tuple[str, str]] = []
        deescalated: List[Tuple[str, str]] = []
        confirm_nodes: List[Tuple[str, str]] = []  # (node, reason)
        with self._lock:
            self._ticks += 1
            tick_no = self._ticks
            for subj, by_source in severity.items():
                rec = self._subjects.get(subj)
                if rec is None:
                    rec = self._subjects[subj] = _SubjectState()
                # refresh this tick's sources into the per-source latches;
                # sources NOT reporting this tick keep their last verdict
                rec.latches.update(by_source)
                fresh_suspicious = any(s >= 1.0 for s in by_source.values())
                latched = any(
                    s >= 1.0 for source, s in rec.latches.items()
                    if source not in by_source
                )
                rec.severity = max([*rec.latches.values(), 0.0])
                rec.score = round(1.0 / (1.0 + max(0.0, rec.severity)), 4)
                rec.signals = signals.get(subj, {})
                rec.last_observed_tick = tick_no
                if fresh_suspicious:
                    rec.reasons = reasons.get(subj, [])[:8]
                    rec.clean = 0
                    rec.streak += 1
                    if rec.state == HEALTHY:
                        rec.state = SUSPECT
                    if rec.state == SUSPECT and rec.streak >= self.confirm_cycles:
                        rec.state = CONFIRMED
                        rec.escalations += 1
                        escalated.append(subj)
                        if subj[0] == "node":
                            confirm_nodes.append(
                                (subj[1],
                                 f"health detector: suspicious in {rec.streak} "
                                 f"consecutive ticks: " + "; ".join(rec.reasons)[:400])
                            )
                    elif (
                        subj[0] == "node"
                        and rec.state == CONFIRMED
                        and rec.streak % self.confirm_cycles == 0
                    ):
                        # the first attempt was refused (cooldown/rate/
                        # budget fence) — a node that STAYS suspicious
                        # keeps asking at the confirmation cadence, like
                        # the remediation policy re-earns per report; a
                        # success moves it to remediating and stops this
                        confirm_nodes.append(
                            (subj[1],
                             f"health detector: still suspicious after "
                             f"{rec.streak} consecutive ticks (earlier "
                             f"quarantine refused): " + "; ".join(rec.reasons)[:400])
                        )
                elif latched:
                    # a silent source's suspicion stands: hold the state —
                    # neither a confirmation step (only the implicating
                    # source re-observing counts) nor a clean step (a fast
                    # clean phase reading does not answer a probe finding)
                    continue
                else:
                    rec.streak = 0
                    rec.clean += 1
                    if rec.state == SUSPECT:
                        # one clean cycle resets: a transient outlier that
                        # clears must not accumulate toward a cordon
                        rec.state = HEALTHY
                        rec.reasons = []
                    elif rec.state in (CONFIRMED, REMEDIATING) and rec.clean >= self.decay_cycles:
                        rec.state = HEALTHY
                        rec.reasons = []
                        deescalated.append(subj)
            # forget long-unobserved healthy subjects (departed nodes);
            # amortized: one sweep per 64 ticks
            if tick_no % 64 == 0:
                for subj in [
                    s for s, r in self._subjects.items()
                    if r.state == HEALTHY
                    and tick_no - r.last_observed_tick > self.SUBJECT_TTL_TICKS
                ]:
                    del self._subjects[subj]
        # actuate OUTSIDE the lock: a slow apiserver PATCH must not block
        # snapshot()/health() readers for its duration
        actions = []
        for node, reason in confirm_nodes:
            actions.append(self._actuate(node, reason))
        if escalated or deescalated:
            for subj in escalated:
                logger.warning(
                    "Health plane: %s/%s CONFIRMED unhealthy (%s)",
                    subj[0], subj[1], "; ".join(reasons.get(subj, []))[:300],
                )
            for subj in deescalated:
                logger.info(
                    "Health plane: %s/%s de-escalated to healthy after %d clean tick(s)",
                    subj[0], subj[1], self.decay_cycles,
                )
            if self._escalations_counter is not None:
                if escalated:
                    self._escalations_counter.inc(len(escalated))
                if deescalated:
                    self._deescalations_counter.inc(len(deescalated))
            self._notify(escalated, deescalated, reasons, actions)
        self._sync_metrics()
        return {
            "tick": tick_no,
            "observed": len(severity),
            "escalated": [f"{k}/{n}" for k, n in escalated],
            "deescalated": [f"{k}/{n}" for k, n in deescalated],
            "actions": [a.to_dict() for a in actions if a is not None],
        }

    def _actuate(self, node: str, reason: str):
        """Hand one confirmed node to the budgeted actuator (dry-run by
        default; its cooldown/rate/budget fences all apply). A successful
        (or would-be, in dry-run) quarantine moves the node to
        ``remediating``; a refusal leaves it ``confirmed`` — the fences
        exist precisely to stop a detector bug from mass-cordoning."""
        if self.actuator is None:
            return None
        record = self.actuator.quarantine(node, reason)
        with self._lock:
            self._actions.append(record.to_dict())
            rec = self._subjects.get(("node", node))
            if rec is not None and record.ok and rec.state == CONFIRMED:
                rec.state = REMEDIATING
        return record

    def release(self, node: str, reason: str = "operator release") -> Dict[str, Any]:
        """Manual de-escalation (remediate_ctl's ``health release`` path):
        reset the node's detector state AND drive the actuator's release
        (uncordon + untaint) when one is wired."""
        with self._lock:
            rec = self._subjects.get(("node", node))
            if rec is not None:
                rec.state = HEALTHY
                rec.streak = rec.clean = 0
                rec.reasons = []
                # clear the per-source latches too: a released node must
                # not stay severity-degraded (and state-frozen on the
                # latched hold path) behind a probe implication the
                # operator just overrode
                rec.latches = {}
                rec.severity = 0.0
                rec.score = 1.0
        if self.actuator is None:
            return {"node": node, "released": True, "actuator": None}
        record = self.actuator.release(node, reason)
        with self._lock:
            self._actions.append(record.to_dict())
        return {"node": node, "released": record.ok, "actuator": record.to_dict()}

    def _notify(self, escalated, deescalated, reasons, actions) -> None:
        if self.sink is None or not (escalated or deescalated):
            return
        from datetime import datetime, timezone

        payload = {
            "event_type": "TPU_HEALTH",
            "escalated": [
                {"kind": k, "name": n, "reasons": reasons.get((k, n), [])[:8]}
                for k, n in escalated
            ],
            "deescalated": [{"kind": k, "name": n} for k, n in deescalated],
            "actions": [a.to_dict() for a in actions if a is not None],
            "event_timestamp": datetime.now(timezone.utc).isoformat(),
        }
        try:
            self.sink(payload)
        except Exception as exc:  # noqa: BLE001 — reporting must not kill the tick
            logger.error("Health notification failed: %s", exc)

    # -- metrics / surfaces ------------------------------------------------

    def _sync_metrics(self) -> None:
        if self.metrics is None:
            return
        with self._lock:
            subjects = {s: (r.state, r.score) for s, r in self._subjects.items()}
        suspect = sum(1 for st, _ in subjects.values() if st == SUSPECT)
        confirmed = sum(
            1 for st, _ in subjects.values() if st in (CONFIRMED, REMEDIATING)
        )
        self._suspect_gauge.set(suspect)
        self._confirmed_gauge.set(confirmed)
        for (kind, name), (state, score) in subjects.items():
            if kind != "node":
                continue
            if name not in self._labeled_nodes:
                if len(self._labeled_nodes) >= self.max_labeled_nodes:
                    if not self._label_overflow_logged:
                        self._label_overflow_logged = True
                        logger.warning(
                            "Health plane: >%d distinct nodes — further nodes get "
                            "verdicts but no labeled node_health_score/health_state "
                            "series (bounded cardinality; /debug/health has all)",
                            self.max_labeled_nodes,
                        )
                    continue
                self._labeled_nodes.add(name)
            self._score_gauge.labels(node=name).set(score)
            for st in HEALTH_STATES:
                self._state_gauge.labels(node=name, state=st).set(
                    1.0 if st == state else 0.0
                )

    def snapshot(self) -> Dict[str, Any]:
        """The full ``/debug/health`` body."""
        with self._lock:
            subjects = {
                f"{kind}/{name}": {
                    "kind": kind,
                    "name": name,
                    "state": rec.state,
                    "score": rec.score,
                    "severity": round(rec.severity, 3),
                    "streak": rec.streak,
                    "clean": rec.clean,
                    "reasons": list(rec.reasons),
                    "signals": dict(rec.signals),
                    "last_observed_tick": rec.last_observed_tick,
                    "escalations": rec.escalations,
                }
                for (kind, name), rec in sorted(self._subjects.items())
            }
            actions = list(self._actions)
            ticks = self._ticks
        body: Dict[str, Any] = {
            "ticks": ticks,
            "suspect_z": self.suspect_z,
            "confirm_cycles": self.confirm_cycles,
            "decay_cycles": self.decay_cycles,
            "subjects": subjects,
            "actions": actions,
        }
        if self.actuator is not None:
            body["actuator"] = {
                "dry_run": self.actuator.dry_run,
                "quarantined_nodes": self.actuator.quarantined_nodes(),
            }
        return body

    def health(self) -> Dict[str, Any]:
        """The /healthz BODY fold: unhealthy while any subject is
        confirmed/remediating. Deliberately NOT the liveness verdict —
        restarting the watcher cannot fix a straggling machine, and a 503
        would crash-loop the very process holding the evidence."""
        with self._lock:
            by_state: Dict[str, List[str]] = {s: [] for s in HEALTH_STATES[1:]}
            for (kind, name), rec in sorted(self._subjects.items()):
                if rec.state != HEALTHY:
                    by_state[rec.state].append(f"{kind}/{name}")
        unhealthy = by_state[CONFIRMED] or by_state[REMEDIATING]
        return {
            "healthy": not unhealthy,
            "suspect": by_state[SUSPECT],
            "confirmed": by_state[CONFIRMED],
            "remediating": by_state[REMEDIATING],
        }
