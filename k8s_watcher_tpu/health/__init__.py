"""Straggler & node-health detection plane (net-new; ROADMAP item 3).

Peer-relative signal fusion over planes the platform already runs (probe
RTTs/suspect links, fleet-view phase latencies, federation freshness
watermarks, trace stage outliers) into per-node / per-slice / per-upstream
verdicts, escalated ``healthy → suspect → confirmed → remediating``
through config-declared hysteresis, with confirmed node verdicts feeding
the existing budgeted dry-run remediation actuator. Grounding: Guard +
ARGUS (PAPERS.md). See ARCHITECTURE.md "Health & remediation plane".
"""

from k8s_watcher_tpu.health.detector import (  # noqa: F401
    CONFIRMED,
    HEALTH_STATES,
    HEALTHY,
    REMEDIATING,
    SUSPECT,
    HealthDetector,
    Observation,
    robust_peer_z,
)
from k8s_watcher_tpu.health.plane import HealthPlane  # noqa: F401

__all__ = [
    "CONFIRMED",
    "HEALTHY",
    "HEALTH_STATES",
    "HealthDetector",
    "HealthPlane",
    "Observation",
    "REMEDIATING",
    "SUSPECT",
    "robust_peer_z",
]
