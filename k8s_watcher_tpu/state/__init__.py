"""Checkpoint/resume (SURVEY.md §5 — ABSENT in the reference: every restart
re-watched from "now", dropping or duplicating notifications)."""

from k8s_watcher_tpu.state.checkpoint import CheckpointStore  # noqa: F401
