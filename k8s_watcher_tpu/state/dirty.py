"""Bounded changed-key accumulator for journaled checkpoints.

The watch source and phase tracker feed ``JournaledMapStore`` a delta
hint: the keys whose persisted entry changed since the last checkpoint
sweep (state/checkpoint.py). When nothing ever drains the hint — a
watcher running without ``state.checkpoint_path``, or a standalone
pipeline — a plain set would grow one entry per pod UID that ever
churns, forever (delete/recreate mints fresh UIDs each cycle).

``DirtyKeys`` bounds that: past ``max(floor, live_size)`` marked keys
the set collapses to the "unknown delta" sentinel (``drain()`` returns
``None``), which checkpoint consumers already treat as "full
compaction" — exactly what the journaled store would do anyway for a
delta that big, so the collapse costs correctness nothing and caps
memory at O(live state).
"""

from __future__ import annotations

import threading
from typing import Any, Optional, Set


class DirtyKeys:
    """Thread-safe: ``mark`` runs on the shard pump threads (the watch
    source tracks pods as it pumps — watch/sharded.py) while ``drain``
    runs on the ingest drain thread's checkpoint sweep. An unlocked
    mark racing the drain's swap could land in the drained set mid-
    iteration (RuntimeError) or be lost. The lock is uncontended in
    steady state (one mark per tracked change, one drain per throttle
    window), so the hot-path cost is a bare acquire."""

    def __init__(self, floor: int = 4096):
        self.floor = floor
        self._lock = threading.Lock()
        self._keys: Optional[Set[Any]] = set()

    def mark(self, key: Any, live_size: int) -> None:
        """Record a changed key; ``live_size`` is the current size of the
        tracked map, so the collapse threshold follows the state."""
        with self._lock:
            if self._keys is None:
                return  # already collapsed; the next drain says "everything"
            self._keys.add(key)
            if len(self._keys) > max(self.floor, live_size):
                self._keys = None

    def drain(self) -> Optional[Set[Any]]:
        """The changed keys since the last drain, or None for "unknown —
        treat everything as changed"; clears the accumulator."""
        with self._lock:
            drained, self._keys = self._keys, set()
            return drained
