"""Durable watcher state: last-seen resourceVersion + phase/slice snapshots.

Atomic JSON file writes (write-temp + rename) with throttling so checkpoint
I/O stays off the hot path even at 1 k events/min. A missing or corrupt
checkpoint degrades to a cold start — never a crash.

Cost at scale (measured, bench_checkpoint_scale / tests/test_k8s.py):
every flush rewrites the whole JSON; at 10k tracked pods the file is
~4 MB and one flush costs tens of ms of serialization + write. That cost
is paid at most once per ``interval_seconds`` (default 5 s) on whichever
thread trips the throttle, and the lock is held only for a shallow dict
copy — the watch loop's per-event ``update_resource_version`` never waits
on serialization.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from pathlib import Path
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

_SCHEMA_VERSION = 1


class CheckpointStore:
    def __init__(self, path: os.PathLike | str, *, interval_seconds: float = 5.0):
        self.path = Path(path)
        self.interval_seconds = interval_seconds
        self._lock = threading.Lock()
        self._state: Dict[str, Any] = {"version": _SCHEMA_VERSION}
        self._dirty = False
        self._last_flush = 0.0
        self._load()

    def _load(self) -> None:
        try:
            data = json.loads(self.path.read_text())
        except FileNotFoundError:
            return
        except (json.JSONDecodeError, OSError) as exc:
            logger.warning("Corrupt checkpoint %s (%s); starting cold", self.path, exc)
            return
        if isinstance(data, dict) and data.get("version") == _SCHEMA_VERSION:
            self._state = data
        else:
            logger.warning("Checkpoint %s has unknown schema; starting cold", self.path)

    # -- accessors ---------------------------------------------------------

    def resource_version(self) -> Optional[str]:
        with self._lock:
            return self._state.get("resource_version")

    def update_resource_version(self, rv: str) -> None:
        with self._lock:
            self._state["resource_version"] = rv
            self._dirty = True
        self.maybe_flush()

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._state.get(key, default)

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            self._state[key] = value
            self._dirty = True
        self.maybe_flush()

    # -- persistence -------------------------------------------------------

    def due(self) -> bool:
        """True when the throttle window has elapsed — callers with expensive
        snapshots should skip building them entirely until this is True."""
        with self._lock:
            return time.monotonic() - self._last_flush >= self.interval_seconds

    def maybe_flush(self) -> None:
        """Flush if dirty and the throttle interval has elapsed."""
        now = time.monotonic()
        with self._lock:
            if not self._dirty or now - self._last_flush < self.interval_seconds:
                return
        self.flush()

    def flush(self) -> None:
        with self._lock:
            # shallow copy under the lock, serialize OUTSIDE it: values are
            # replaced wholesale (put/update_resource_version), never
            # mutated in place (known_pods() documents the same contract),
            # so the copy is consistent — and json.dumps of a 10k-pod
            # skeleton map (~4 MB, tens of ms) must not hold the lock the
            # watch loop takes on every event's _save_rv
            snapshot_state = dict(self._state)
            self._dirty = False
            self._last_flush = time.monotonic()
        snapshot = json.dumps(snapshot_state)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=str(self.path.parent), prefix=self.path.name, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                fh.write(snapshot)
            os.replace(tmp, self.path)
        except OSError as exc:
            logger.error("Checkpoint flush to %s failed: %s", self.path, exc)
            try:
                os.unlink(tmp)
            except OSError:
                pass
