"""Durable watcher state: last-seen resourceVersion + phase/slice snapshots.

Atomic JSON file writes (write-temp + rename) with throttling so checkpoint
I/O stays off the hot path even at 1 k events/min. A missing or corrupt
checkpoint degrades to a cold start — never a crash.

Cost at scale (measured, bench_checkpoint_scale / tests/test_k8s.py):
a plain flush rewrites the whole JSON — ~4 MB / tens of ms at 10k tracked
pods, ~19 MB / >200 ms at 50k. That whole-state rewrite is fine for the
small sections (resourceVersion, phase/slice snapshots) but not for the
``known_pods`` skeleton map, which dominates the state and whose churn per
throttle window is tiny compared to its size. Large maps therefore go
through :class:`JournaledMapStore` (attach via
``CheckpointStore.attach_journaled_map``): a base snapshot plus an
append-only delta journal, so a steady-state flush costs O(changed
entries), not O(tracked pods) — measured at 50k pods in
``bench_checkpoint_scale``. The base is rewritten (compaction) only when
the journal has grown past the size of the map itself, amortizing the
O(state) cost over O(state) appended deltas.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from pathlib import Path
from typing import Any, Dict, Iterable, Optional, Set

logger = logging.getLogger(__name__)

_SCHEMA_VERSION = 1


def _atomic_write(path: Path, payload: str) -> bool:
    """Write-temp + rename; returns False (after logging) on failure so
    callers can keep their dirty state for a retry."""
    tmp = None
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=path.name, suffix=".tmp")
        with os.fdopen(fd, "w") as fh:
            fh.write(payload)
        os.replace(tmp, path)
        return True
    except OSError as exc:
        logger.error("Atomic write to %s failed: %s", path, exc)
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


class JournaledMapStore:
    """Incremental persistence for one large string-keyed map.

    On-disk layout (both under the parent checkpoint's directory):

    - ``<name>.base.json`` — ``{"version": 1, "gen": G, "map": {...}}``,
      written atomically (temp + rename) on compaction;
    - ``<name>.journal.jsonl`` — one JSON object per line,
      ``{"g": G, "k": key, "v": value}`` for an upsert or
      ``{"g": G, "k": key, "d": true}`` for a delete, appended in
      complete lines on each flush.

    Load replays journal lines IN ORDER over the base map (last write
    wins) and stops at the first malformed line — a crash mid-append
    leaves at most one partial trailing line, which is discarded. The
    generation number fences the compaction crash window: a new base is
    renamed into place BEFORE the journal is truncated, so a crash
    between the two leaves stale journal lines whose ``g`` no longer
    matches the base's — they are skipped on load instead of reverting
    newer base values.

    Same contracts as CheckpointStore: values must be replaced, never
    mutated in place (``replace`` keeps the caller's dict by reference);
    serialization happens outside the lock; a corrupt file degrades to a
    cold start, never a crash; no fsync (a lost checkpoint costs a cold
    start, by design).
    """

    def __init__(
        self,
        path_stem: os.PathLike | str,
        *,
        compact_factor: float = 1.0,
        min_compact_entries: int = 2048,
        compact_slice_entries: int = 4096,
    ):
        stem = Path(path_stem)
        self.base_path = stem.with_name(stem.name + ".base.json")
        self.journal_path = stem.with_name(stem.name + ".journal.jsonl")
        # in-progress incremental compaction target; a leftover from a
        # crash is garbage (never loaded) and removed on startup
        self.tmp_path = stem.with_name(stem.name + ".base.json.compacting")
        # compact when journal lines > max(min_compact_entries,
        # compact_factor * len(map)) — the default amortizes one O(state)
        # base rewrite over >= O(state) appended deltas
        self.compact_factor = compact_factor
        self.min_compact_entries = min_compact_entries
        # throttled flushes (CheckpointStore.maybe_flush) serialize at most
        # this many entries of an in-progress compaction per call, bounding
        # the per-flush pause: a 50k-entry one-shot compact was ~217 ms of
        # stop-the-world on the drain thread (BENCH_r05); 4096-entry slices
        # bound it at ~50 ms, interleaved with normal delta flushes.
        # 0 = one-shot compaction always.
        # Direct flush() calls still complete compaction in full — they are
        # the durability barrier (shutdown, tests).
        self.compact_slice_entries = compact_slice_entries
        self._lock = threading.Lock()
        # serializes flush/compaction I/O: a concurrent append racing a
        # compaction's generation bump would write lines the new fence
        # silently discards on load
        self._io_lock = threading.Lock()
        self._map: Dict[str, Any] = {}
        self._gen = 0
        self._journal_entries = 0
        # keys journaled at next flush; None = full compaction needed
        # (unknown delta, e.g. legacy migration or a replace() without a
        # changed_keys hint)
        self._pending: Optional[Set[str]] = set()
        # in-progress sliced compaction (guarded by _io_lock): dict with
        # gen/snapshot/keys/idx/fh/delta, or None
        self._compacting: Optional[Dict[str, Any]] = None
        # True once this map has EVER held state (a base/journal existed
        # on disk, or replace() ran): distinguishes an empty-but-present
        # map (every key legitimately deleted) from a never-populated one
        # (CheckpointStore.get must fall back to its default only for
        # the latter)
        self._populated = False
        # lock-free stats mirror: a dict REPLACED wholesale (atomic ref
        # swap under the GIL) at every point gen/journal/compaction state
        # changes, so a /debug/checkpoint scrape never blocks on _io_lock
        # behind an in-flight compaction slice
        self._io_shadow: Dict[str, Any] = {
            "generation": 0, "journal_entries": 0, "compacting": None,
        }
        self._load()
        self._publish_io_shadow()  # _load's early returns skip the one inside

    def _load(self) -> None:
        try:
            # a crash mid-compaction leaves a partial target file; it is
            # never read (only the renamed base is), so just reclaim it
            self.tmp_path.unlink()
        except OSError:
            pass
        try:
            data = json.loads(self.base_path.read_text())
            # gen is load-bearing (it fences journal replay): a base whose
            # gen isn't a plain int is corrupt AS A WHOLE — adopting its
            # map with a reset gen would replay the wrong journal lines
            # over it, and int(None/list) raising out of __init__ would
            # crash-loop the watcher instead of degrading (the module
            # contract: cold start, never a crash)
            if (
                isinstance(data, dict)
                and data.get("version") == _SCHEMA_VERSION
                and isinstance(data.get("map"), dict)
                and isinstance(data.get("gen", 0), int)
                and not isinstance(data.get("gen", 0), bool)
            ):
                self._map = data["map"]
                self._gen = data.get("gen", 0)
                self._populated = True
            else:
                logger.warning("Journaled map %s has unknown schema; starting cold", self.base_path)
        except FileNotFoundError:
            pass
        except (json.JSONDecodeError, OSError, ValueError) as exc:
            logger.warning("Corrupt journaled map base %s (%s); starting cold", self.base_path, exc)
        try:
            journal = self.journal_path.read_text()
        except FileNotFoundError:
            return
        except OSError as exc:
            logger.warning("Unreadable journal %s (%s); using base only", self.journal_path, exc)
            return
        for line in journal.splitlines():
            if not line.strip():
                continue
            try:
                entry = json.loads(line)
                if entry.get("g") != self._gen:
                    continue  # stale generation (compaction crash window)
                key = entry["k"]
            except (json.JSONDecodeError, KeyError, TypeError, AttributeError):
                # partial trailing line from a crash mid-append; anything
                # after it is unordered relative to the tear — stop
                logger.warning("Journal %s has a torn line; replay stopped there", self.journal_path)
                break
            self._journal_entries += 1
            self._populated = True
            if entry.get("d"):
                self._map.pop(key, None)
            else:
                self._map[key] = entry.get("v")
        self._publish_io_shadow()

    def _publish_io_shadow(self) -> None:
        """Refresh the lock-free stats mirror. Call from every site that
        mutates gen/journal depth/compaction progress (all run under
        ``_io_lock``, so the build is consistent); readers just grab the
        reference — no lock, no stall behind a compaction slice."""
        comp = self._compacting
        self._io_shadow = {
            "generation": self._gen,
            "journal_entries": self._journal_entries,
            "compacting": (
                {"target_gen": comp["gen"], "written": comp["idx"], "total": len(comp["keys"])}
                if comp is not None
                else None
            ),
        }

    # -- accessors ---------------------------------------------------------

    def current(self) -> Dict[str, Any]:
        """Shallow copy of the live map (same contract as known_pods())."""
        with self._lock:
            return dict(self._map)

    @property
    def populated(self) -> bool:
        """True once the map has ever held state (disk or replace()).
        An empty-but-populated map means "every key deleted" — a real
        answer, distinct from "nothing persisted yet"."""
        with self._lock:
            return self._populated

    def stats(self) -> Dict[str, Any]:
        """Observability snapshot for /debug/checkpoint: generation,
        journal depth, live-map size, and on-disk byte counts.

        Deliberately does NOT take ``_io_lock``: a scrape must never
        stall behind an in-flight compaction slice (a 50k-map rewrite
        holds that lock for tens of ms at a time). It reads the
        ``_io_shadow`` mirror instead — replaced wholesale under
        ``_io_lock`` by every mutator, so one reference read yields an
        internally-consistent (gen, journal depth, compaction progress)
        triple; it can be one flush stale, never torn."""
        shadow = self._io_shadow
        gen = shadow["generation"]
        journal_entries = shadow["journal_entries"]
        compacting = shadow["compacting"]
        with self._lock:
            map_size = len(self._map)
            pending = self._pending
            pending_desc = "full" if pending is None else len(pending)
        def _size(p: Path) -> Optional[int]:
            try:
                return p.stat().st_size
            except OSError:
                return None
        return {
            "generation": gen,
            "journal_entries": journal_entries,
            "pending": pending_desc,
            "map_size": map_size,
            "base_bytes": _size(self.base_path),
            "journal_bytes": _size(self.journal_path),
            "compacting": compacting,
        }

    @property
    def pending(self) -> bool:
        with self._lock:
            if self._pending is None or bool(self._pending):
                return True
        # an in-progress compaction is pending work too: the throttled
        # flusher must keep calling until the new base lands (read without
        # _io_lock — a momentarily stale answer only delays one interval)
        return self._compacting is not None

    def replace(self, new_map: Dict[str, Any], changed_keys: Optional[Iterable[str]] = None) -> None:
        """Adopt ``new_map`` as the live state. ``changed_keys`` is the
        caller's delta hint (keys upserted or deleted since the LAST
        replace); without it the next flush pays a full compaction —
        correct for any caller, incremental only for hinting ones."""
        with self._lock:
            self._map = new_map
            self._populated = True
            if changed_keys is None:
                self._pending = None
            elif self._pending is not None:
                self._pending.update(changed_keys)
            # else: full compaction already pending, which supersedes hints

    # -- persistence -------------------------------------------------------

    def flush(self, finalize: bool = True) -> None:
        """Persist pending deltas. ``finalize=True`` (the default — direct
        calls are the durability barrier: shutdown, tests) also drives any
        in-progress compaction to completion; ``finalize=False`` (the
        throttled ``CheckpointStore.maybe_flush`` path) advances it by at
        most ``compact_slice_entries`` entries, bounding the per-flush
        pause on the ingest drain thread."""
        with self._io_lock:
            self._flush_locked(finalize)

    def _flush_locked(self, finalize: bool = True) -> None:
        with self._lock:
            pending = self._pending
            snapshot = self._map  # entries are never mutated in place
            self._pending = set()
        if self._compacting is not None:
            if pending is None:
                # a newer full rewrite supersedes the half-built target
                self._abort_compaction()
                self._start_compaction(snapshot, finalize)
            else:
                if pending:
                    # journal at the CURRENT gen — the old base + journal
                    # stay the durable truth until the new base lands —
                    # and remember the keys: their values changed after
                    # the compaction snapshot, so the new base needs them
                    # re-journaled under the new gen at finalize
                    if not self._append_journal(pending, snapshot):
                        self._abort_compaction()
                        return
                    self._compacting["delta"].update(pending)
                self._advance_compaction(finalize)
            return
        if pending is None:
            self._start_compaction(snapshot, finalize)
            return
        if not pending:
            return
        # a delta at or past the compaction threshold (>= so a mass change
        # that marked EVERY uid dirty lands here at the default factor of
        # 1.0) would journal ~the whole state and then compact next flush
        # anyway — writing the state up to 3x; compact instead. One-shot
        # (finalize) compaction skips the journal entirely: the new base
        # lands in THIS call. SLICED compaction journals the delta first —
        # its new base lands many throttle windows later, and a crash in
        # between must not revert these keys to their pre-delta values.
        if len(pending) >= max(self.min_compact_entries, self.compact_factor * len(snapshot)):
            if not finalize and self.compact_slice_entries:
                if not self._append_journal(pending, snapshot):
                    return
            self._start_compaction(snapshot, finalize)
            return
        if not self._append_journal(pending, snapshot):
            return
        if self._journal_entries > max(self.min_compact_entries, self.compact_factor * len(snapshot)):
            self._start_compaction(snapshot, finalize)

    def _append_journal(self, pending: Set[str], snapshot: Dict[str, Any]) -> bool:
        """Append ``pending``'s current values as gen-fenced journal lines;
        False (with a forced full rewrite owed) on failure."""
        lines = []
        for key in pending:
            if key in snapshot:
                lines.append(json.dumps({"g": self._gen, "k": key, "v": snapshot[key]}))
            else:
                lines.append(json.dumps({"g": self._gen, "k": key, "d": True}))
        blob = "\n".join(lines) + "\n"
        try:
            self.journal_path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.journal_path, "a") as fh:
                fh.write(blob)  # one write call: a crash tears at most the tail
        except OSError as exc:
            logger.error("Journal append to %s failed: %s", self.journal_path, exc)
            with self._lock:
                # a SURVIVED write error (ENOSPC mid-flush) can leave a
                # torn line in the MIDDLE of the journal; replay stops at
                # the first malformed line, so any append after the tear
                # would be silently discarded on reload. Force a full
                # compaction (new base, truncated journal) instead of
                # retrying appends past the tear.
                self._pending = None
            return False
        self._journal_entries += len(pending)
        self._publish_io_shadow()
        return True

    # -- sliced compaction -------------------------------------------------

    def _start_compaction(self, snapshot: Dict[str, Any], finalize: bool) -> None:
        """One-shot compact when finalizing (or slicing disabled), else
        open the incremental target and write the first slice."""
        if finalize or not self.compact_slice_entries:
            self._compact(snapshot)
            return
        gen = self._gen + 1
        try:
            self.tmp_path.parent.mkdir(parents=True, exist_ok=True)
            fh = open(self.tmp_path, "w")
            fh.write('{"version": %d, "gen": %d, "map": {' % (_SCHEMA_VERSION, gen))
        except OSError as exc:
            logger.error("Could not open compaction target %s: %s", self.tmp_path, exc)
            with self._lock:
                self._pending = None  # still owe the full write
            return
        self._compacting = {
            "gen": gen,
            "snapshot": snapshot,
            "keys": list(snapshot),
            "idx": 0,
            "fh": fh,
            # keys whose value changed after the snapshot was captured;
            # re-journaled under the new gen at finalize so the new base +
            # journal replay to the LIVE state, not the snapshot
            "delta": set(),
        }
        self._publish_io_shadow()
        self._advance_compaction(finalize=False)

    def _abort_compaction(self) -> None:
        comp = self._compacting
        if comp is None:
            return
        self._compacting = None
        self._publish_io_shadow()
        try:
            comp["fh"].close()
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass
        try:
            self.tmp_path.unlink()
        except OSError:
            pass

    def _compaction_failed(self, what: str, exc: Exception) -> None:
        logger.error("Compaction %s for %s failed: %s", what, self.base_path, exc)
        self._abort_compaction()
        with self._lock:
            self._pending = None  # still owe the full write

    def _advance_compaction(self, finalize: bool) -> None:
        """Serialize the next slice (all remaining when ``finalize``) into
        the target file; rename it into place once every entry is down."""
        comp = self._compacting
        keys = comp["keys"]
        idx = comp["idx"]
        end = len(keys) if finalize else min(idx + self.compact_slice_entries, len(keys))
        if end > idx:
            snapshot = comp["snapshot"]
            dumps = json.dumps
            blob = ",".join(dumps(k) + ":" + dumps(snapshot[k]) for k in keys[idx:end])
            if idx > 0:
                blob = "," + blob
            try:
                comp["fh"].write(blob)
            except OSError as exc:
                self._compaction_failed("slice write", exc)
                return
            comp["idx"] = end
            self._publish_io_shadow()
        if comp["idx"] < len(keys):
            return  # more slices on later flushes
        self._finalize_compaction()

    def _finalize_compaction(self) -> None:
        """Close the target, re-journal the during-compaction delta under
        the NEW generation, then rename the base into place.

        Crash ordering (same fence discipline as ``_compact``):
        - after the delta append, before the rename: the old base is still
          in place, its old-gen journal lines replay, the new-gen delta
          lines are fenced out — consistent;
        - after the rename, before the journal rewrite below: old-gen
          lines are fenced out, the new-gen delta lines replay over the
          new base — consistent. The rewrite is space reclamation only.
        """
        comp = self._compacting
        gen = comp["gen"]
        try:
            comp["fh"].write("}}")
            comp["fh"].close()
        except OSError as exc:
            self._compaction_failed("target close", exc)
            return
        with self._lock:
            current = self._map
            delta_entries = [(k, k in current, current.get(k)) for k in comp["delta"]]
        lines = [
            json.dumps({"g": gen, "k": k, "v": v}) if present
            else json.dumps({"g": gen, "k": k, "d": True})
            for k, present, v in delta_entries
        ]
        if lines:
            try:
                with open(self.journal_path, "a") as jfh:
                    jfh.write("\n".join(lines) + "\n")
            except OSError as exc:
                self._compaction_failed("delta append", exc)
                return
        try:
            os.replace(self.tmp_path, self.base_path)
        except OSError as exc:
            # orphaned future-gen delta lines stay in the journal —
            # harmless, the fence skips them on load
            self._compaction_failed("rename", exc)
            return
        self._compacting = None
        self._gen = gen
        self._journal_entries = len(lines)
        self._publish_io_shadow()
        # reclaim the old-gen (now fenced-out) journal lines; atomic so a
        # crash can't tear the delta lines we just made load-bearing
        _atomic_write(self.journal_path, "\n".join(lines) + "\n" if lines else "")

    def _compact(self, snapshot: Dict[str, Any]) -> None:
        """Rewrite the base from ``snapshot`` under a new generation, then
        truncate the journal. Crash between the two: stale journal lines
        carry the old generation and are skipped on load."""
        gen = self._gen + 1
        payload = json.dumps({"version": _SCHEMA_VERSION, "gen": gen, "map": snapshot})
        if not _atomic_write(self.base_path, payload):
            with self._lock:
                self._pending = None  # still owe a full write
            return
        self._gen = gen
        self._journal_entries = 0
        self._publish_io_shadow()
        try:
            open(self.journal_path, "w").close()
        except OSError as exc:
            # harmless: the stale lines are generation-fenced out on load
            logger.warning("Could not truncate journal %s: %s", self.journal_path, exc)


class CheckpointStore:
    def __init__(
        self,
        path: os.PathLike | str,
        *,
        interval_seconds: float = 5.0,
        metrics=None,  # metrics.MetricsRegistry, optional
    ):
        self.path = Path(path)
        self.interval_seconds = interval_seconds
        self.metrics = metrics
        self._lock = threading.Lock()
        self._state: Dict[str, Any] = {"version": _SCHEMA_VERSION}
        self._dirty = False
        self._last_flush = 0.0
        self._last_flush_ms: Optional[float] = None
        self._journaled: Dict[str, JournaledMapStore] = {}
        self._load()

    def stats(self) -> Dict[str, Any]:
        """Observability snapshot for /debug/checkpoint."""
        with self._lock:
            main_keys = sorted(k for k in self._state if k != "version")
            last_flush_age = time.monotonic() - self._last_flush if self._last_flush else None
            last_flush_ms = self._last_flush_ms
        return {
            "path": str(self.path),
            "interval_seconds": self.interval_seconds,
            "last_flush_age_seconds": round(last_flush_age, 1) if last_flush_age is not None else None,
            "last_flush_ms": last_flush_ms,
            "single_file_keys": main_keys,
            "journaled": {key: s.stats() for key, s in self._journaled.items()},
        }

    def attach_journaled_map(self, key: str, **opts: Any) -> JournaledMapStore:
        """Route ``key`` through an incremental :class:`JournaledMapStore`
        (files ``<checkpoint>.<key>.base.json`` / ``.journal.jsonl``).
        ``get``/``put``/``flush`` keep working unchanged for the key; a
        legacy copy inside the single-file state is migrated out on
        attach, so old checkpoints restore seamlessly."""
        store = JournaledMapStore(self.path.with_name(self.path.name + "." + key), **opts)
        with self._lock:
            legacy = self._state.pop(key, None)
            if legacy is not None:
                self._dirty = True
        if not isinstance(legacy, (dict, type(None))):
            # a foreign writer's garbage (string/list/number) must degrade
            # to a cold map, not crash the first get() — same tolerance as
            # the per-entry checks in watch.py
            logger.warning(
                "Discarding malformed legacy %r section during journaled-map migration", key
            )
            legacy = None
        if legacy is not None and not store.populated:
            # migrate only into a NEVER-populated store: an existing
            # journaled map (even one emptied to {}) is newer truth than
            # a stale legacy section
            store.replace(legacy)  # unknown delta -> full compaction on flush
        self._journaled[key] = store
        return store

    def _load(self) -> None:
        try:
            data = json.loads(self.path.read_text())
        except FileNotFoundError:
            return
        except (json.JSONDecodeError, OSError) as exc:
            logger.warning("Corrupt checkpoint %s (%s); starting cold", self.path, exc)
            return
        if isinstance(data, dict) and data.get("version") == _SCHEMA_VERSION:
            self._state = data
        else:
            logger.warning("Checkpoint %s has unknown schema; starting cold", self.path)

    # -- accessors ---------------------------------------------------------

    def resource_version(self) -> Optional[str]:
        with self._lock:
            return self._state.get("resource_version")

    def update_resource_version(self, rv: str) -> None:
        with self._lock:
            self._state["resource_version"] = rv
            self._dirty = True
        self.maybe_flush()

    def get(self, key: str, default: Any = None) -> Any:
        journaled = self._journaled.get(key)
        if journaled is not None:
            # an empty-but-present map is a real answer (every entry was
            # legitimately deleted — e.g. a cluster drained to zero pods);
            # conflating it with "missing" (the old `current() or default`)
            # resurrected the caller's default state after a restart. The
            # default applies only when the map was NEVER populated.
            if journaled.populated:
                return journaled.current()
            return default
        with self._lock:
            return self._state.get(key, default)

    def put(self, key: str, value: Any, *, changed_keys: Optional[Iterable[str]] = None) -> None:
        journaled = self._journaled.get(key)
        if journaled is not None:
            journaled.replace(value, changed_keys=changed_keys)
            self.maybe_flush()
            return
        with self._lock:
            self._state[key] = value
            self._dirty = True
        self.maybe_flush()

    # -- persistence -------------------------------------------------------

    def due(self) -> bool:
        """True when the throttle window has elapsed — callers with expensive
        snapshots should skip building them entirely until this is True."""
        with self._lock:
            return time.monotonic() - self._last_flush >= self.interval_seconds

    def maybe_flush(self) -> None:
        """Flush if dirty and the throttle interval has elapsed. Throttled
        flushes advance an in-progress base compaction by bounded slices
        (``finalize=False``) so the hot path never eats a whole-map
        serialization in one pause."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_flush < self.interval_seconds:
                return
            if not self._dirty and not any(s.pending for s in self._journaled.values()):
                return
        self.flush(finalize=False)

    def flush(self, finalize: bool = True) -> None:
        t0 = time.perf_counter()
        for store in self._journaled.values():
            store.flush(finalize)
        self._flush_main()
        flush_ms = 1e3 * (time.perf_counter() - t0)
        with self._lock:
            self._last_flush_ms = round(flush_ms, 2)
        if self.metrics is not None:
            self.metrics.counter("checkpoint_flushes").inc()
            self.metrics.histogram("checkpoint_flush_duration").record(flush_ms / 1e3)

    def _flush_main(self) -> None:
        with self._lock:
            # shallow copy under the lock, serialize OUTSIDE it: values are
            # replaced wholesale (put/update_resource_version), never
            # mutated in place (known_pods() documents the same contract),
            # so the copy is consistent — and json.dumps of a 10k-pod
            # skeleton map (~4 MB, tens of ms) must not hold the lock the
            # watch loop takes on every event's _save_rv
            snapshot_state = dict(self._state)
            self._dirty = False
            self._last_flush = time.monotonic()
        snapshot = json.dumps(snapshot_state)
        _atomic_write(self.path, snapshot)
