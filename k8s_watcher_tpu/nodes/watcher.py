"""Resilient node watch loop (companion to k8s/watch.py's pod source).

Runs on its own thread with its OWN ``K8sClient`` (a client carries at most
one live watch — ``abort_watch`` closes it). Same resilience contract as
the pod source: list→watch with resourceVersion resume, exponential
backoff, 410-relist. Node readiness transitions flow two ways:

- a notification payload per transition (``NODE_CONDITION_CHANGE`` /
  ``NODE_DELETED``) through the dispatcher, and
- into the slice tracker (``note_node``), which may emit
  ``SLICE_PHASE_CHANGE`` notifications for slices whose members sit on the
  affected node — THIS is the fast path that beats pod eviction by minutes.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from k8s_watcher_tpu.config.schema import RetryPolicy
from k8s_watcher_tpu.k8s.client import K8sClient, K8sGoneError
from k8s_watcher_tpu.nodes.tracker import NodeTracker
from k8s_watcher_tpu.pipeline.pipeline import Notification

logger = logging.getLogger(__name__)


class NodeWatcher:
    def __init__(
        self,
        client: K8sClient,
        tracker: NodeTracker,
        sink,  # Callable[[Notification], Any] — normally Dispatcher.submit
        *,
        slice_tracker=None,  # slices.SliceTracker: gets note_node() on transitions
        label_selector: Optional[str] = None,
        retry: Optional[RetryPolicy] = None,
        watch_timeout_seconds: int = 300,
        metrics=None,
        list_page_size: int = 500,  # LIST pagination (limit+continue)
    ):
        self.client = client
        self.tracker = tracker
        self.sink = sink
        self.slice_tracker = slice_tracker
        self.label_selector = label_selector
        self.retry = retry or RetryPolicy()
        self.watch_timeout_seconds = watch_timeout_seconds
        self.list_page_size = list_page_size
        self.metrics = metrics
        self.resource_version: Optional[str] = None
        # set once the first node list has been folded: callers (and tests)
        # can sequence against startup instead of racing the initial relist
        self.synced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "NodeWatcher":
        self._thread = threading.Thread(target=self._run, name="node-watch", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.client.abort_watch()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -- internals ---------------------------------------------------------

    def node_existence(self, name: str):
        """Existence answer for the slice plane (``Optional[bool]``): None
        when this watcher's view can't prove absence — before the first
        list has landed, or when a label selector makes the view partial —
        else whether the node is in the cluster view."""
        if self.label_selector is not None or not self.synced.is_set():
            return None
        return self.tracker.exists(name)

    def _emit(self, event_type: str, node: dict, received_monotonic: float) -> None:
        name = (node.get("metadata") or {}).get("name", "")
        was_known = self.tracker.exists(name)
        payloads = self.tracker.observe(event_type, node)
        for payload in payloads:
            self.sink(Notification(payload, received_monotonic, kind="node"))
            if self.metrics is not None:
                self.metrics.counter("node_notifications_enqueued").inc()
        if self.slice_tracker is None:
            return
        # Sync slice state on EVERY determination, not only on notifying
        # transitions: a deleted node re-added Ready arrives as a silent
        # baseline observation, and skipping the sync would leave it in the
        # slice tracker's down-set forever. note_node is a cheap no-op when
        # nothing changes.
        after = self.tracker.is_ready(name)
        if event_type == "DELETED":
            if not was_known:
                # a node never in our cluster view (deleted before the
                # first list): nothing to fold — the slice plane's
                # existence provider / relist reconciliation covers it
                slice_payloads = []
            else:
                # any known node (TPU-tracked or not — a TPU pod can sit on
                # a node whose device plugin never reported) folds as down;
                # exists=False lets the entry prune once unreferenced
                slice_payloads = self.slice_tracker.note_node(name, False, exists=False)
        elif after is not None:  # None = untracked (non-TPU) or unheartbeated
            slice_payloads = self.slice_tracker.note_node(name, bool(after))
        else:
            slice_payloads = []
        for slice_payload in slice_payloads:
            self.sink(Notification(slice_payload, received_monotonic, kind="slice"))
            if self.metrics is not None:
                self.metrics.counter("slice_notifications_enqueued").inc()

    def _relist(self) -> None:
        """Paged node LIST (limit+continue, same contract as the pod
        source's relist): bounded responses, and the listed-name set
        resets when an expired continue token restarts the list from a
        new snapshot — tombstones must come from ONE snapshot's view."""
        now = time.monotonic()
        listed: set = set()
        rv = None
        # shared consumption driver (K8sClient.iter_list_pages): same
        # snapshot-reset/cost-metric invariants as the pod relist, node-
        # prefixed metric names
        for page_rv, items, restarted in K8sClient.iter_list_pages(
            self.client.list_nodes_paged(
                page_size=self.list_page_size, label_selector=self.label_selector,
            ),
            metrics=self.metrics,
            metric_prefix="node_relist",
        ):
            if restarted:
                listed.clear()
            rv = page_rv or rv
            for node in items:
                listed.add((node.get("metadata") or {}).get("name", ""))
                self._emit("ADDED", node, now)
        # nodes that vanished while we were disconnected
        for name in [n for n in self.tracker.known_nodes() if n not in listed]:
            self._emit("DELETED", {"metadata": {"name": name}}, now)
        self.tracker.reconcile_existence(listed)
        # nodes that vanished before we EVER listed them (deleted while the
        # watcher was down/unstarted): no DELETED event exists to fold, so
        # reconcile slice members directly against the fresh node-list.
        # Only an UNfiltered list proves absence — with a label selector a
        # member's node may simply not match the selector.
        if self.slice_tracker is not None and self.label_selector is None:
            for slice_payload in self.slice_tracker.reconcile_nodes(listed):
                self.sink(Notification(slice_payload, now, kind="slice"))
                if self.metrics is not None:
                    self.metrics.counter("slice_notifications_enqueued").inc()
        self.resource_version = rv
        self.synced.set()

    def _run(self) -> None:
        backoff = self.retry.delay_seconds
        need_list = True
        # consecutive watch-phase 410s with nothing healthy in between:
        # the first relists immediately (normal recovery), repeats back
        # off with escalation — same discipline as the pod watch loop,
        # minus the give-up (this daemon thread must never die)
        gone_streak = 0
        while not self._stop.is_set():
            try:
                if need_list:
                    try:
                        self._relist()
                    except K8sGoneError as exc:
                        # the paged LIST's continue tokens kept expiring
                        # (max_restarts exhausted on a churning cluster):
                        # falling through to the watch-phase 410 handler
                        # would relist IMMEDIATELY in a tight loop — back
                        # off like any other error instead
                        logger.warning(
                            "Node LIST failed (%s%s); backing off %.1fs",
                            "continue tokens kept expiring: "
                            if getattr(exc, "token_expiry", False)
                            else "",
                            exc,
                            backoff,
                        )
                        if self._stop.wait(backoff):
                            return
                        backoff = min(
                            backoff * self.retry.backoff_multiplier,
                            self.retry.max_delay_seconds,
                        )
                        continue
                    need_list = False
                for raw in self.client.watch_nodes(
                    resource_version=self.resource_version,
                    timeout_seconds=self.watch_timeout_seconds,
                    label_selector=self.label_selector,
                ):
                    if self._stop.is_set():
                        return
                    obj = raw.get("object") or {}
                    rv = (obj.get("metadata") or {}).get("resourceVersion")
                    if rv:
                        self.resource_version = rv
                    event_type = raw.get("type", "")
                    backoff = self.retry.delay_seconds
                    gone_streak = 0  # a delivered frame breaks the 410 cycle
                    if event_type == "BOOKMARK":
                        continue
                    self._emit(event_type, obj, time.monotonic())
                gone_streak = 0  # surviving a whole window proves the rv
                logger.debug("Node watch window expired; reconnecting from rv=%s", self.resource_version)
            except K8sGoneError:
                logger.warning("Node watch resourceVersion expired; relisting")
                self.resource_version = None
                need_list = True
                gone_streak += 1
                if gone_streak > 1:
                    delay = min(
                        self.retry.delay_seconds
                        * self.retry.backoff_multiplier ** (gone_streak - 2),
                        self.retry.max_delay_seconds,
                    )
                    logger.warning(
                        "Node watch 410d again right after a relist (streak %d); backing off %.1fs",
                        gone_streak, delay,
                    )
                    if self._stop.wait(delay):
                        return
            except Exception as exc:  # noqa: BLE001 — this daemon thread must
                # never die silently: the pod plane's failures crash run() and
                # restart the process, but an uncaught error here would just
                # stop node-driven degradation while the app reports healthy
                if self._stop.is_set():
                    return
                logger.warning("Node watch error (%s); reconnecting in %.1fs", exc, backoff)
                need_list = True  # unknown failure point: relist to resync
                if self._stop.wait(backoff):
                    return
                backoff = min(backoff * self.retry.backoff_multiplier, self.retry.max_delay_seconds)
