"""TPU node condition tracking.

The pod stream alone cannot see the failure mode that matters most for
slice health: a TPU node dropping out (kubelet dead, machine preempted,
ICI brick failure taking the VM down). Its pods can linger in ``Running``
for minutes until the node controller evicts them — long past the <1 s
notify target. Watching ``/api/v1/nodes`` closes that gap: a Ready→NotReady
flip is visible within a kubelet heartbeat, and the slice tracker can mark
every slice with a member on that node Degraded immediately.

Net-new capability (the reference watched only pods; SURVEY.md §2.6), but
squarely inside the north star: "pod-event→notify latency ... or ICI link
fault" — a node drop IS the coarse-grained link fault signal available from
the control plane.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


def node_is_ready(node: Dict[str, Any]) -> Optional[bool]:
    """The Ready condition as a bool, or None if the condition is absent
    (a brand-new node that has not heartbeated yet)."""
    for condition in (node.get("status") or {}).get("conditions") or []:
        if condition.get("type") == "Ready":
            return condition.get("status") == "True"
    return None


def node_tpu_info(
    node: Dict[str, Any],
    *,
    resource_key: str = "google.com/tpu",
    accelerator_label: str = "cloud.google.com/gke-tpu-accelerator",
    topology_label: str = "cloud.google.com/gke-tpu-topology",
) -> Optional[Dict[str, Any]]:
    """TPU facts for a node, or None if it carries no accelerators."""
    status = node.get("status") or {}
    labels = (node.get("metadata") or {}).get("labels") or {}
    chips = 0
    for bucket in ("allocatable", "capacity"):
        value = (status.get(bucket) or {}).get(resource_key)
        if value is not None:
            try:
                chips = max(chips, int(str(value)))
            except ValueError:
                chips = max(chips, 1)
    accelerator = labels.get(accelerator_label)
    if chips <= 0 and not accelerator:
        return None
    return {
        "chips": chips,
        "accelerator": accelerator,
        "topology": labels.get(topology_label),
    }


class NodeTracker:
    """Folds node watch events into per-node readiness state and emits a
    notification payload on every Ready-condition transition.

    ``tpu_only`` (default) ignores non-accelerator nodes — a control-plane
    watcher for TPU slices has no business alerting on every generic node
    in a shared cluster (``tpu.backend: gpu`` swaps the resource key, so
    gpu-compat mode tracks GPU nodes the same way).
    """

    def __init__(
        self,
        environment: str,
        *,
        resource_key: str = "google.com/tpu",
        accelerator_label: str = "cloud.google.com/gke-tpu-accelerator",
        topology_label: str = "cloud.google.com/gke-tpu-topology",
        tpu_only: bool = True,
    ):
        self.environment = environment
        self.resource_key = resource_key
        self.accelerator_label = accelerator_label
        self.topology_label = topology_label
        self.tpu_only = tpu_only
        self._ready: Dict[str, Optional[bool]] = {}
        # EVERY node name in the cluster view, including non-accelerator
        # nodes `tpu_only` skips for readiness tracking: existence is what
        # lets the slice plane tell "node deleted" from "node not yet seen"
        # (a TPU pod can sit on a node whose device plugin hasn't reported
        # capacity yet, so the readiness map alone can't answer that)
        self._exists: set = set()
        self._lock = threading.Lock()

    def is_ready(self, name: str) -> Optional[bool]:
        """Last observed readiness, or None for an unknown node."""
        with self._lock:
            return self._ready.get(name)

    def is_tracked(self, name: str) -> bool:
        """O(1): has this node a readiness entry (TPU-tracked)?"""
        with self._lock:
            return name in self._ready

    def exists(self, name: str) -> bool:
        """O(1): is this node in the cluster view (any node, not just TPU)?"""
        with self._lock:
            return name in self._exists

    def reconcile_existence(self, listed) -> None:
        """Drop existence entries absent from a fresh full node list."""
        with self._lock:
            self._exists &= set(listed)

    def known_nodes(self) -> Dict[str, Optional[bool]]:
        with self._lock:
            return dict(self._ready)

    def observe(self, event_type: str, node: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Fold one node event; returns notification payloads (empty for
        steady-state heartbeats that do not change readiness)."""
        name = (node.get("metadata") or {}).get("name", "")
        if not name:
            return []
        with self._lock:
            if event_type == "DELETED":
                self._exists.discard(name)
            else:
                self._exists.add(name)
        tpu = node_tpu_info(
            node,
            resource_key=self.resource_key,
            accelerator_label=self.accelerator_label,
            topology_label=self.topology_label,
        )
        if self.tpu_only and tpu is None and event_type != "DELETED":
            return []

        if event_type == "DELETED":
            with self._lock:
                was = self._ready.pop(name, None)
            if was is None:
                return []  # never tracked (non-TPU or unseen)
            logger.warning("TPU node %s deleted", name)
            return [self._payload(name, node, "NODE_DELETED", ready=False, tpu=tpu)]

        ready = node_is_ready(node)
        with self._lock:
            previous = self._ready.get(name, _UNSEEN)
            self._ready[name] = ready
        if previous is _UNSEEN:
            # baseline observation: only a node arriving UNhealthy is news
            if ready is False:
                logger.warning("TPU node %s first seen NotReady", name)
                return [self._payload(name, node, "NODE_CONDITION_CHANGE", ready=False, tpu=tpu)]
            return []
        if previous == ready:
            return []  # heartbeat, no transition
        logger.log(
            logging.INFO if ready else logging.WARNING,
            "TPU node %s: Ready %s -> %s", name, previous, ready,
        )
        return [self._payload(name, node, "NODE_CONDITION_CHANGE", ready=bool(ready), tpu=tpu)]

    def _payload(
        self, name: str, node: Dict[str, Any], event_type: str, *, ready: bool, tpu
    ) -> Dict[str, Any]:
        from datetime import datetime, timezone

        conditions = [
            {
                "type": c.get("type"),
                "status": c.get("status"),
                "reason": c.get("reason"),
                "message": c.get("message"),
            }
            for c in (node.get("status") or {}).get("conditions") or []
        ]
        return {
            "event_type": event_type,
            "environment": self.environment,
            "node": name,
            "ready": ready,
            "tpu": tpu,
            "conditions": conditions,
            "unschedulable": bool((node.get("spec") or {}).get("unschedulable")),
            "event_timestamp": datetime.now(timezone.utc).isoformat(),
        }


class _Unseen:
    def __repr__(self):  # pragma: no cover - debug aid
        return "<unseen>"


_UNSEEN = _Unseen()
