from k8s_watcher_tpu.nodes.tracker import NodeTracker, node_is_ready, node_tpu_info
from k8s_watcher_tpu.nodes.watcher import NodeWatcher

__all__ = ["NodeTracker", "NodeWatcher", "node_is_ready", "node_tpu_info"]
