"""Fleet-state materialized view: the serve-side watch cache.

The pipeline makes the watcher fast at *pushing* one notify target; this
module is the plane that lets many downstream consumers *read* fleet
state — schedulers, dashboards, remediation controllers (the ARGUS/Guard
class of consumers in PAPERS.md) — without each of them holding a watch
against the apiserver.

It mirrors the kube-apiserver watch cache, on the serve side of the
pipeline instead of the ingest side:

- ``FleetView`` is a materialized map of the pipeline's output — pod
  phases, slice topology/health, probe verdicts — keyed by ``(kind,
  key)`` with one monotonic **view resourceVersion**: every applied
  delta bumps ``rv`` by exactly 1, so the rv space is *dense* and a
  contiguous delta range ``(from_rv, to_rv]`` provably contains
  ``to_rv - from_rv`` deltas (the property subscribers' gap/dup checkers
  lean on).
- A bounded **delta journal** (the last ``compact_horizon`` deltas)
  backs resumable subscriptions: a consumer takes a snapshot at ``rv``,
  then reads deltas ``> rv``; its resume token is just the last rv it
  applied. Tokens survive reconnects for free — the journal, not the
  connection, is the state.
- **Compaction horizon**: the journal forgets history beyond
  ``compact_horizon`` deltas. A resume token that falls behind the
  horizon gets ``GONE`` (HTTP 410) and the consumer re-snapshots — the
  exact semantics the in-repo mock apiserver implements on the ingest
  side (``MockCluster.events_since`` returning None).
- **Lag shedding**: a subscriber whose pending backlog exceeds its
  ``queue_depth`` does not get the full history replayed; the pending
  range is compacted **latest-wins per key** before delivery. The batch
  is flagged ``compacted`` so sequence checkers know the rv jump is
  sanctioned; per-key final state is still exact (state serving, not
  event logging — same contract as the egress plane's coalescing).

Concurrency: the view is written by the pipeline thread (pods, via the
``publish_batch`` hook) and by sink taps (slices/probes, possibly from
probe/node threads) under one lock; readers (``read_since``/``snapshot``)
share that lock and long-polls wait on its condition. Deltas and objects
are replaced, never mutated, so readers can hand out references without
copies.

Encode-once fan-out (the O(deltas) data plane): every applied delta's
**wire frame** — its serialized payload, already wrapped in HTTP
chunked-transfer framing — is serialized to bytes at most once *per
codec*, into per-codec frame arrays parallel to the journal (trimmed
together). 10k subscribers streaming the same delta in the same codec
all reference the *same* ``bytes`` object; the per-subscriber cost of a
delivery is a buffer append, never a re-serialization. Compacted/paged
batches reuse the per-delta frames and only synthesize the small
COMPACTED/SYNC/GONE control frames. ``GET /serve/fleet`` rides the same
idea one level up: the whole snapshot body is serialized at most once
per ``(rv, codec)`` (``snapshot_bytes``, invalidated implicitly when a
publish bumps rv; one codec's read never evicts the other's body).

Two wire codecs share the frame contract:

- ``json`` (the default, and the PR-4/PR-7 golden contract): one JSON
  line per frame, byte-identical to what the thread-per-connection
  encoder wrote. Local publish paths (``apply``/``publish_batch``)
  encode it eagerly at publish — the PR-7 encodes==publishes invariant
  the fan-out bench gates.
- ``msgpack`` (``Accept: application/x-msgpack``): the same frame dicts
  msgpack-packed — self-delimiting, so the stream needs no line framing
  and a consumer feeds raw reads into a streaming unpacker. Frames are
  built lazily, on the first read that needs them, and memoized into
  the parallel array (still at most one encode per delta per codec).

The merge-facing ``apply_batch`` (federation fan-in) appends *unencoded*
journal entries for BOTH codecs: a federator folding three clusters'
churn storms must not pay a ``json.dumps`` per delta inside its publish
lock for frames no subscriber may ever pull in that codec. The first
subscriber read in a given codec fills the holes (off the publish lock)
and every later read shares the memoized bytes.
"""

from __future__ import annotations

import json
import os
import threading
import time
from bisect import bisect_left, bisect_right
from typing import Any, Callable, Dict, List, Mapping, NamedTuple, Optional, Tuple

from k8s_watcher_tpu.pipeline.phase import pod_key, pod_ready
from k8s_watcher_tpu.pipeline.pipeline import NEVER_IN_VIEW as _NEVER_IN_VIEW
from k8s_watcher_tpu.serve.columns import (
    ColumnarStore,
    assemble_json_body,
    assemble_msgpack_body,
    iter_snapshot_objects,
)
from k8s_watcher_tpu.watch.source import EventType

# msgpack is baked into the image (history/wal.py measured it packing a
# batch ~3x faster than json.dumps in this tree); a stripped environment
# falls back to JSON-only serving — content negotiation simply never
# selects a codec the process cannot encode.
try:
    import msgpack as _msgpack
except ImportError:  # pragma: no cover - the image bakes msgpack in
    _msgpack = None

#: delivery record types on the wire (and in Delta.type)
UPSERT = "UPSERT"
DELETE = "DELETE"

#: wire codecs (the ``Accept`` negotiation vocabulary)
CODEC_JSON = "json"
CODEC_MSGPACK = "msgpack"
CODECS = (CODEC_JSON, CODEC_MSGPACK)

#: frame-variant suffix for freshness-stamped frames (``?fresh=1``):
#: negotiated like the codec, and cached like one — each (codec, fresh)
#: combination is its own parallel frame array, so stamped frames are
#: still encoded at most once per delta per variant while the plain-JSON
#: frames stay byte-golden for every peer that did not ask for stamps
FRESH_SUFFIX = "+ts"
#: frame-variant suffix for trace-forwarding frames (``?trace=1``):
#: sampled deltas additionally carry their journey's compact ``trace``
#: field. Trace implies freshness (the federator derives ``serve_wire``
#: from the ``ts`` stamps), so the traced variants always stack on
#: ``+ts`` — six parallel arrays total, each still encode-once
TRACE_SUFFIX = "+tr"
FRAME_VARIANTS = (
    CODEC_JSON,
    CODEC_MSGPACK,
    CODEC_JSON + FRESH_SUFFIX,
    CODEC_MSGPACK + FRESH_SUFFIX,
    CODEC_JSON + FRESH_SUFFIX + TRACE_SUFFIX,
    CODEC_MSGPACK + FRESH_SUFFIX + TRACE_SUFFIX,
)


def frame_variant(codec: str, fresh: bool, traced: bool = False) -> str:
    """The frame-array key for one negotiated (codec, freshness, trace)
    triple. ``traced`` implies the stamped variant."""
    if traced:
        return codec + FRESH_SUFFIX + TRACE_SUFFIX
    return codec + FRESH_SUFFIX if fresh else codec
JSON_CONTENT_TYPE = "application/json"
MSGPACK_CONTENT_TYPE = "application/x-msgpack"
CODEC_CONTENT_TYPES = {
    CODEC_JSON: JSON_CONTENT_TYPE,
    CODEC_MSGPACK: MSGPACK_CONTENT_TYPE,
}


def msgpack_available() -> bool:
    """Whether this process can encode/decode the msgpack wire codec
    (the server advertises/falls back to JSON when it cannot)."""
    return _msgpack is not None

#: read_since verdicts
OK = "ok"
GONE = "gone"  # resume token fell behind the compaction horizon -> 410
INVALID = "invalid"  # token ahead of the view (restart or client bug);
# the HTTP layer answers 410 so bare-rv clients recover by re-snapshot


class Delta(NamedTuple):
    """One journaled view mutation. ``object`` is None for DELETE.

    ``ts_wall`` is the ORIGIN stamp: the wall-clock time the mutation was
    first observed entering the system — the watch event's receive stamp
    for pods, the apply time for sink-tap producers, and for federated
    deltas the stamp PROPAGATED from the upstream frame (so a second-tier
    federator still measures true end-to-end age). ``pub_wall`` is when
    THIS view published the delta; the gap between them is what the
    freshness plane's histograms attribute per hop. Wall clocks because
    origin and reader may be different hosts (monotonic stamps don't
    cross machines); ARCHITECTURE.md documents the skew caveat.
    """

    rv: int
    kind: str  # "pod" | "slice" | "probe"
    key: str
    type: str  # UPSERT | DELETE
    object: Optional[Dict[str, Any]]
    t: float  # monotonic append stamp (feeds the delta-lag histogram)
    ts_wall: Optional[float] = None  # origin wall stamp (None = unknown)
    pub_wall: float = 0.0  # publish wall stamp (0 = unstamped/restored)
    # the sampled journey riding this delta, for the negotiated ?trace=1
    # wire field: a live trace.Trace on the local publish path (its spans
    # snapshot at encode time), or the upstream's already-compact dict on
    # the federation fan-in path (merge.apply_batch 5-tuples). None for
    # the unsampled 255/256 — the plain wire dict never changes shape.
    # Never persisted: the WAL's delta records carry explicit fields.
    trace: Optional[Any] = None

    def to_wire(self, fresh: bool = False, trace: bool = False) -> Dict[str, Any]:
        out = {"type": self.type, "rv": self.rv, "kind": self.kind, "key": self.key}
        if self.object is not None:
            out["object"] = self.object
        if fresh and self.ts_wall is not None:
            # the negotiated freshness field: [origin_wall, publish_wall]
            # — consumers derive serve-wire latency from the second and
            # end-to-end propagation age from the first. Only present
            # when the peer asked (?fresh=1); the default wire dict is
            # byte-identical to the PR-4 golden.
            out["ts"] = [self.ts_wall, self.pub_wall]
        if trace and self.trace is not None:
            # the negotiated trace field (?trace=1): the journey's
            # identity + local spans so far, compacted at encode time —
            # a federation dict passes through verbatim (second hop)
            if isinstance(self.trace, dict):
                out["trace"] = self.trace
            else:
                from k8s_watcher_tpu.trace.trace import wire_trace

                out["trace"] = wire_trace(self.trace)
        return out


def frame_body(obj: Mapping[str, Any], codec: str = CODEC_JSON) -> bytes:
    """One frame's wire payload, pre-chunk-framing. JSON: the PR-4
    golden line (default ``json.dumps`` separators + trailing newline).
    msgpack: ``packb`` of the same dict — self-delimiting, no line
    framing needed (the decoded dict equals the decoded JSON line)."""
    if codec == CODEC_MSGPACK:
        if _msgpack is None:
            raise RuntimeError("msgpack codec requested but msgpack is not importable")
        return _msgpack.packb(obj, use_bin_type=True)
    return (json.dumps(obj) + "\n").encode()


def chunk_frame(obj: Mapping[str, Any], codec: str = CODEC_JSON) -> bytes:
    """One wire frame: the codec payload wrapped in HTTP chunked-transfer
    framing (``<hex len>\\r\\n<payload>\\r\\n``). For JSON the payload is
    byte-identical to what the PR-4 thread-per-connection encoder wrote
    — chunk *boundaries* moved from per-batch to per-frame, which
    dechunking erases; the de-chunked byte stream a client sees is
    unchanged. Used for every frame on a watch stream: per-delta frames
    (encoded at most once per codec) and the small per-connection
    SYNC/COMPACTED/GONE control frames."""
    return chunk_wrap(frame_body(obj, codec))


def chunk_wrap(payload: bytes) -> bytes:
    """Wrap already-encoded payload bytes in the per-frame
    chunked-transfer framing — the ONE place the framing shape lives.
    The relay's raw passthrough calls this directly: upstream payload
    bytes re-framed (a length prefix, never a re-serialization)."""
    return b"%x\r\n" % len(payload) + payload + b"\r\n"


def frame_payload(frame: bytes) -> bytes:
    """Strip the chunked-transfer framing off one ``chunk_frame`` result
    (test/debug helper — the inverse a dechunking client applies)."""
    head, _, rest = frame.partition(b"\r\n")
    size = int(head, 16)
    return rest[:size]


_RV_MARK = b'"rv": '


def splice_frame_rv(payload: bytes, rv: int) -> Optional[bytes]:
    """Replace the frame-level ``rv`` number in an already-encoded JSON
    frame payload (a ``frame_body`` line, pre-chunk-framing) with ``rv``
    — the fan-in passthrough's ONLY byte mutation on this side of the
    process boundary. The first ``"rv": `` in the line is always the
    frame's own (the wire dict opens ``{"type": ..., "rv": ...`` and
    ``type`` is drawn from UPSERT/DELETE). Returns None when the shape
    is not recognized — the caller falls back to a lazy re-encode hole,
    never a corrupt frame."""
    i = payload.find(_RV_MARK)
    if i < 0:
        return None
    j = i + len(_RV_MARK)
    k = j
    n = len(payload)
    while k < n and payload[k] in b"-0123456789":
        k += 1
    if k == j or k >= n or payload[k] not in b",}":
        return None
    return b"%s%d%s" % (payload[:j], rv, payload[k:])


class ReadResult(NamedTuple):
    """One ``read_since`` pull.

    ``status == OK``: ``deltas`` covers exactly ``(from_rv, to_rv]``.
    When ``compacted`` is False the deltas are the contiguous journal
    range (``len(deltas) == to_rv - from_rv``, dense rv space); when True
    they are the latest-wins per-key summary of that range — every key
    touched in the range appears once, at its newest rv, so applying them
    reproduces the view state at ``to_rv`` for those keys.
    """

    status: str
    from_rv: int
    to_rv: int
    compacted: bool
    deltas: List[Delta]


class FrameReadResult(NamedTuple):
    """One ``read_frames_since`` pull: ``read_since`` semantics plus the
    publish-time wire frames, parallel to ``deltas`` (``frames[i]`` is
    ``deltas[i]`` already chunk-framed). The bytes objects are SHARED
    across every subscriber pulling the same rv range — append them,
    never mutate them."""

    status: str
    from_rv: int
    to_rv: int
    compacted: bool
    deltas: List[Delta]
    frames: List[bytes]


class FleetView:
    def __init__(
        self,
        *,
        compact_horizon: int = 8192,
        metrics=None,  # metrics.MetricsRegistry, optional
        columnar: bool = True,
    ):
        self.compact_horizon = max(1, int(compact_horizon))
        self.metrics = metrics
        # the columnar core (serve/columns.py): fleet state as parts +
        # int columns instead of a dict of dicts — same rv line, same
        # dedup, byte-identical bodies/frames; ``columnar=False`` keeps
        # the dict core (the A/B reference and the ``serve.columnar:
        # off`` escape hatch)
        self.columnar = bool(columnar)
        # This incarnation of the rv space. rv restarts at 0 with the
        # process ("the journal is the state" — and the journal dies with
        # it), so a resume token is only meaningful inside the instance
        # that minted it: a pre-restart token grafted onto the new rv
        # space would pass every dense-range gap check while silently
        # merging two incarnations' states. Clients echo this id; the
        # server answers 410 on mismatch (re-snapshot), same recovery as
        # the compaction horizon.
        self.instance = os.urandom(6).hex()
        self._cond = threading.Condition()
        self._rv = 0
        self._oldest_rv = 0  # deltas with rv <= this are compacted away
        # dict-of-dicts on the reference core; the columnar store speaks
        # the same (kind, key)-keyed mapping protocol, so the relay fold
        # and the debug pokes read either shape
        self._objects = ColumnarStore() if self.columnar else {}
        # parallel append-only arrays (trimmed together at the horizon):
        # bisect over _delta_rvs finds a resume point in O(log n);
        # _frames[codec][i] is _deltas[i]'s wire frame in that codec,
        # serialized AT MOST ONCE per codec — eagerly at publish for
        # JSON on the local paths (the encode-once contract the fan-out
        # bench gates), lazily on first read everywhere else (msgpack
        # frames, and everything appended by the merge-facing
        # apply_batch). A ``None`` entry is a hole the next read in
        # that codec fills and memoizes.
        self._delta_rvs: List[int] = []
        self._deltas: List[Delta] = []
        self._frames: Dict[str, List[Optional[bytes]]] = {
            variant: [] for variant in FRAME_VARIANTS
        }
        # (rv, codec)-keyed snapshot byte cache: rebuilt at most once per
        # rv PER CODEC, served only while rv is still current (a publish
        # invalidates by bumping rv) — a msgpack snapshot read must not
        # evict the JSON body, or an A/B-consuming tier would thrash both
        self._snapshot_cache: Dict[str, Tuple[int, bytes]] = {}
        # relay mode: the journal may be SPARSE below this rv — an
        # upstream that latest-wins-compacted the relay's own stream
        # skips rvs the relay can never journal. Reads whose resume token
        # falls below it are flagged compacted (the skip is sanctioned
        # downstream exactly the way the upstream sanctioned it to us);
        # 0 = dense (every local publish path keeps it 0).
        self._relay_sparse_rv = 0
        # rv-keyed per-kind object tables (snapshot_tables): ONE object
        # walk per rv shared by every per-kind consumer — the health
        # plane's phase collector and the analytics encoder both read
        # this instead of each re-classifying the full snapshot per tick
        self._tables_cache: Optional[Tuple[int, Dict[str, List[Dict[str, Any]]]]] = None
        # post-publish wakeups OUTSIDE the lock (the broadcast event
        # loop's one-wakeup-per-publish signal; never the per-waiter
        # notify_all herd)
        self._wakeups: List[Callable[[], None]] = []
        # durable history plane (history.HistoryStore), when enabled:
        # every applied delta is handed off (O(1) enqueue) UNDER the
        # publish lock — that lock ordering is what keeps the WAL
        # rv-ordered across the pipeline thread and the sink-tap threads
        self._history = None
        self._publish_seconds = (
            metrics.histogram("serve_publish_seconds") if metrics is not None else None
        )
        self._delta_lag = (
            metrics.histogram("serve_delta_lag_seconds") if metrics is not None else None
        )
        self._deltas_published = (
            metrics.counter("serve_deltas_published") if metrics is not None else None
        )
        self._rv_gauge = metrics.gauge("serve_view_rv") if metrics is not None else None
        self._encode_seconds = (
            metrics.histogram("serve_encode_seconds") if metrics is not None else None
        )
        self._frame_encodes = (
            metrics.counter("serve_frame_encodes") if metrics is not None else None
        )
        self._frame_encodes_mp = (
            metrics.counter("serve_frame_encodes_msgpack") if metrics is not None else None
        )
        # freshness-stamped frame fills pay their own counter: the PR-7
        # encodes==publishes amortization gate is defined over the plain
        # JSON publish path and must not be perturbed by a stamped peer
        self._frame_encodes_fresh = (
            metrics.counter("serve_frame_encodes_fresh") if metrics is not None else None
        )
        # trace-forwarding fills likewise bill their own counter — the
        # amortization gate stays stated over the plain JSON publish path
        self._frame_encodes_trace = (
            metrics.counter("serve_frame_encodes_trace") if metrics is not None else None
        )
        self._snap_hits = (
            metrics.counter("serve_snapshot_cache_hits") if metrics is not None else None
        )
        self._snap_misses = (
            metrics.counter("serve_snapshot_cache_misses") if metrics is not None else None
        )
        # per-codec breakdown as REAL labels (`...{codec="json"}`); the
        # parents above keep the cross-codec totals
        self._snap_hits_by_codec = (
            {c: self._snap_hits.labels(codec=c) for c in CODECS}
            if metrics is not None
            else None
        )
        self._snap_misses_by_codec = (
            {c: self._snap_misses.labels(codec=c) for c in CODECS}
            if metrics is not None
            else None
        )
        # freshness plane: how long a mutation took from its origin stamp
        # (watch receive for pods; apply for sink taps) to local view
        # visibility — monotonic clock, same host, no skew
        self._watch_to_local = (
            metrics.histogram("watch_to_local_view_seconds") if metrics is not None else None
        )
        # the columnar core's own instruments (RUNBOOK "Columnar view
        # core"): per-publish apply cost and the store's resident-bytes
        # estimate (0 on the dict core — no cheap estimator there)
        self._apply_seconds = (
            metrics.histogram("view_apply_seconds") if metrics is not None else None
        )
        self._resident_bytes = (
            metrics.gauge("view_resident_bytes") if metrics is not None else None
        )

    # -- durable history (restart-surviving rv line) -----------------------

    def restore(
        self,
        *,
        instance: str,
        rv: int,
        objects: Dict[Tuple[str, str], Dict[str, Any]],
        journal: List[Delta],
    ) -> None:
        """Adopt WAL-recovered state: the previous incarnation's instance
        id, its rv line (new deltas continue from ``rv``), its objects,
        and the preloaded journal tail (rv-ascending, contiguous, ending
        at ``rv``) so pre-restart resume tokens read straight from
        memory. Call before any publish (app wiring does)."""
        with self._cond:
            self.instance = instance
            self._rv = rv
            if self.columnar:
                # reseed the columns in place: interners KEEP their codes
                # across the restore (the analytics-encoder stability
                # contract, now a core property), and nothing serializes
                # here — the first body build flushes lazily
                self._objects.reseed(objects)
            else:
                self._objects = dict(objects)
            self._deltas = list(journal)
            self._delta_rvs = [d.rv for d in journal]
            # holes, not eager re-encodes: a restart must not pay
            # O(journal) json.dumps before serving — the first resumed
            # subscriber's read fills (and memoizes) exactly what it pulls
            self._frames = {variant: [None] * len(journal) for variant in FRAME_VARIANTS}
            self._snapshot_cache = {}
            # restore() can re-seed the SAME rv with different objects
            # (replay re-seeding across a rebase hole) — rv keying alone
            # would serve the old incarnation's tables
            self._tables_cache = None
            # tokens older than the preloaded tail 410 — the compaction-
            # horizon contract, now spanning incarnations
            self._oldest_rv = journal[0].rv - 1 if journal else rv
            self._relay_sparse_rv = 0
            if self._rv_gauge is not None:
                self._rv_gauge.set(self._rv)

    def attach_history(self, history) -> None:
        """Wire the durable WAL (history.HistoryStore): deltas flow to
        it from every apply path; it reads the live state back only on
        overrun rebase."""
        history.state_provider = self.state_for_history
        self._history = history

    def state_for_history(self) -> Tuple[int, Dict[Tuple[str, str], Dict[str, Any]]]:
        """``(rv, {(kind, key): obj})`` — the WAL writer's rebase anchor
        (objects are replaced, never mutated, so the copy is shallow).
        On the columnar core the structural snapshot is taken under the
        lock and the O(fleet) object reconstruction happens outside it —
        rebase is the rare overrun path, not a hot one."""
        with self._cond:
            if not self.columnar:
                return self._rv, dict(self._objects)
            rv = self._rv
            snap = self._objects.snapshot_parts(with_keys=True)
        return rv, {
            (kind, key): obj for kind, key, obj in iter_snapshot_objects(snap)
        }

    # -- relay mode (upstream-mirrored rv line; relay/plane.py) ------------

    def adopt_relay(
        self,
        *,
        instance: str,
        rv: int,
        objects: Dict[Tuple[str, str], Dict[str, Any]],
    ) -> None:
        """Adopt an UPSTREAM serving plane's state wholesale: its view
        instance id, its rv, its objects — the relay tier's snapshot
        reconcile. Unlike ``restore()`` (which runs before any serving),
        this can happen MID-LIFE (upstream restart / relay fell past the
        upstream horizon), so parked waiters are woken and the wakeup
        hooks fire: existing subscribers discover the resync as
        GONE/INVALID (410 → re-snapshot FROM THIS RELAY — the recovery
        herd lands here, not on the root) instead of idling against a
        swapped rv space. The journal resets empty; ``publish_relayed``
        backfill entries re-extend ``oldest_rv`` downward afterwards so
        recent resume tokens keep working across the adopt."""
        with self._cond:
            self.instance = instance
            self._rv = rv
            if self.columnar:
                self._objects.reseed(objects)
            else:
                self._objects = dict(objects)
            self._delta_rvs = []
            self._deltas = []
            self._frames = {variant: [] for variant in FRAME_VARIANTS}
            self._snapshot_cache = {}
            self._tables_cache = None
            self._relay_sparse_rv = 0
            self._oldest_rv = rv
            if self._rv_gauge is not None:
                self._rv_gauge.set(rv)
            self._cond.notify_all()
        for fn in self._wakeups:
            fn()

    def publish_relayed(
        self,
        entries,
        *,
        variant: str = CODEC_JSON,
        fold_objects: bool = True,
    ) -> int:
        """Append upstream-journaled deltas VERBATIM at their upstream
        rvs — the relay tier's publish path. ``entries`` is a list of
        ``(Delta, frame_or_None)`` pairs: the Delta carries the decoded
        wire metadata (its ``rv`` is the UPSTREAM's — rv is adopted, not
        minted), and ``frame`` is the upstream's frame payload already
        chunk-framed, stored into the ``variant`` frame array untouched.
        That is the zero-re-encode contract: ``serve_frame_encodes*``
        stays 0 for relayed deltas; every other variant journals a hole
        that the usual lazy ``_fill_frames`` path fills (at most once
        per delta per variant) for subscribers that negotiated a shape
        the upstream wire didn't carry.

        ``fold_objects=False`` is the BACKFILL path: entries older than
        the adopted snapshot extend the journal (and lower
        ``oldest_rv``) without touching object state — the snapshot
        already reflects them, and replaying them into the map would
        expose intermediate states to concurrent readers.

        A skip in the upstream rv sequence (the upstream latest-wins-
        compacted OUR stream) marks the journal sparse up to that rv;
        reads resuming below the mark are flagged compacted so
        downstream gap checkers get the same sanction we did.

        Deliberately NOT wired to the history WAL: a relay is a
        stateless edge (schema forbids relay+history) — durability
        belongs to the root that owns the rv line."""
        if not entries:
            return 0
        appended = 0
        first_rv = None
        with self._cond:
            for delta, frame in entries:
                rv = delta.rv
                if self._delta_rvs:
                    last = self._delta_rvs[-1]
                    if rv <= last:
                        continue  # overlap with already-journaled wire reads
                    if rv > last + 1:
                        # upstream-sanctioned skip (its COMPACTED covered
                        # it); sanction our own readers below this rv
                        self._relay_sparse_rv = max(self._relay_sparse_rv, rv)
                elif fold_objects and rv > self._rv + 1:
                    # first live entry after an adopt already skips past
                    # the snapshot rv: same upstream-sanctioned hole
                    self._relay_sparse_rv = max(self._relay_sparse_rv, rv)
                if fold_objects:
                    map_key = (delta.kind, delta.key)
                    if delta.type == DELETE:
                        self._objects.pop(map_key, None)
                    else:
                        self._objects[map_key] = delta.object
                self._delta_rvs.append(rv)
                self._deltas.append(delta)
                for v in FRAME_VARIANTS:
                    self._frames[v].append(frame if v == variant else None)
                if first_rv is None:
                    first_rv = rv
                appended += 1
            if appended:
                self._rv = max(self._rv, self._delta_rvs[-1])
                # backfill lowers the horizon: tokens minted against the
                # pre-adopt journal resume from memory again
                self._oldest_rv = min(self._oldest_rv, first_rv - 1)
                self._trim_locked()
                if self._rv_gauge is not None:
                    self._rv_gauge.set(self._rv)
                self._cond.notify_all()
        if appended:
            if self._deltas_published is not None:
                self._deltas_published.inc(appended)
            for fn in self._wakeups:
                fn()
        return appended

    def note_upstream_rv(self, rv: int) -> int:
        """Adopt an upstream rv seen WITHOUT a journal entry (a SYNC
        heartbeat that outran the deltas we hold — only possible when
        the upstream compacted/paged our stream). The journal goes
        sparse up to ``rv`` so the jump is sanctioned, exactly like a
        delta-carried skip. Returns the (possibly unchanged) view rv."""
        with self._cond:
            if rv > self._rv:
                self._rv = rv
                self._relay_sparse_rv = max(self._relay_sparse_rv, rv)
                if self._rv_gauge is not None:
                    self._rv_gauge.set(self._rv)
                self._cond.notify_all()
            else:
                return self._rv
        for fn in self._wakeups:
            fn()
        return rv

    # -- writing (pipeline thread + sink taps) ----------------------------

    def register_wakeup(self, fn: Callable[[], None]) -> None:
        """Register a post-publish wakeup hook, called OUTSIDE the lock
        after every publish that applied at least one delta. This is the
        broadcast event loop's signal: one call per publish, not one
        ``notify_all`` herd per blocked socket thread."""
        self._wakeups.append(fn)

    def unregister_wakeup(self, fn: Callable[[], None]) -> None:
        """Withdraw a wakeup hook (loop shutdown): a stopped loop must
        not keep being called per publish against torn-down pipes."""
        try:
            self._wakeups.remove(fn)
        except ValueError:
            pass

    def _encode_locked(self, delta: Delta) -> bytes:
        """Serialize ``delta``'s JSON wire frame — the once in
        encode-once for the local publish paths. Called under the lock,
        before the delta becomes visible to any reader, so memoization
        needs no CAS and the encode counter is exact (the bench's
        amortization gate: encodes == publishes, independent of
        subscriber count)."""
        if self._encode_seconds is not None:
            t0 = time.perf_counter()
            frame = chunk_frame(delta.to_wire())
            self._encode_seconds.record(time.perf_counter() - t0)
        else:
            frame = chunk_frame(delta.to_wire())
        if self._frame_encodes is not None:
            self._frame_encodes.inc()
        return frame

    def _apply_locked(
        self,
        kind: str,
        key: str,
        obj: Optional[Dict[str, Any]],
        now: float,
        encode: bool = True,
        ts_wall: Optional[float] = None,
        pub_wall: float = 0.0,
        trace: Optional[Any] = None,
        frame: Optional[bytes] = None,
    ) -> bool:
        """One delta under the lock. Returns False for no-ops (identical
        upsert, delete of an absent key) — no rv burn, no journal entry.
        ``encode=False`` (the merge-facing batch path) journals a hole in
        every codec's frame array instead of paying json.dumps here; the
        first read in a codec fills it. ``ts_wall``/``pub_wall`` are the
        freshness plane's origin/publish stamps; ``trace`` is the sampled
        journey the ?trace=1 wire forwards (see ``Delta``). ``frame`` is
        the fan-in passthrough's pre-encoded JSON payload (an upstream
        ``frame_body`` line, already re-keyed): the minted rv is spliced
        into the bytes and the result fills the plain-JSON frame slot —
        no encode here, no lazy re-encode later. An unrecognized shape
        falls back to the hole (correctness over the fast path)."""
        if self.columnar:
            # the store owns dedup (exact dict-core parity: identical
            # upsert / absent-key delete mint no rv) and defers pod
            # serialization to the next reader's flush — the hot-path
            # apply cost here is one pending-dict write
            if obj is None:
                if not self._objects.delete(kind, key):
                    return False
                delta_type = DELETE
            else:
                if not self._objects.upsert(kind, key, obj):
                    return False
                delta_type = UPSERT
        else:
            map_key = (kind, key)
            if obj is None:
                if self._objects.pop(map_key, None) is None:
                    return False
                delta_type = DELETE
            else:
                if self._objects.get(map_key) == obj:
                    return False
                self._objects[map_key] = obj
                delta_type = UPSERT
        self._rv += 1
        delta = Delta(self._rv, kind, key, delta_type, obj, now, ts_wall, pub_wall, trace)
        self._delta_rvs.append(self._rv)
        self._deltas.append(delta)
        if encode:
            json_frame: Optional[bytes] = self._encode_locked(delta)
        elif frame is not None:
            spliced = splice_frame_rv(frame, self._rv)
            json_frame = chunk_wrap(spliced) if spliced is not None else None
        else:
            json_frame = None
        self._frames[CODEC_JSON].append(json_frame)
        # every other variant (msgpack, and both freshness-stamped
        # shapes) is ALWAYS lazy: most deployments never attach such a
        # subscriber, and the ones that do pay once, at read time
        for variant in FRAME_VARIANTS:
            if variant != CODEC_JSON:
                self._frames[variant].append(None)
        return True

    def _trim_locked(self) -> None:
        """Enforce the compaction horizon; amortized — trims in quarter-
        horizon chunks so steady publishing pays O(1) amortized."""
        overflow = len(self._deltas) - self.compact_horizon
        if overflow < max(1, self.compact_horizon // 4):
            return
        self._oldest_rv = self._delta_rvs[overflow - 1]
        del self._delta_rvs[:overflow]
        del self._deltas[:overflow]
        for frames in self._frames.values():
            del frames[:overflow]

    def apply(
        self,
        kind: str,
        key: str,
        obj: Optional[Dict[str, Any]],
        *,
        ts_wall: Optional[float] = None,
        trace: Optional[Any] = None,
    ) -> bool:
        """Upsert (``obj``) or delete (``obj is None``) one object and wake
        subscribers. Public single-delta shape (benches, sink taps).
        ``ts_wall`` overrides the origin stamp (default: now — for a sink
        tap, the apply IS the origin); ``trace`` rides the ?trace=1 wire
        (the merge's per-delta baseline path propagates it here)."""
        now = time.monotonic()
        wall = time.time()
        with self._cond:
            changed = self._apply_locked(
                kind, key, obj, now,
                ts_wall=ts_wall if ts_wall is not None else wall, pub_wall=wall,
                trace=trace,
            )
            if changed:
                if self._history is not None:
                    # BEFORE the trim: a horizon shorter than the burst
                    # must never cost the WAL a delta. The already-encoded
                    # JSON frame rides along so the WAL writer reuses the
                    # bytes instead of re-packing the object
                    self._history.publish(
                        self._deltas[-1:], frames=self._frames[CODEC_JSON][-1:]
                    )
                self._trim_locked()
                if self._rv_gauge is not None:
                    self._rv_gauge.set(self._rv)
                self._cond.notify_all()
        if changed:
            if self._deltas_published is not None:
                self._deltas_published.inc()
            if self._apply_seconds is not None:
                self._apply_seconds.record(time.monotonic() - now)
            if self._resident_bytes is not None and self.columnar:
                self._resident_bytes.set(self._objects.resident_bytes())
            for fn in self._wakeups:
                fn()
        return changed

    def apply_batch(self, items) -> int:
        """Fold a batch of ``(kind, key, obj_or_None)`` mutations under
        ONE publish-lock hold, with one history hand-off, one gauge set,
        one ``notify_all`` and one coalesced wakeup for the whole batch —
        the merge-facing mirror of the pipeline's ``publish_batch``, so a
        federation fan-in storm costs per-batch, not per-delta, locking.

        Frames are journaled as holes (``encode=False``): the fan-in hot
        path must not pay a per-delta ``json.dumps`` inside the lock for
        bytes no subscriber may ever pull in that codec; the first read
        in each codec fills and memoizes them (still at most one encode
        per delta per codec). Returns the number of deltas minted
        (identical upserts and absent-key deletes are free).

        Items are ``(kind, key, obj_or_None)`` or — the federation
        fan-in's stamped shape — ``(kind, key, obj_or_None, ts_wall)``,
        carrying the upstream frame's ORIGIN stamp so the merged delta
        keeps measuring true end-to-end age (and a second-tier federator
        propagates it again). A fifth element carries the upstream's
        compact ``trace`` dict (the ?trace=1 field) so the merged view's
        republished frames keep the journey's identity across hops. A
        sixth element is the sharded fan-in's PASSTHROUGH frame: the
        upstream's already-encoded JSON payload (re-keyed by the merge
        worker), which fills this view's plain-JSON frame slot with only
        an rv splice — the encode-once invariant held across the process
        boundary."""
        now = time.monotonic()
        wall = time.time()
        changed = 0
        with self._cond:
            for item in items:
                kind, key, obj = item[0], item[1], item[2]
                ts = item[3] if len(item) > 3 and item[3] is not None else wall
                tr = item[4] if len(item) > 4 else None
                fr = item[5] if len(item) > 5 else None
                if self._apply_locked(
                    kind, key, obj, now, encode=False, ts_wall=ts, pub_wall=wall,
                    trace=tr, frame=fr,
                ):
                    changed += 1
            if changed:
                if self._history is not None:
                    # pre-trim, one O(1) hand-off for the whole batch —
                    # the deltas are the journal tail (appended under
                    # THIS lock hold, so they are contiguous); passthrough
                    # frames ride along for WAL byte reuse (holes re-pack)
                    self._history.publish(
                        self._deltas[-changed:],
                        frames=self._frames[CODEC_JSON][-changed:],
                    )
                self._trim_locked()
                if self._rv_gauge is not None:
                    self._rv_gauge.set(self._rv)
                self._cond.notify_all()
        if changed:
            if self._deltas_published is not None:
                self._deltas_published.inc(changed)
            if self._publish_seconds is not None:
                self._publish_seconds.record(time.monotonic() - now)
            if self._apply_seconds is not None:
                self._apply_seconds.record(time.monotonic() - now)
            if self._resident_bytes is not None and self.columnar:
                self._resident_bytes.set(self._objects.resident_bytes())
            for fn in self._wakeups:
                fn()
        return changed

    def publish_batch(self, events, results) -> int:
        """The pipeline hook: fold one processed batch into the view —
        one lock hold, one subscriber wake, for the whole batch.

        Only events that *passed the filters* enter the fleet view.
        ``no_significant_change`` events are applied too: phase/readiness
        significance gates *notification*, but fields the view serves and
        the pipeline doesn't weigh — ``nodeName`` after the scheduler
        binds a Pending pod, the pod resourceVersion — may still have
        moved, and ``_apply_locked``'s identical-upsert dedup makes true
        no-ops free (no rv burn, no wake). DELETED events drop the key.

        Sampled journeys still OPEN here — not handed off to the
        dispatcher, i.e. suppressed/insignificant events whose only
        egress IS the serving plane — get a ``serve_fanout`` span
        covering this batch's publish (the pipeline publishes before it
        finishes those journeys). Handed-off traces belong to the
        dispatcher's thread by now (finish() reads spans once), so they
        are left alone.
        """
        t_start = time.monotonic()
        wall = time.time()
        changed = 0
        stamp = []
        applied_watch_stamps: List[float] = []
        with self._cond:
            for event, result in zip(events, results):
                if result.reason in _NEVER_IN_VIEW:
                    continue
                # origin stamp = the watch receive stamp (wall for the
                # wire's cross-host field, monotonic for the same-host
                # watch_to_local_view histogram below)
                ts_wall = getattr(event, "received_at", None) or wall
                # the sampled journey (1/N) rides its delta onto the
                # ?trace=1 wire — the LIVE Trace object, so spans stamped
                # after this publish (the traced variants encode lazily,
                # on first traced read) still make the wire
                event_trace = getattr(event, "trace", None)
                if event.type == EventType.DELETED:
                    meta = (event.pod or {}).get("metadata") or {}
                    applied = self._apply_locked(
                        "pod", pod_key(meta), None, t_start,
                        ts_wall=ts_wall, pub_wall=wall, trace=event_trace,
                    )
                else:
                    uid, obj = _pod_object(event)
                    applied = self._apply_locked(
                        "pod", uid, obj, t_start, ts_wall=ts_wall, pub_wall=wall,
                        trace=event_trace,
                    )
                if applied:
                    changed += 1
                    received = getattr(event, "received_monotonic", None)
                    if received is not None:
                        applied_watch_stamps.append(received)
                trace = getattr(event, "trace", None)
                if trace is not None and not trace.handed_off:
                    stamp.append(trace)
            t_wal = 0.0
            if changed:
                if self._history is not None:
                    # one O(1) hand-off for the whole batch, pre-trim;
                    # the span below attributes the enqueue cost (disk
                    # latency lives on the WAL writer thread — see
                    # history_wal_write_seconds)
                    t_wal = time.monotonic()
                    self._history.publish(
                        self._deltas[-changed:],
                        frames=self._frames[CODEC_JSON][-changed:],
                    )
                self._trim_locked()
                if self._rv_gauge is not None:
                    self._rv_gauge.set(self._rv)
                self._cond.notify_all()
        t_end = time.monotonic()
        for trace in stamp:
            trace.add_span("serve_fanout", t_start, t_end)
            if t_wal:
                trace.add_span("wal_append", t_wal, t_end)
        if changed:
            if self._deltas_published is not None:
                self._deltas_published.inc(changed)
            if self._publish_seconds is not None:
                self._publish_seconds.record(t_end - t_start)
            if self._apply_seconds is not None:
                self._apply_seconds.record(t_end - t_start)
            if self._resident_bytes is not None and self.columnar:
                self._resident_bytes.set(self._objects.resident_bytes())
            if self._watch_to_local is not None:
                # per applied delta: watch receive -> view visibility,
                # both stamps monotonic on THIS host (no wall skew)
                for received in applied_watch_stamps:
                    self._watch_to_local.record(max(0.0, t_end - received))
            for fn in self._wakeups:
                fn()
        return changed

    def observe_notification(self, notification) -> None:
        """Sink tap for the derived planes: slice aggregates and probe
        verdicts ride the dispatcher sink; this folds them into the view.
        Pod payloads are ignored — pods enter via ``publish_batch``, which
        sees every post-filter event (the critical gate suppresses pod
        *notifications*, never view state)."""
        kind = notification.kind
        payload = notification.payload
        if kind == "slice":
            key = payload.get("slice")
            if not key:
                return
            transition = payload.get("phase_transition") or {}
            if transition.get("to") == "Terminated":
                self.apply("slice", key, None)
            else:
                self.apply("slice", key, {"kind": "slice", "key": key, **payload})
        elif kind == "probe":
            key = str(payload.get("host") or "local")
            self.apply("probe", key, {"kind": "probe", "key": key, **payload})

    # -- reading (serve plane / subscribers) ------------------------------

    @property
    def rv(self) -> int:
        with self._cond:
            return self._rv

    @property
    def oldest_rv(self) -> int:
        with self._cond:
            return self._oldest_rv

    def token_status(self, rv: int) -> str:
        """``OK``/``GONE``/``INVALID`` verdict for a resume token WITHOUT
        reading deltas — the pre-stream check. A reconnect storm after a
        consumer outage (the 410/resume scenario) must cost two compares
        per connect, not a discarded O(pending) latest-wins walk."""
        with self._cond:
            if rv > self._rv:
                return INVALID
            if rv < self._oldest_rv:
                return GONE
            return OK

    def snapshot(self) -> Tuple[int, List[Dict[str, Any]]]:
        """``(rv, objects)`` — the GET-snapshot shape. Dict core:
        objects are the live references (replaced on write, never
        mutated), so the copy is shallow and O(objects). Columnar core:
        the structural snapshot is taken under the lock and pod dicts
        are reconstructed from their fragments OUTSIDE it (equal by
        value to what was stored; side objects are the live refs)."""
        with self._cond:
            if not self.columnar:
                return self._rv, list(self._objects.values())
            rv = self._rv
            snap = self._objects.snapshot_parts()
        return rv, [obj for _kind, _key, obj in iter_snapshot_objects(snap)]

    def snapshot_bytes(self, codec: str = CODEC_JSON) -> bytes:
        """The serialized ``GET /serve/fleet`` body, rebuilt at most once
        per ``(rv, codec)``: built on first read, served from cache while
        rv is unchanged, invalidated implicitly by the next publish (the
        cache entry is keyed by the rv it was built at; a bumped rv
        simply stops matching). The per-codec entries are independent —
        a msgpack read never evicts the JSON body (and vice versa), so a
        mixed-codec dashboard tier still costs one serialization per
        delta per codec, not one per request."""
        with self._cond:
            cached = self._snapshot_cache.get(codec)
            if cached is not None and cached[0] == self._rv:
                if self._snap_hits is not None:
                    self._snap_hits.inc()
                    self._snap_hits_by_codec[codec].inc()
                return cached[1]
            rv = self._rv
            instance = self.instance
            if self.columnar:
                snap = self._objects.snapshot_parts()
                objects = None
            else:
                objects = list(self._objects.values())
        # serialize OUTSIDE the lock (O(fleet) work must not stall
        # publishes); parts bytes are immutable and objects are
        # replaced-never-mutated, so either snapshot shape is consistent
        if objects is None:
            # columnar: the JSON body is a join over already-serialized
            # fragments (only keys CHANGED since the last reader pay a
            # dumps, inside snapshot_parts' flush); msgpack composes the
            # same parts element-by-element. Both byte-identical to the
            # dict walk below.
            if codec == CODEC_MSGPACK:
                if _msgpack is None:
                    raise RuntimeError("msgpack codec requested but msgpack is not importable")
                data = assemble_msgpack_body(
                    rv, instance, snap,
                    lambda o: _msgpack.packb(o, use_bin_type=True),
                )
            else:
                data = assemble_json_body(rv, instance, snap)
        else:
            body = {"rv": rv, "view": instance, "objects": objects}
            if codec == CODEC_MSGPACK:
                if _msgpack is None:
                    raise RuntimeError("msgpack codec requested but msgpack is not importable")
                data = _msgpack.packb(body, use_bin_type=True)
            else:
                data = json.dumps(body).encode()
        with self._cond:
            # store keyed by the rv it was built at; if a publish landed
            # meanwhile, the next read sees the mismatch and rebuilds
            self._snapshot_cache[codec] = (rv, data)
        if self._snap_misses is not None:
            self._snap_misses.inc()
            self._snap_misses_by_codec[codec].inc()
        return data

    def object_count(self) -> int:
        with self._cond:
            return len(self._objects)

    def snapshot_tables(self) -> Tuple[int, Dict[str, List[Dict[str, Any]]]]:
        """``(rv, {kind: [objects]})`` — the bulk per-kind snapshot
        accessor: one object walk, grouped by kind, built at most once
        per rv and shared by reference across consumers (the health
        plane's phase collector and the analytics encoder both read the
        SAME walk instead of re-classifying the snapshot each). Objects
        are the live references (replaced on write, never mutated) and
        the lists/dict are shared — treat the whole result as
        immutable. The grouping happens OUTSIDE the lock (O(fleet) work
        must not stall publishes); a publish landing mid-build just
        means the next read rebuilds at the new rv."""
        with self._cond:
            cached = self._tables_cache
            if cached is not None and cached[0] == self._rv:
                return cached
            rv = self._rv
            if self.columnar:
                snap = self._objects.snapshot_parts(with_keys=True)
                items = None
            else:
                items = list(self._objects.items())
        tables: Dict[str, List[Dict[str, Any]]] = {}
        if items is None:
            for kind, _key, obj in iter_snapshot_objects(snap):
                tables.setdefault(kind, []).append(obj)
        else:
            for (kind, _key), obj in items:
                tables.setdefault(kind, []).append(obj)
        result = (rv, tables)
        with self._cond:
            if self._rv == rv:
                self._tables_cache = result
        return result

    # -- zero-copy columnar readers (health/analytics/federation) ---------

    def fleet_columns(self):
        """``(rv, FleetColumns)`` straight off the columnar core — the
        analytics plane's arrays, materialized at most once per dirty
        generation and shared by reference (the per-request re-encode
        collapses to this handle). Columnar core only; the dict core's
        consumers keep the encoder/snapshot_tables path."""
        with self._cond:
            return self._rv, self._objects.fleet_columns()

    def fleet_handle(self):
        """``(rv, PodHandle)`` — the health plane's per-pod sequences
        (keys/phases/nodes) plus the live slice objects, decoded from
        the columns at most once per dirty generation. Columnar core
        only. Treat every field as immutable; the handle is shared."""
        with self._cond:
            return self._rv, self._objects.pod_handle()

    def federated_keys(self) -> List[Tuple[str, str, str]]:
        """``(kind, global_key, cluster_name)`` for every federated
        object — the merge registry's reseed, WITHOUT reconstructing a
        million local pods (the columnar core answers off its cluster
        column; the dict core walks objects)."""
        with self._cond:
            if self.columnar:
                return self._objects.federated_entries()
            entries = []
            for (kind, key), obj in self._objects.items():
                cluster = obj.get("cluster") if isinstance(obj, dict) else None
                if cluster:
                    entries.append((kind, key, str(cluster)))
            return entries

    def freshness(self) -> Dict[str, Any]:
        """The local view's freshness watermark (the /debug/freshness
        ``local`` section): how old the newest published delta is, by the
        local monotonic publish stamp AND by its origin wall stamp. An
        idle fleet legitimately ages here — the watermark says "nothing
        newer has been seen", never "something is wrong" by itself; the
        SLO plane is what turns age into a verdict."""
        with self._cond:
            rv = self._rv
            objects = len(self._objects)
            last = self._deltas[-1] if self._deltas else None
        out: Dict[str, Any] = {
            "rv": rv,
            "objects": objects,
            "last_delta_age_seconds": (
                round(time.monotonic() - last.t, 3) if last is not None else None
            ),
        }
        if last is not None and last.ts_wall is not None:
            # origin-stamped age (wall clock: comparable across hosts,
            # subject to the documented skew caveat)
            out["last_delta_origin_age_seconds"] = round(
                max(0.0, time.time() - last.ts_wall), 3
            )
        return out

    def read_since(
        self,
        rv: int,
        *,
        max_deltas: int = 128,
        limit: Optional[int] = None,
        timeout: float = 0.0,
    ) -> ReadResult:
        """Deltas ``> rv``, the subscription primitive.

        - token behind the horizon -> ``GONE`` (client re-snapshots);
        - token ahead of the view -> ``INVALID`` (client bug);
        - backlog ``<= max_deltas`` -> the raw contiguous range;
        - backlog ``> max_deltas`` (a lagging subscriber) -> the range
          compacted latest-wins per key, flagged ``compacted`` — the
          bounded per-connection queue materialized at read time;
        - nothing pending -> block up to ``timeout`` seconds (long-poll),
          then return an empty OK batch (``from_rv == to_rv``).

        ``limit`` is a **page bound, never lossy**: at most ``limit``
        deltas are returned and ``to_rv`` retreats to the last delivered
        rv, so the client resumes from ``to_rv`` and pages through the
        rest — nothing is dropped. It is deliberately a different knob
        from ``max_deltas`` (the lag-shedding threshold): a healthy
        subscriber asking for small pages must not be forced into the
        latest-wins compaction path. Truncating a *compacted* batch at a
        delta boundary is sound too — the batch is rv-sorted, so every
        key whose newest rv is ``> to_rv`` is simply re-delivered by the
        next page. Non-positive ``limit`` means unpaged (the HTTP layer
        rejects negatives before they get here).
        """
        status, from_rv, to_rv, compacted, deltas, _ = self._read(
            rv, max_deltas, limit, timeout, want_frames=False
        )
        return ReadResult(status, from_rv, to_rv, compacted, deltas)

    def read_frames_since(
        self,
        rv: int,
        *,
        max_deltas: int = 128,
        limit: Optional[int] = None,
        timeout: float = 0.0,
        codec: str = CODEC_JSON,
        fresh: bool = False,
        traced: bool = False,
    ) -> FrameReadResult:
        """``read_since`` plus the wire frames in ``codec`` — the
        broadcast path. ``frames[i]`` is ``deltas[i]`` chunk-framed in
        that codec, encoded AT MOST ONCE per delta per codec and shared
        by reference across every subscriber pulling this range
        (compacted and paged batches included — they subset the same
        bytes objects). Holes left by lazy paths (msgpack, the merge's
        ``apply_batch``) are filled off the publish lock and memoized.
        ``fresh`` selects the freshness-stamped frame variant (its own
        parallel array — stamped peers share stamped bytes, unstamped
        peers keep the byte-golden plain frames); ``traced`` selects the
        trace-forwarding variant (always stamped — trace implies fresh)."""
        return FrameReadResult(
            *self._read(
                rv, max_deltas, limit, timeout, want_frames=True,
                variant=frame_variant(codec, fresh, traced),
            )
        )

    def _fill_frames(self, deltas: List[Delta], frames: List[Optional[bytes]], variant: str) -> None:
        """Encode the ``None`` holes in one pulled frame slice (OFF the
        publish lock — a large catch-up read must not stall publishers
        behind O(pending) serialization), then memoize the results back
        into the master array under a short lock hold. A delta's
        position is found by rv bisect, not ``rv - base`` arithmetic: a
        RELAY journal can be sparse (upstream-compacted holes), and the
        lookup is equally trim-safe on dense local journals — an
        already-trimmed delta simply isn't memoized. Two racing readers
        may both encode the same hole (identical bytes; last write wins)
        — the eager JSON publish path never races because its frames are
        minted under the lock, before the delta is readable.

        Cost note: on the broadcast path this runs on the epoll worker
        thread, like the latest-wins compaction walk always has (PR-7
        deliberately moved O(pending) read work off the publish lock and
        onto the puller). The fill is bounded by what the pull DELIVERS
        — ``max_deltas``/``queue_depth`` raw, unique-keys-in-range
        compacted — and is paid once per delta per codec ever."""
        traced = variant.endswith(TRACE_SUFFIX)
        base = variant[: -len(TRACE_SUFFIX)] if traced else variant
        fresh = base.endswith(FRESH_SUFFIX)
        codec = base[: -len(FRESH_SUFFIX)] if fresh else base
        t0 = time.perf_counter() if self._encode_seconds is not None else 0.0
        encoded: List[Tuple[int, bytes]] = []
        for i, frame in enumerate(frames):
            if frame is None:
                frame = chunk_frame(deltas[i].to_wire(fresh=fresh, trace=traced), codec)
                frames[i] = frame
                encoded.append((deltas[i].rv, frame))
        if not encoded:
            return
        if self._encode_seconds is not None:
            self._encode_seconds.record(time.perf_counter() - t0)
        if traced:
            counter = self._frame_encodes_trace
        elif fresh:
            # stamped/traced variants bill their own counters: the PR-7
            # encodes==publishes invariant is stated over the plain
            # JSON path and must stay exact with stamped peers attached
            counter = self._frame_encodes_fresh
        else:
            counter = self._frame_encodes if codec == CODEC_JSON else self._frame_encodes_mp
        if counter is not None:
            counter.inc(len(encoded))
        with self._cond:
            master = self._frames[variant]
            rvs = self._delta_rvs
            if not rvs:
                return
            for frame_rv, frame in encoded:
                # bisect, not rv-base arithmetic: a RELAY journal can be
                # sparse (upstream-compacted holes), so position is found
                # by rv lookup — O(log n), trim-safe, dense-safe too
                pos = bisect_left(rvs, frame_rv)
                if pos < len(master) and rvs[pos] == frame_rv and master[pos] is None:
                    master[pos] = frame

    def _read(
        self,
        rv: int,
        max_deltas: int,
        limit: Optional[int],
        timeout: float,
        want_frames: bool,
        variant: str = CODEC_JSON,
    ) -> Tuple[str, int, int, bool, List[Delta], List[bytes]]:
        deadline = time.monotonic() + timeout if timeout > 0 else None
        frames: List[bytes] = []
        with self._cond:
            while True:
                if rv > self._rv:
                    return (INVALID, rv, rv, False, [], [])
                if rv < self._oldest_rv:
                    # covers falling behind *while waiting*, too
                    return (GONE, rv, rv, False, [], [])
                pending = self._rv - rv
                if pending:
                    break
                remaining = deadline - time.monotonic() if deadline is not None else 0.0
                if remaining <= 0:
                    return (OK, rv, rv, False, [], [])
                # wait the FULL remaining window: publishes notify the
                # condition, GONE/INVALID can only change on a publish,
                # and the deadline re-check above handles spurious wakes
                # — so an idle long-poll sleeps once, instead of the old
                # 0.5 s self-tick that woke every parked waiter (5k idle
                # once=1 pollers = 10k wasted wakeups/s) to discover
                # nothing happened
                self._cond.wait(timeout=remaining)
            idx = bisect_right(self._delta_rvs, rv)
            to_rv = self._rv
            # ONLY the slice happens under the lock (an O(pending) ref
            # copy of an append-only journal — front-trims mutate the
            # shared list, so the slice is an independent snapshot); the
            # latest-wins walk below must NOT hold the lock, or 5k lagging
            # subscribers' compactions serialize every publish behind them
            deltas = self._deltas[idx:]
            if want_frames:
                frames = self._frames[variant][idx:]
            sparse_rv = self._relay_sparse_rv
        if not deltas:
            # only reachable on a sparse relay journal (note_upstream_rv
            # advanced rv past a journal with no entries pending): an
            # empty batch advancing to to_rv, sanctioned by the sparse
            # mark so the skip never reads as a gap
            return (OK, rv, to_rv, rv < sparse_rv, [], [])
        oldest_pending_t = deltas[0].t
        if pending <= max_deltas:
            # a relay journal may be sparse below _relay_sparse_rv (the
            # upstream compacted our stream): a resume token under the
            # mark gets the compacted flag so the rv skips are sanctioned
            # downstream — per-key latest-wins still holds (the upstream's
            # compaction was latest-wins, and anything newer is here)
            compacted = rv < sparse_rv
        else:
            # latest-wins per key over the slice; the journal is
            # rv-ascending, so keeping each key's last INDEX and sorting
            # indices preserves rv order and keeps deltas/frames parallel
            latest: Dict[Tuple[str, str], int] = {}
            for i, delta in enumerate(deltas):
                latest[(delta.kind, delta.key)] = i
            order = sorted(latest.values())
            deltas = [deltas[i] for i in order]
            if want_frames:
                frames = [frames[i] for i in order]
            compacted = True
        if limit is not None and 0 < limit < len(deltas):
            deltas = deltas[:limit]
            if want_frames:
                frames = frames[:limit]
            to_rv = deltas[-1].rv
        if want_frames:
            # fill lazy holes for exactly what this pull delivers (after
            # compaction/paging subset the range — never for deltas the
            # subscriber won't receive)
            self._fill_frames(deltas, frames, variant)
        if self._delta_lag is not None:
            # lag = how stale the oldest pending delta had become by the
            # time this pull delivered it
            self._delta_lag.record(time.monotonic() - oldest_pending_t)
        return (OK, rv, to_rv, compacted, deltas, frames)


def _pod_object(event) -> Tuple[str, Dict[str, Any]]:
    """The compact pod view object — what a fleet-state consumer needs to
    route/diagnose, not the whole manifest."""
    pod = event.pod or {}
    meta = pod.get("metadata") or {}
    status = pod.get("status") or {}
    uid = pod_key(meta)
    return uid, {
        "kind": "pod",
        "key": uid,
        "name": meta.get("name", ""),
        "namespace": meta.get("namespace", ""),
        "phase": status.get("phase", "Unknown"),
        "ready": pod_ready(pod),
        "node": (pod.get("spec") or {}).get("nodeName"),
        "pod_resource_version": meta.get("resourceVersion"),
    }


class Subscription:
    """One consumer's resumable cursor into the view.

    A subscription is *just* the cursor plus accounting — the journal is
    shared, so 5k subscribers cost 5k small objects, not 5k queues. Pull
    from ONE thread at a time (each connection/poller owns its cursor;
    the view itself is the thread-safe part).
    """

    __slots__ = ("view", "sub_id", "rv", "queue_depth", "pulls", "compacted_pulls", "resyncs")

    def __init__(self, view: FleetView, sub_id: int, rv: int, queue_depth: int):
        self.view = view
        self.sub_id = sub_id
        self.rv = rv
        self.queue_depth = queue_depth
        self.pulls = 0
        self.compacted_pulls = 0
        self.resyncs = 0

    def _advance(self, result):
        """ONE cursor-advance rule for both pull shapes — the threaded
        and broadcast paths must never diverge on resume semantics."""
        self.pulls += 1
        if result.status == OK:
            self.rv = result.to_rv
            if result.compacted:
                self.compacted_pulls += 1
        return result

    def pull(self, *, timeout: float = 0.0, limit: Optional[int] = None) -> ReadResult:
        """One cursor advance. ``queue_depth`` (the subscription's
        bounded-queue size) is the only lag-shedding trigger; ``limit``
        only pages the response (non-lossy, see ``read_since``)."""
        return self._advance(
            self.view.read_since(
                self.rv, max_deltas=self.queue_depth, limit=limit, timeout=timeout
            )
        )

    def pull_frames(
        self,
        *,
        timeout: float = 0.0,
        limit: Optional[int] = None,
        codec: str = CODEC_JSON,
        fresh: bool = False,
        traced: bool = False,
    ) -> FrameReadResult:
        """``pull`` returning the wire frames in ``codec`` alongside the
        deltas — the broadcast core's (and fan-out bench's) shape; the
        frames are shared bytes, a delivery is a buffer append. ``fresh``
        selects the freshness-stamped frame variant; ``traced`` the
        trace-forwarding one."""
        return self._advance(
            self.view.read_frames_since(
                self.rv, max_deltas=self.queue_depth, limit=limit, timeout=timeout,
                codec=codec, fresh=fresh, traced=traced,
            )
        )

    def rebase(self, rv: int) -> None:
        """Reset the cursor after a GONE -> re-snapshot resync."""
        self.rv = rv
        self.resyncs += 1


class SubscriptionHub:
    """Registry + admission control for subscriptions.

    Enforces ``max_subscribers`` (the fan-out budget — every active
    subscriber costs journal reads on publish-adjacent paths) and owns
    the subscriber-count gauge.
    """

    def __init__(
        self,
        view: FleetView,
        *,
        max_subscribers: int = 5000,
        queue_depth: int = 128,
        metrics=None,
    ):
        self.view = view
        self.max_subscribers = max(1, int(max_subscribers))
        self.queue_depth = max(1, int(queue_depth))
        self.metrics = metrics
        self._lock = threading.Lock()
        self._active: Dict[int, Subscription] = {}
        self._next_id = 0
        self._rejected = metrics.counter("serve_subscribers_rejected") if metrics else None
        self._gauge = metrics.gauge("serve_subscribers") if metrics else None

    def subscribe(self, rv: Optional[int] = None) -> Optional[Subscription]:
        """A new subscription resuming from ``rv`` (default: the current
        view rv, i.e. "deltas from now"). None when the hub is full."""
        with self._lock:
            if len(self._active) >= self.max_subscribers:
                if self._rejected is not None:
                    self._rejected.inc()
                return None
            self._next_id += 1
            sub = Subscription(
                self.view,
                self._next_id,
                rv if rv is not None else self.view.rv,
                self.queue_depth,
            )
            self._active[sub.sub_id] = sub
            if self._gauge is not None:
                self._gauge.set(len(self._active))
            return sub

    def unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            self._active.pop(sub.sub_id, None)
            if self._gauge is not None:
                self._gauge.set(len(self._active))

    @property
    def active_count(self) -> int:
        with self._lock:
            return len(self._active)
