"""HTTP surface of the serving plane: snapshot + resumable delta watch.

kube-apiserver-style contract on one resource, ``/serve/fleet``:

- ``GET /serve/fleet`` → ``{"rv": N, "view": "<id>", "objects": [...]}``
  — the snapshot. ``view`` identifies this incarnation of the rv space
  (rv restarts at 0 when the watcher restarts).
- ``GET /serve/fleet?watch=1&rv=N`` → chunked stream of JSON-line delta
  frames ``> N`` (UPSERT/DELETE, plus SYNC heartbeats that advance the
  resume token on idle streams and a COMPACTED marker when lag shedding
  collapsed a range). The stream closes cleanly after ``timeout``
  seconds (default 30) with a final SYNC frame; the client reconnects
  with ``rv=<last SYNC/delta rv>`` — that IS the resume protocol.
- ``GET /serve/fleet?watch=1&rv=N&once=1`` → long-poll: one JSON body
  ``{"from_rv", "to_rv", "compacted", "items"}`` (curl-friendly).
- ``&limit=K`` is a **page bound** (kube ``limit``/``continue`` spirit):
  at most K items per response, ``to_rv`` retreats to the last delivered
  rv, and the client pages by resuming from it — never lossy. Lag
  shedding (latest-wins compaction) is governed ONLY by the server-side
  ``serve.queue_depth``, never by a client's page size.
- A resume token behind the compaction horizon answers **410 Gone**
  (pre-stream) or an in-band ``GONE`` frame (mid-stream); the consumer
  re-snapshots and resubscribes from the new rv. Pass the snapshot's
  ``view`` id back as ``&view=<id>`` and a watcher restart (new rv
  space, rv reset to 0 — a bare rv could silently graft onto it) also
  answers 410 instead of serving wrong deltas; long-poll bodies and
  SYNC frames echo ``view`` so the loop can carry it.
- ``once=1`` long-poll windows are capped at ``MAX_LONG_POLL_SECONDS``
  (a dead long-poll socket is invisible until we write, and an orphaned
  window pins a subscriber slot; streams heartbeat, so they may run the
  full ``MAX_WATCH_SECONDS``).
- ``GET /serve/healthz`` → open liveness (never needs the token, same
  contract as the status server's /healthz).
- **Codec negotiation**: ``Accept: application/x-msgpack`` selects the
  compact msgpack codec on every ``/serve/fleet`` shape — snapshot,
  ``?watch=1`` streams, ``&once=1`` long-polls and ``?at=`` time travel
  (response bodies, stream frames, and the 410/400 recovery bodies all
  ride the negotiated codec; Content-Type says which one won). The
  decoded payloads are identical across codecs; only JSON bodies are
  byte-stable (the golden contract). A server without msgpack — or any
  other Accept value — serves JSON; the fallback can only widen the
  wire, never fail a request.

Auth reuses the status plane's bearer contract (metrics/server.py
``bearer_authorized`` — constant-time compare): when the watcher runs
with ``watcher.status_auth_token``, every /serve route except
/serve/healthz requires ``Authorization: Bearer <token>`` — the serving
plane must not be an unauthenticated side door to fleet state.

The HTTP threads here are a FRONT, not the data plane: a ``?watch=1``
stream's handshake (parse/auth/pre-stream 410/headers) runs on the
per-connection thread, then the socket is handed off non-blocking to
the broadcast event loop (serve/broadcast.py), which writes
publish-time-encoded frame bytes to every stream — the thread returns
to the pool immediately. Snapshots serve the view's rv-keyed byte
cache; ``?at=`` reconstructions sit in a small LRU. With
``serve.io_threads: 0`` the legacy thread-per-connection streamer
(``_stream``) carries watches instead — the reference implementation
the equivalence tests compare the loop against.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler
from typing import Optional
from urllib.parse import parse_qs, urlparse

from k8s_watcher_tpu.metrics.server import (
    QuietThreadingHTTPServer,
    bearer_authorized,
    send_json,
)
from k8s_watcher_tpu.serve.broadcast import BroadcastLoop
from k8s_watcher_tpu.serve.view import (
    CODEC_CONTENT_TYPES,
    CODEC_JSON,
    CODEC_MSGPACK,
    GONE,
    INVALID,
    MSGPACK_CONTENT_TYPE,
    FleetView,
    SubscriptionHub,
    frame_body,
    msgpack_available,
)

logger = logging.getLogger(__name__)

#: server-side cap on one watch window; clients reconnect (resume) past it
MAX_WATCH_SECONDS = 300.0
#: tighter cap for once= long-polls: a dead long-poll socket is
#: undetectable until we write (streams heartbeat every 2 s, so they may
#: run the full window), and each orphaned window pins a subscriber slot
#: + handler thread — a reconnect storm must not 503 the hub for 5 min
MAX_LONG_POLL_SECONDS = 30.0
#: idle heartbeat cadence: SYNC frames keep the resume token fresh and
#: prove the stream is alive through proxies
SYNC_INTERVAL_SECONDS = 2.0


class _HandoffHTTPServer(QuietThreadingHTTPServer):
    """ThreadingHTTPServer that can RELEASE a connection to the broadcast
    event loop: a handler marks its socket handed off and the server's
    per-request teardown (``shutdown(SHUT_WR)`` + ``close()``) skips it —
    the loop owns the fd from then on. Without this, the handler thread
    returning would FIN the stream the loop just adopted."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._handed_off = set()
        self._handoff_lock = threading.Lock()

    def hand_off(self, request) -> None:
        with self._handoff_lock:
            self._handed_off.add(request)

    def shutdown_request(self, request) -> None:
        with self._handoff_lock:
            if request in self._handed_off:
                self._handed_off.discard(request)
                return
        super().shutdown_request(request)


class _AtCache:
    """Tiny LRU for ``?at=rv`` reconstructions: dashboards polling the
    same historical rv must not re-read WAL segments per request. Keys
    carry the view instance id AND the history store's ``cache_epoch``
    (bumped on overrun rebase and retention deletion), so anything that
    can change what an rv reconstructs to simply stops matching."""

    def __init__(self, capacity: int = 16):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()

    def get(self, key):
        with self._lock:
            body = self._entries.get(key)
            if body is not None:
                self._entries.move_to_end(key)
            return body

    def put(self, key, body: bytes) -> None:
        with self._lock:
            self._entries[key] = body
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)


class _ServeHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True
    # socket timeout (reads AND writes): a stalled-but-alive consumer
    # (paused container, zero-window proxy) must not block write_frames
    # forever — TCP zero-window probes keep such a peer "connected"
    # indefinitely, and a blocked write never re-checks the watch
    # deadline, pinning one OS thread + one max_subscribers slot each.
    # With this set, the blocked write raises and the finally-
    # unsubscribe in _serve_watch frees the slot.
    timeout = 30.0
    view: FleetView
    hub: SubscriptionHub
    plane = None  # the owning ServePlane (health payload)
    history = None  # history.HistoryStore -> ?at= time-travel reads
    analytics = None  # analytics.AnalyticsPlane -> /serve/analytics
    # trace.TraceRing -> GET /debug/trace on the SERVE port: the lazy
    # stitch path a downstream federator queries for this process's local
    # spans (its federation config only knows the serve URL; the status
    # port is a separate, possibly unreachable, surface). Bearer-gated
    # like every serve route; 404 when tracing is off.
    trace = None
    loop: Optional[BroadcastLoop] = None  # epoll core; None = threaded streams
    at_cache: Optional[_AtCache] = None  # ?at= reconstruction LRU
    at_hits = None  # metrics counters (bound by ServeServer when wired)
    at_misses = None
    auth_token: Optional[str] = None

    def log_message(self, *a):
        pass

    def _json(self, status: int, body: dict) -> None:
        send_json(self, status, body)

    def _body_bytes(self, status: int, data: bytes, content_type: str = "application/json") -> None:
        """A pre-serialized body (snapshot byte cache / ?at= LRU /
        msgpack): the Content-Length framing of ``send_json`` without
        re-encoding."""
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_obj(self, status: int, body: dict, codec: str) -> None:
        """One bounded response body in the negotiated codec (errors
        included — a msgpack consumer's one decode path must cover the
        410/400 bodies it recovers from, not just the 200s)."""
        if codec == CODEC_MSGPACK:
            self._body_bytes(status, frame_body(body, CODEC_MSGPACK), MSGPACK_CONTENT_TYPE)
        else:
            self._json(status, body)

    def _codec(self) -> str:
        """Content negotiation: ``Accept: application/x-msgpack`` (and a
        server that can encode it) selects the compact codec; everything
        else — including a stripped no-msgpack build — serves JSON. The
        fallback is silent and lossless by design: codecs carry the same
        frame dicts, so a consumer that offered msgpack and got JSON
        just runs its JSON decode path."""
        accept = (self.headers.get("Accept") or "").lower()
        if msgpack_available() and MSGPACK_CONTENT_TYPE in accept:
            return CODEC_MSGPACK
        return CODEC_JSON

    def do_GET(self):  # noqa: N802
        parsed = urlparse(self.path)
        path = parsed.path
        if path == "/serve/healthz":
            health = self.plane.health() if self.plane is not None else {"healthy": True}
            self._json(200 if health.get("healthy", True) else 503, health)
            return
        if not bearer_authorized(self.headers.get("Authorization"), self.auth_token):
            self.send_response(401)
            self.send_header("WWW-Authenticate", "Bearer")
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        if path == "/serve/analytics":
            # keep_blank_values: "" is MEANINGFUL here (?drain_cluster=
            # names the local cluster) — the default drop would silently
            # answer the summary instead of the rehearsal the operator
            # asked for
            self._serve_analytics(
                {k: v[0] for k, v in parse_qs(
                    parsed.query, keep_blank_values=True
                ).items()},
                self._codec(),
            )
            return
        if path == "/debug/trace":
            from k8s_watcher_tpu.metrics.server import trace_ring_response

            status, body = trace_ring_response(
                self.trace, {k: v[0] for k, v in parse_qs(parsed.query).items()}
            )
            self._json(status, body)
            return
        if path != "/serve/fleet":
            self._json(404, {"error": f"no route {path}"})
            return
        params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        codec = self._codec()
        if params.get("watch") in ("1", "true"):
            self._serve_watch(params, codec)
            return
        if "at" in params:
            self._serve_at(params, codec)
            return
        # (rv, codec)-keyed snapshot byte cache: serialized at most once
        # per rv per codec (rebuilt on first read after a publish), so a
        # polling dashboard tier costs one serialization per DELTA, not
        # one per request
        self._body_bytes(
            200, self.view.snapshot_bytes(codec=codec), CODEC_CONTENT_TYPES[codec]
        )

    def _serve_analytics(self, params: dict, codec: str = CODEC_JSON) -> None:
        """``GET /serve/analytics``: the fleet's columnar rollup, or a
        batched what-if evaluation (ARCHITECTURE.md "Analytics plane").

        Shapes (all bearer-gated and codec-negotiated like every other
        serve route):

        - no params -> the summary (rollup + quorum/capacity stance +
          the declared scenario vocabulary);
        - ``?scenarios=<json array>`` -> batched evaluation (at most
          ``analytics.max_scenarios`` per request, 400 past it);
        - ``?drain_cluster=<name>`` / ``?cordon_nodes=a,b`` -> the two
          common questions as curl-friendly single-scenario sugar.
        """
        if self.analytics is None:
            self._send_obj(
                404,
                {"error": "analytics plane disabled (analytics.enabled)"},
                codec,
            )
            return
        from k8s_watcher_tpu.analytics import ScenarioError

        raw_scenarios = None
        if "scenarios" in params:
            try:
                raw_scenarios = json.loads(params["scenarios"])
            except ValueError:
                self._send_obj(
                    400, {"error": "scenarios= must be a JSON array"}, codec
                )
                return
        elif "drain_cluster" in params:
            raw_scenarios = [
                {"kind": "drain_cluster", "cluster": params["drain_cluster"]}
            ]
        elif "cordon_nodes" in params:
            nodes = [n for n in params["cordon_nodes"].split(",") if n]
            raw_scenarios = [{"kind": "cordon_nodes", "nodes": nodes}]
        try:
            if raw_scenarios is None:
                body = self.analytics.summary()
            else:
                body = self.analytics.evaluate(raw_scenarios)
        except ScenarioError as exc:
            self._send_obj(400, {"error": str(exc)}, codec)
            return
        self._send_obj(200, body, codec)

    def _serve_at(self, params: dict, codec: str = CODEC_JSON) -> None:
        """Time travel: ``GET /serve/fleet?at=N`` reconstructs the fleet
        snapshot as of rv N from the history WAL (snapshot record +
        deltas). 410 past the retention horizon — the same re-snapshot
        recovery contract as a compacted resume token, one layer deeper."""
        if self.history is None:
            self._send_obj(
                400,
                {"error": "time-travel reads need the history plane (history.enabled)"},
                codec,
            )
            return
        try:
            at_rv = int(params["at"])
        except ValueError:
            self._send_obj(400, {"error": "at= must be an integer rv"}, codec)
            return
        if at_rv < 0:
            self._send_obj(400, {"error": "at= must be >= 0"}, codec)
            return
        # LRU over recent reconstructions: a WAL-segment fold is a
        # forensic-grade read, and dashboards poll the same historical rv
        # repeatedly. The key's instance + cache_epoch components make
        # rebase/retention/restart invalidation automatic (stale keys
        # just stop matching and age out of the LRU); the codec component
        # keeps a msgpack read from evicting the JSON reconstruction.
        cache_key = None
        if self.at_cache is not None:
            cache_key = (
                self.view.instance,
                getattr(self.history, "cache_epoch", 0),
                at_rv,
                codec,
            )
            cached = self.at_cache.get(cache_key)
            if cached is not None:
                if self.at_hits is not None:
                    self.at_hits.inc()
                self._body_bytes(200, cached, CODEC_CONTENT_TYPES[codec])
                return
            if self.at_misses is not None:
                self.at_misses.inc()
        status, rv, objects = self.history.reconstruct(at_rv)
        if status == "gone":
            self._send_obj(
                410,
                {"error": "rv is not reconstructible from retained history "
                          "(behind the retention horizon, or inside a rebase/tear hole)",
                 "rv": at_rv, "retention_floor_rv": rv},
                codec,
            )
            return
        if status == "future":
            self._send_obj(
                400,
                {"error": "rv is past the durable history (not yet written, or never minted)",
                 "rv": at_rv, "durable_rv": rv},
                codec,
            )
            return
        reconstruction = {
            "rv": at_rv,
            "view": self.view.instance,
            "historical": True,
            # deterministic order (sorted (kind, key)) — reconstructions
            # are compared byte-wise in the smoke/replay legs
            "objects": [objects[k] for k in sorted(objects)],
        }
        if codec == CODEC_MSGPACK:
            body = frame_body(reconstruction, CODEC_MSGPACK)
        else:
            body = json.dumps(reconstruction).encode()
        if self.at_cache is not None and cache_key is not None:
            self.at_cache.put(cache_key, body)
        self._body_bytes(200, body, CODEC_CONTENT_TYPES[codec])

    def _serve_watch(self, params: dict, codec: str = CODEC_JSON) -> None:
        try:
            rv = int(params["rv"])
        except (KeyError, ValueError):
            self._send_obj(400, {"error": "watch requires an integer rv= (from a snapshot or a prior to_rv/SYNC)"}, codec)
            return
        try:
            timeout = min(float(params.get("timeout", "30") or "30"), MAX_WATCH_SECONDS)
            limit = int(params.get("limit", "0") or "0") or None
        except ValueError:
            self._send_obj(400, {"error": "bad timeout=/limit="}, codec)
            return
        if limit is not None and limit < 0:
            self._send_obj(400, {"error": "limit= must be >= 0 (0 = unpaged)"}, codec)
            return
        # freshness negotiation (``fresh=1``): delta frames additionally
        # carry ``ts: [origin_wall, publish_wall]`` — negotiated like the
        # codec, so peers that don't ask keep the byte-golden frames.
        # trace negotiation (``trace=1``): sampled deltas additionally
        # carry their journey's compact ``trace`` field; trace implies
        # fresh (the federator's serve_wire span reads the ts stamps).
        traced = params.get("trace") in ("1", "true")
        fresh = traced or params.get("fresh") in ("1", "true")
        client_view = params.get("view")
        if client_view and client_view != self.view.instance:
            # token minted by a previous incarnation of the rv space:
            # same recovery as the compaction horizon — re-snapshot
            self._send_obj(
                410,
                {"error": "view instance changed (watcher restarted); re-snapshot",
                 "view": self.view.instance},
                codec,
            )
            return
        sub = self.hub.subscribe(rv=rv)
        if sub is None:
            self._send_obj(
                503,
                {"error": "max_subscribers reached", "max_subscribers": self.hub.max_subscribers},
                codec,
            )
            return
        handed_off = False
        try:
            if params.get("once") in ("1", "true"):
                self._long_poll(sub, min(timeout, MAX_LONG_POLL_SECONDS), limit, codec, fresh, traced)
            elif self.loop is not None:
                handed_off = self._stream_handoff(sub, timeout, limit, codec, fresh, traced)
            else:
                self._stream(sub, timeout, limit, codec, fresh, traced)
        finally:
            if not handed_off:
                self.hub.unsubscribe(sub)

    def _long_poll(self, sub, timeout: float, limit, codec: str = CODEC_JSON, fresh: bool = False, traced: bool = False) -> None:
        result = sub.pull(timeout=timeout, limit=limit)
        if result.status == GONE:
            self._send_obj(
                410,
                {"error": "resume token compacted away; re-snapshot",
                 "rv": result.from_rv, "oldest_rv": self.view.oldest_rv},
                codec,
            )
            return
        if result.status == INVALID:
            # a token AHEAD of the view almost always means the watcher
            # restarted into a fresh rv space and the client didn't send
            # &view= — 410 so the documented resume loop (which only
            # handles 410) recovers by re-snapshotting, instead of
            # wedging on an error it never retries
            self._send_obj(
                410,
                {"error": "rv is ahead of this view (watcher restarted?); re-snapshot",
                 "rv": result.from_rv, "view_rv": self.view.rv, "view": self.view.instance},
                codec,
            )
            return
        self._send_obj(
            200,
            {
                "from_rv": result.from_rv,
                "to_rv": result.to_rv,
                "view": self.view.instance,
                "compacted": result.compacted,
                "items": [d.to_wire(fresh=fresh, trace=traced) for d in result.deltas],
            },
            codec,
        )

    def _pre_stream_410(self, sub, codec: str = CODEC_JSON) -> bool:
        """Pre-stream 410: a dead resume token must fail the REQUEST, not
        arrive as a frame the client has to dig out of a 200 stream.
        Returns True when a 410 was answered (caller stops)."""
        peek_status = self.view.token_status(sub.rv)
        if peek_status == GONE:
            self._send_obj(
                410,
                {"error": "resume token compacted away; re-snapshot",
                 "rv": sub.rv, "oldest_rv": self.view.oldest_rv},
                codec,
            )
            return True
        if peek_status == INVALID:
            # same restart heuristic as the long-poll path: recoverable 410
            self._send_obj(
                410,
                {"error": "rv is ahead of this view (watcher restarted?); re-snapshot",
                 "rv": sub.rv, "view_rv": self.view.rv, "view": self.view.instance},
                codec,
            )
            return True
        return False

    def _stream_handoff(self, sub, timeout: float, limit, codec: str = CODEC_JSON, fresh: bool = False, traced: bool = False) -> bool:
        """The epoll path: handshake/auth/410 checks ran on THIS thread
        (the HTTP front's job); write the response headers, then release
        the socket to the broadcast loop and return the thread to the
        pool. Returns True once the loop owns socket + subscription —
        the caller must then NOT unsubscribe."""
        if self._pre_stream_410(sub, codec):
            return False
        if not self.loop.accepting:
            # a dead loop's inbox is a black hole; serve this stream on
            # the legacy threaded path instead (degraded but correct —
            # /healthz is already reporting the loop unhealthy)
            self._stream(sub, timeout, limit, codec, fresh, traced)
            return False
        self.send_response(200)
        self.send_header("Content-Type", CODEC_CONTENT_TYPES[codec])
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        self.wfile.flush()
        # from here the loop writes the chunked body; the handler thread
        # must neither FIN nor close the fd on return. submit() precedes
        # hand_off so a raise (all workers died since the alive check)
        # leaves the socket owned by the server, which then closes it
        # normally and the finally-unsubscribe frees the slot.
        self.close_connection = True
        try:
            self.loop.submit(
                self.connection, sub,
                timeout=timeout, limit=limit, view_id=self.view.instance,
                codec=codec, fresh=fresh, traced=traced,
            )
        except RuntimeError:
            return False
        self.server.hand_off(self.connection)
        return True

    def _stream(self, sub, timeout: float, limit, codec: str = CODEC_JSON, fresh: bool = False, traced: bool = False) -> None:
        # legacy thread-per-connection streamer (serve.io_threads: 0):
        # kept as the PR-4 reference encoder the golden/equivalence tests
        # compare the broadcast core against
        if self._pre_stream_410(sub, codec):
            return
        self.send_response(200)
        self.send_header("Content-Type", CODEC_CONTENT_TYPES[codec])
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        if codec == CODEC_MSGPACK:
            def write_frames(frames: list) -> None:
                data = b"".join(frame_body(f, CODEC_MSGPACK) for f in frames)
                self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                self.wfile.flush()
        else:
            def write_frames(frames: list) -> None:
                data = "".join(json.dumps(f) + "\n" for f in frames).encode()
                self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
                self.wfile.flush()

        deadline = time.monotonic() + timeout
        last_frame = time.monotonic()
        stream_view = self.view.instance
        try:
            write_frames([{"type": "SYNC", "rv": sub.rv, "view": stream_view}])
            while time.monotonic() < deadline:
                if self.view.instance != stream_view:
                    # mid-stream view swap (relay re-adopt): terminate
                    # with the GONE recovery instead of grafting rv lines
                    write_frames([{"type": "GONE", "rv": sub.rv, "view": self.view.instance}])
                    break
                result = sub.pull(
                    timeout=min(0.5, max(0.0, deadline - time.monotonic())),
                    limit=limit,
                )
                if result.status == GONE:
                    # fell behind the horizon while blocked on a slow
                    # client: in-band terminal frame, then close
                    write_frames([{"type": "GONE", "rv": result.from_rv, "oldest_rv": self.view.oldest_rv}])
                    break
                if result.deltas:
                    frames = []
                    if result.compacted:
                        frames.append({
                            "type": "COMPACTED",
                            "from_rv": result.from_rv,
                            "to_rv": result.to_rv,
                        })
                    frames.extend(d.to_wire(fresh=fresh, trace=traced) for d in result.deltas)
                    write_frames(frames)
                    last_frame = time.monotonic()
                elif result.compacted:
                    # sparse relay journal: the cursor advanced over an
                    # upstream-sanctioned hole with nothing to send —
                    # COMPACTED sanctions the range, SYNC moves the
                    # resume token past it so the next live delta reads
                    # contiguous instead of surfacing as a false gap
                    write_frames([
                        {"type": "COMPACTED", "from_rv": result.from_rv,
                         "to_rv": result.to_rv},
                        {"type": "SYNC", "rv": sub.rv, "view": self.view.instance},
                    ])
                    last_frame = time.monotonic()
                elif time.monotonic() - last_frame >= SYNC_INTERVAL_SECONDS:
                    write_frames([{"type": "SYNC", "rv": sub.rv, "view": self.view.instance}])
                    last_frame = time.monotonic()
            else:
                # clean window end: final SYNC carries the resume token
                write_frames([{"type": "SYNC", "rv": sub.rv, "view": self.view.instance}])
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, TimeoutError):
            pass  # subscriber went away (or stalled past the socket
            # timeout); unsubscribe happens in the caller


class ServeServer:
    """Owns the serving plane's HTTP thread (kube-style: one resource,
    snapshot + watch on the same route)."""

    def __init__(
        self,
        view: FleetView,
        hub: SubscriptionHub,
        *,
        host: str = "0.0.0.0",
        port: int = 0,
        auth_token: Optional[str] = None,
        plane=None,
        history=None,
        analytics=None,
        trace=None,  # trace.TraceRing -> GET /debug/trace (lazy stitch)
        io_threads: int = 1,
        sub_buffer_bytes: int = 1 << 20,
        metrics=None,
    ):
        # the broadcast event loop carries every ?watch=1 stream once the
        # HTTP front hands the socket off; io_threads=0 keeps the legacy
        # thread-per-connection streamer (the equivalence tests' reference)
        self.loop: Optional[BroadcastLoop] = (
            BroadcastLoop(
                view, hub,
                threads=io_threads,
                sub_buffer_bytes=sub_buffer_bytes,
                metrics=metrics,
            )
            if io_threads > 0
            else None
        )
        handler = type(
            "BoundServeHandler",
            (_ServeHandler,),
            {"view": view, "hub": hub, "auth_token": auth_token, "plane": plane,
             "history": history, "analytics": analytics, "trace": trace,
             "loop": self.loop,
             "at_cache": _AtCache() if history is not None else None,
             "at_hits": metrics.counter("serve_at_cache_hits")
             if metrics is not None and history is not None else None,
             "at_misses": metrics.counter("serve_at_cache_misses")
             if metrics is not None and history is not None else None},
        )
        self._server = _HandoffHTTPServer((host, port), handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def start(self) -> "ServeServer":
        if self.loop is not None:
            self.loop.start()
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="serve-plane", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=2.0)
        if self.loop is not None:
            self.loop.stop()


class ServePlane:
    """Bundle the app wires: view + hub + HTTP server + healthz verdict.

    Built when ``serve.enabled``; the view exists from construction (the
    pipeline publishes into it immediately) while the HTTP server starts
    with the app's other servers in ``run()``.
    """

    def __init__(self, config, *, metrics=None, auth_token: Optional[str] = None, history=None):
        self.config = config
        self.metrics = metrics
        self.view = FleetView(
            compact_horizon=config.compact_horizon,
            metrics=metrics,
            # serve.columnar: "auto"/"on" = the columnar core, "off" =
            # the legacy dict core (byte-identical wire either way)
            columnar=getattr(config, "columnar", "auto") != "off",
        )
        # durable history plane (history.HistoryStore, already recovered):
        # restore the previous incarnation's rv line + instance + journal
        # tail into the fresh view, then open the WAL writer on this
        # (possibly inherited) instance and start persisting new deltas
        self.history = history
        if history is not None:
            recovered = history.recovered
            if recovered is not None and recovered.instance:
                from k8s_watcher_tpu.history.recovery import journal_deltas

                if recovered.clean:
                    self.view.restore(
                        instance=recovered.instance,
                        rv=recovered.rv,
                        objects=recovered.objects,
                        journal=journal_deltas(recovered.journal),
                    )
                else:
                    # UNCLEAN end (no final snapshot / torn tail): deltas
                    # acked to subscribers beyond the durable rv may be
                    # lost, and new churn would re-mint those rvs with
                    # different contents — inheriting the instance would
                    # let pre-crash tokens graft two divergent rv lines
                    # into one token space. Keep the durable state + rv
                    # line (history/?at= stay coherent) under a FRESH
                    # instance: pre-crash tokens 410 into a re-snapshot,
                    # the pre-PR contract, now only for unclean crashes.
                    logger.warning(
                        "History WAL ends uncleanly (crash?): resuming rv line at %d "
                        "under a fresh view instance — pre-crash resume tokens will "
                        "re-snapshot (410)", recovered.rv,
                    )
                    self.view.restore(
                        instance=self.view.instance,
                        rv=recovered.rv,
                        objects=recovered.objects,
                        journal=[],
                    )
            history.open(self.view.instance)
            self.view.attach_history(history)
        self.hub = SubscriptionHub(
            self.view,
            max_subscribers=config.max_subscribers,
            queue_depth=config.queue_depth,
            metrics=metrics,
        )
        self.server: Optional[ServeServer] = None
        self._auth_token = auth_token
        # analytics.AnalyticsPlane, attached by the app AFTER the view
        # exists (and after federation, so the columnar twin covers the
        # merged global fleet) — routes /serve/analytics when set
        self.analytics = None
        # trace.TraceRing, attached by the app when tracing is on —
        # routes GET /debug/trace on the serve port (the lazy-stitch
        # surface a downstream federator reads this process's spans from)
        self.trace_ring = None
        # relay.RelayPlane, attached by the app when relay.enabled: the
        # view is fed by the upstream mirror instead of a local pipeline,
        # and health() folds the relay verdict (downstream relays read
        # their depth off the /serve/healthz body here)
        self.relay = None

    def attach_analytics(self, analytics) -> None:
        """Wire the analytics plane; call before ``start()`` so the HTTP
        handler binds the route."""
        self.analytics = analytics

    def attach_trace(self, ring) -> None:
        """Wire the tracing ring; call before ``start()`` so the HTTP
        handler binds /debug/trace on the serve port."""
        self.trace_ring = ring

    def attach_relay(self, relay) -> None:
        """Wire the relay plane: its verdict (and its ``depth`` — the
        thing a downstream relay stamps its own off) folds into the
        /serve/healthz body."""
        self.relay = relay

    def wrap_sink(self, sink):
        """Tap a notification sink: every Notification folds into the view
        (slices/probes; pods no-op — they ride ``publish_batch``) before
        reaching the real sink."""
        observe = self.view.observe_notification

        def serving_sink(notification):
            observe(notification)
            sink(notification)

        return serving_sink

    def start(self) -> "ServePlane":
        self.server = ServeServer(
            self.view,
            self.hub,
            port=self.config.port,
            auth_token=self._auth_token,
            plane=self,
            history=self.history,
            analytics=self.analytics,
            trace=self.trace_ring,
            io_threads=getattr(self.config, "io_threads", 1),
            sub_buffer_bytes=getattr(self.config, "sub_buffer_bytes", 1 << 20),
            metrics=self.metrics,
        ).start()
        logger.info(
            "Serving plane on :%d (/serve/fleet snapshot+watch%s, max_subscribers=%d, "
            "queue_depth=%d, compact_horizon=%d, io_threads=%d)",
            self.server.port,
            ", /serve/analytics" if self.analytics is not None else "",
            self.config.max_subscribers,
            self.config.queue_depth, self.config.compact_horizon,
            getattr(self.config, "io_threads", 1),
        )
        return self

    def stop(self) -> None:
        if self.server is not None:
            self.server.stop()
            self.server = None

    @property
    def port(self) -> int:
        return self.server.port if self.server is not None else 0

    def health(self) -> dict:
        """Serving-plane liveness, folded into /healthz: the plane is
        unhealthy once its HTTP thread has died (subscribers silently get
        nothing — as blind-making as a dead egress worker)."""
        server = self.server  # racing stop(); read once
        body = {
            "healthy": server is None or server.alive,
            "started": server is not None,
            "subscribers": self.hub.active_count,
            "max_subscribers": self.hub.max_subscribers,
            "view_rv": self.view.rv,
            "oldest_rv": self.view.oldest_rv,
            "objects": self.view.object_count(),
        }
        if server is not None and server.loop is not None:
            # a dead broadcast loop starves every handed-off stream while
            # the HTTP front keeps accepting — fold it like the thread
            loop_alive = server.loop.alive
            body["io_loop"] = {
                "healthy": loop_alive,
                "threads": server.loop.threads,
                "streams": server.loop.client_count,
            }
            if not loop_alive:
                body["healthy"] = False
        if self.history is not None:
            # a dead WAL writer silently stops persisting deltas — as
            # blind-making for the restart story as a dead serve thread
            # is for subscribers; only fold it while the plane runs (a
            # closed writer after stop() is lifecycle, not a fault)
            history_health = self.history.health()
            body["history"] = history_health
            if server is not None and not history_health["healthy"]:
                body["healthy"] = False
        if self.relay is not None:
            # the relay fold: depth (downstream relays stamp off it) +
            # upstream connectivity. Only a DEAD subscriber thread flips
            # the top-level verdict (local fault, restart fixes it); a
            # dark upstream degrades this section only — restarting the
            # relay cannot revive its upstream (the federation posture)
            relay_health = self.relay.health()
            body["relay"] = relay_health
            if relay_health.get("started") and not relay_health.get("thread_alive", True):
                body["healthy"] = False
        return body
