"""Columnar fleet-state store: the million-object core behind FleetView.

PR 12 proved the columnar-int32-table + interner method at the analytics
edge (``analytics/encode.py``, ~9.4x batched speedup) — but the tables
there are a *cache* rebuilt from the dict-of-dicts view. This module
promotes the representation to the CORE: ``ColumnarStore`` is the
fleet-state storage itself, and every O(fleet) reader — snapshot bodies,
health phase scans, the analytics kernels, federation reseeds — reads
the same arrays instead of re-walking a million Python dicts.

Layout
------

Pods (the million-row kind) live in append-only columnar rows:

- ``_parts[row]``: the pod's serialized JSON fragment, stored WITH its
  leading ``b", "`` element separator so the ``GET /serve/fleet`` body
  is a header + one ``b"".join`` over the parts — byte-identical to
  ``json.dumps`` of the dict core's body (default separators), built in
  O(rows) C-speed joins instead of O(fleet) re-serialization.
- int columns (``phase``/``ready``/``node``/``cluster``) in
  capacity-doubling numpy arrays, codes drawn from the same fixed
  POD_PHASES vocabulary and stable ``Interner`` dictionaries the
  analytics encoder uses — health/analytics/SLO readers get these
  arrays zero-copy (materialized at most once per dirty generation).
- ``_rows``: key -> row. Deletes TOMBSTONE the row (empty part,
  phase -1) instead of swap-removing it, because row order is the
  body's object order and must reproduce the dict core's insertion
  order byte-for-byte; tombstones are reclaimed by an amortized
  order-preserving compaction once they outnumber half the table.

Everything else — slice aggregates, probe verdicts, and the rare pod
object that does not round-trip through JSON — stays object-shaped in a
side table, each entry pinned to an ``anchor`` (the pod row index it
was inserted before) so body assembly interleaves kinds in exact dict
insertion order.

Write path: ``upsert()`` is LAZY — the object lands in a pending map
(one dict write, the same cost the dict core pays) and serialization is
deferred to the next flush, which every reader triggers first. A key
overwritten many times between reads is serialized once; the dumps a
changed key pays at read time is the same dumps the snapshot body
needed anyway. Identical-upsert dedup is exact dict-core parity:
pending entries compare dict==dict; flushed rows compare fragment
bytes, with a parse-and-compare fallback when lengths match so a
key-order-shuffled-but-equal object still refuses to burn an rv.

Object fidelity caveat (documented in ARCHITECTURE.md): flushed pods
are canonicalized through JSON — an object holding tuples or non-string
dict keys would not survive the round trip, so any pod object that
fails or lies under ``json.dumps`` is kept object-shaped in the side
table instead (correctness over the fast path). The real pipeline only
ever stores JSON-decoded objects, so production rows all columnize.

Concurrency contract: the OWNER (FleetView) serializes every call under
its publish lock. Readers take a cheap structural snapshot
(``snapshot_parts`` — a flush plus list copies) under the lock and do
O(fleet) assembly/reconstruction OUTSIDE it; parts bytes are immutable
and side objects are replaced-never-mutated, so the snapshot stays
consistent while publishes continue.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterator, List, NamedTuple, Optional, Tuple

import numpy as np

from k8s_watcher_tpu.analytics.encode import (
    LOCAL_CLUSTER,
    POD_PHASES,
    POD_PHASE_CODE,
    FleetColumns,
    Interner,
    build_slice_tables,
)

#: the one pod kind the columnar table owns; every other kind (and the
#: rare non-JSON-faithful pod) lives in the anchored side table
POD_KIND = "pod"

#: element separator baked into every stored fragment (json.dumps
#: default separators — the PR-4 golden byte contract)
SEP = b", "

#: CPython bytes-object overhead, for the resident-bytes estimate
_BYTES_OVERHEAD = 33
#: rough per-entry dict/str bookkeeping (hash table slot + str header)
_KEY_OVERHEAD = 130

_dumps = json.dumps
_loads = json.loads


def _fragment(obj: Dict[str, Any]) -> bytes:
    """``obj``'s body fragment (no separator) — byte-identical to its
    slice of ``json.dumps`` over the whole body."""
    return _dumps(obj).encode()


def _side_fragment(obj: Dict[str, Any]) -> Optional[bytes]:
    """``SEP + fragment`` for a side-table entry, or ``None`` when the
    object does not serialize — taking a structural snapshot must never
    raise (the side table is where non-JSON-faithful objects are pinned
    object-shaped); only the JSON body assembly may, at build time."""
    try:
        return SEP + _fragment(obj)
    except (TypeError, ValueError):
        return None


class BodySnapshot(NamedTuple):
    """One consistent structural snapshot (taken under the publish
    lock, consumed outside it): the pod parts in row order (tombstones
    are empty), the side entries as ``(anchor, fragment, kind, key,
    obj)`` sorted into body order, and the live object count."""

    parts: List[bytes]
    sides: List[Tuple[int, bytes, str, str, Dict[str, Any]]]
    count: int
    keys: Optional[List[Optional[str]]]  # row -> pod key (when requested)


class PodHandle(NamedTuple):
    """The health plane's zero-copy read handle: parallel per-pod
    sequences (alive rows only, side-table pods appended) plus the live
    slice objects — no per-kind dict tables, shared per generation.
    Phases are normalized to the fixed POD_PHASES vocabulary."""

    keys: List[str]
    phases: List[str]
    nodes: List[Optional[str]]
    slices: List[Dict[str, Any]]


class ColumnarStore:
    """Append/tombstone columnar fleet store with dict-of-dicts
    semantics (insertion order, identical-upsert dedup) — see module
    docstring. NOT thread-safe; the owning FleetView serializes calls
    under its publish lock."""

    def __init__(self) -> None:
        self.nodes = Interner()
        self.clusters = Interner()
        self.clusters.code(LOCAL_CLUSTER)  # code 0 = the local cluster
        # flushed pod rows
        self._rows: Dict[str, int] = {}  # live keys only
        self._parts: List[bytes] = []  # b", "+fragment; b"" = tombstone
        cap = 1024
        self._phase = np.full(cap, -1, dtype=np.int8)
        self._ready = np.zeros(cap, dtype=np.int8)
        self._node = np.zeros(cap, dtype=np.int32)
        self._cluster = np.zeros(cap, dtype=np.int32)
        self._arr_len = 0  # arrays are valid for rows [0, _arr_len)
        self._dead = 0  # tombstoned rows awaiting compaction
        # lazy write buffer: key -> obj (upserts only; deletes are eager)
        self._pending: Dict[str, Dict[str, Any]] = {}
        self._pending_new = 0  # pending keys with no flushed row yet
        # anchored side table: (kind, key) -> (anchor, obj). anchor =
        # the pod row index this entry sorts before (dict insertion
        # order across kinds); non-decreasing in insertion order.
        self._side: Dict[Tuple[str, str], Tuple[int, Dict[str, Any]]] = {}
        # generation: bumps on every logical mutation (not on flush);
        # keys the materialization caches below
        self._gen = 0
        self._cols: Optional[FleetColumns] = None
        self._cols_gen = -1
        self._handle: Optional[PodHandle] = None
        self._handle_gen = -1
        # incrementally-maintained resident estimate (view_resident_bytes)
        self._parts_bytes = 0  # sum of len(part) over live+dead rows
        self._keys_bytes = 0  # key strings + per-entry bookkeeping

    # -- write path (owner-locked) ----------------------------------------

    def upsert(self, kind: str, key: str, obj: Dict[str, Any]) -> bool:
        """Insert/replace one object. Returns False for the identical
        no-op (dict-core dedup parity: no rv burn)."""
        if kind != POD_KIND:
            return self._side_upsert(kind, key, obj)
        sk = (POD_KIND, key)
        if sk in self._side:  # non-JSON-faithful pod pinned object-shaped
            anchor, prev = self._side[sk]
            if prev == obj:
                return False
            self._side[sk] = (anchor, obj)
            self._gen += 1
            return True
        pend = self._pending.get(key)
        if pend is not None:
            if pend == obj:
                return False
            self._pending[key] = obj
            self._gen += 1
            return True
        row = self._rows.get(key)
        if row is None:
            self._pending[key] = obj
            self._pending_new += 1
            self._gen += 1
            return True
        # flushed row: exact dedup against the stored fragment
        try:
            frag = _fragment(obj)
        except (TypeError, ValueError):
            # does not serialize: it cannot equal the (serialized) row.
            # Tombstone the row and pin the object in the side table at
            # the SAME position (anchor = the row index) — overwrite
            # must not move the object to the end.
            self._tombstone(key, row)
            self._side[sk] = (row, obj)
            self._gen += 1
            return True
        old = self._parts[row]
        if len(old) - len(SEP) == len(frag):
            if old[len(SEP):] == frag:
                return False
            # same length, different bytes: a reordered-but-equal dict
            # still must not mint a delta (dict-core parity)
            if _loads(old[len(SEP):]) == obj:
                return False
        self._set_row(row, SEP + frag, obj)
        self._gen += 1
        return True

    def delete(self, kind: str, key: str) -> bool:
        """Remove one object. Returns False when absent (dict-core
        parity: no rv burn for deleting nothing)."""
        if kind != POD_KIND:
            if self._side.pop((kind, key), None) is None:
                return False
            self._gen += 1
            return True
        if self._side.pop((POD_KIND, key), None) is not None:
            self._gen += 1
            return True
        if key in self._pending and key not in self._rows:
            # a never-flushed insert. When no side anchor counts a
            # pending row (anchors are minted as len(parts)+pending_new,
            # so only anchors PAST len(parts) reference pending
            # positions), this is a plain dict pop — dict-core
            # semantics, zero flush. That keeps a churning pods-only
            # stream (the fan-in shape: interleaved upserts/deletes, no
            # reader between batches) entirely on the pending buffer's
            # dict-equality dedup path instead of flushing the working
            # set into rows whose every later update pays a json.dumps.
            if all(anchor <= len(self._parts)
                   for anchor, _obj in self._side.values()):
                self._pending.pop(key)
                self._pending_new -= 1
                self._gen += 1
                return True
            # a side anchor references a pending position: materialize
            # the whole pending set first so row order (and every side
            # anchor counted against it) stays exactly dict insertion
            # order, then tombstone
            self._flush()
        elif key in self._pending:
            self._pending.pop(key)  # discard the pending overwrite
        row = self._rows.get(key)
        if row is None:
            return False
        self._tombstone(key, row)
        self._gen += 1
        if self._dead > 1024 and self._dead * 2 > len(self._parts):
            self._compact()
        return True

    def reseed(self, objects) -> None:
        """Adopt a full ``{(kind, key): obj}`` state (restore()/relay
        adopt). Interners are KEPT — codes stay stable across reseeds,
        the same contract the analytics encoder's ``reset`` keeps —
        and nothing is serialized here (a restart must not pay O(fleet)
        dumps before serving; the first body build flushes lazily)."""
        self._rows.clear()
        self._parts.clear()
        self._phase[: self._arr_len] = -1
        self._arr_len = 0
        self._dead = 0
        self._pending.clear()
        self._pending_new = 0
        self._side.clear()
        self._parts_bytes = 0
        self._keys_bytes = 0
        for (kind, key), obj in objects.items():
            if kind == POD_KIND:
                self._pending[key] = obj
                self._pending_new += 1
            else:
                self._side[(kind, key)] = (self._anchor(), obj)
        self._gen += 1

    def _side_upsert(self, kind: str, key: str, obj: Dict[str, Any]) -> bool:
        sk = (kind, key)
        prev = self._side.get(sk)
        if prev is not None:
            if prev[1] == obj:
                return False
            self._side[sk] = (prev[0], obj)  # replace keeps its position
        else:
            self._side[sk] = (self._anchor(), obj)
        self._gen += 1
        return True

    def _anchor(self) -> int:
        """The pod row index the next inserted side entry sorts before:
        every pod inserted so far — flushed rows (dead ones still hold
        their order slot) plus pending first-inserts."""
        return len(self._parts) + self._pending_new

    def _tombstone(self, key: str, row: int) -> None:
        self._rows.pop(key, None)
        old = self._parts[row]
        self._parts[row] = b""
        if row < self._arr_len:
            self._phase[row] = -1
        self._parts_bytes -= len(old)
        self._keys_bytes -= _KEY_OVERHEAD + len(key)
        self._dead += 1

    def _set_row(self, row: int, part: bytes, obj: Dict[str, Any]) -> None:
        self._parts_bytes += len(part) - len(self._parts[row])
        self._parts[row] = part
        self._phase[row] = POD_PHASE_CODE.get(obj.get("phase") or "Unknown", 0)
        self._ready[row] = 1 if obj.get("ready") else 0
        node = obj.get("node")
        self._node[row] = self.nodes.code(str(node)) if node else -1
        self._cluster[row] = self.clusters.code(str(obj.get("cluster") or LOCAL_CLUSTER))

    # -- flush (pending -> columns; every reader's first step) -------------

    def _grow(self, need: int) -> None:
        cap = len(self._phase)
        if need <= cap:
            return
        while cap < need:
            cap *= 2
        for name in ("_phase", "_ready", "_node", "_cluster"):
            old = getattr(self, name)
            fresh = np.full(cap, -1, dtype=old.dtype) if name == "_phase" else np.zeros(cap, dtype=old.dtype)
            fresh[: self._arr_len] = old[: self._arr_len]
            setattr(self, name, fresh)

    def _flush(self) -> None:
        """Serialize the pending buffer into rows. Amortized: O(keys
        changed since the last reader), each dumps paid at most once per
        changed key per read cycle — the same dumps the snapshot body
        was going to spend."""
        if not self._pending:
            return
        pending, self._pending = self._pending, {}
        self._pending_new = 0
        new_phase: List[int] = []
        new_ready: List[int] = []
        new_node: List[int] = []
        new_cluster: List[int] = []
        nodes_code = self.nodes.code
        clusters_code = self.clusters.code
        for key, obj in pending.items():
            row = self._rows.get(key)
            try:
                part = SEP + _fragment(obj)
            except (TypeError, ValueError):
                # non-JSON-faithful: pin object-shaped at its position
                if row is not None:
                    self._tombstone(key, row)
                    self._side[(POD_KIND, key)] = (row, obj)
                else:
                    self._side[(POD_KIND, key)] = (len(self._parts), obj)
                continue
            if row is None:
                self._rows[key] = len(self._parts)
                self._parts.append(part)
                self._parts_bytes += len(part)
                self._keys_bytes += _KEY_OVERHEAD + len(key)
                new_phase.append(POD_PHASE_CODE.get(obj.get("phase") or "Unknown", 0))
                new_ready.append(1 if obj.get("ready") else 0)
                node = obj.get("node")
                new_node.append(nodes_code(str(node)) if node else -1)
                new_cluster.append(clusters_code(str(obj.get("cluster") or LOCAL_CLUSTER)))
            else:
                self._set_row(row, part, obj)
        if new_phase:
            n = self._arr_len
            m = len(new_phase)
            self._grow(n + m)
            self._phase[n : n + m] = new_phase
            self._ready[n : n + m] = new_ready
            self._node[n : n + m] = new_node
            self._cluster[n : n + m] = new_cluster
        self._arr_len = len(self._parts)

    def _compact(self) -> None:
        """Amortized order-preserving tombstone reclaim: rewrite rows
        keeping insertion order, remap the key index and side anchors.
        O(rows), triggered only once tombstones outnumber live rows."""
        self._flush()
        n = len(self._parts)
        mask = self._phase[:n] >= 0
        idx = np.flatnonzero(mask)
        before = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(mask, out=before[1:])
        new_of_old = before[1:] - 1  # new row of each alive old row
        self._parts = [self._parts[i] for i in idx.tolist()]
        m = len(self._parts)
        for name in ("_phase", "_ready", "_node", "_cluster"):
            old = getattr(self, name)
            fresh = np.full(max(1024, m), -1, dtype=old.dtype) if name == "_phase" else np.zeros(max(1024, m), dtype=old.dtype)
            fresh[:m] = old[:n][mask]
            setattr(self, name, fresh)
        self._arr_len = m
        for key, row in self._rows.items():
            self._rows[key] = int(new_of_old[row])
        if self._side:
            self._side = {
                sk: (int(before[min(anchor, n)]), obj)
                for sk, (anchor, obj) in self._side.items()
            }
        self._dead = 0

    # -- structural snapshots (owner-locked; assembly happens outside) ----

    def snapshot_parts(self, *, with_keys: bool = False) -> BodySnapshot:
        """Flush and hand out a consistent body-order snapshot: list
        copies only — parts bytes are immutable and side objects are
        replaced-never-mutated, so the caller assembles/reconstructs
        OUTSIDE the publish lock."""
        self._flush()
        # key on the anchor ALONE: equal anchors (consecutive side
        # inserts with no pod flushed between) must keep side-table
        # insertion order — the dict core's order. The stable sort over
        # the insertion-ordered dict gives exactly that; a full-tuple
        # sort would break ties on fragment BYTES ("slice-10" before
        # "slice-2"). Anchors are non-decreasing in insertion order
        # (parts only shrink in _compact, which remaps monotonically),
        # so anchor-then-insertion IS body order.
        # fragments are computed TOLERANTLY (None when the object does
        # not serialize): the side table is exactly where non-JSON-
        # faithful objects live pinned object-shaped, and the object-
        # shaped readers (iter_snapshot_objects, the msgpack assembly)
        # must keep serving them — dict-core parity, where snapshot()
        # works and only the body json.dumps raises. _body_chunks
        # re-raises at JSON-body-build time.
        sides = sorted(
            ((anchor, _side_fragment(obj), kind, key, obj)
             for (kind, key), (anchor, obj) in self._side.items()),
            key=lambda entry: entry[0],
        ) if self._side else []
        keys: Optional[List[Optional[str]]] = None
        if with_keys:
            keys = [None] * len(self._parts)
            for key, row in self._rows.items():
                keys[row] = key
        return BodySnapshot(
            parts=self._parts.copy(),
            sides=sides,
            count=len(self._rows) + len(self._side),
            keys=keys,
        )

    # -- zero-copy reader handles ------------------------------------------

    def fleet_columns(self) -> FleetColumns:
        """The analytics plane's arrays, materialized at most once per
        dirty generation (the FleetEncoder contract, now served by the
        storage itself): alive pod rows masked out of the columns,
        side-table pods appended, slice/worker tables built from the
        live slice objects through the same shared builder."""
        self._flush()
        if self._cols is not None and self._cols_gen == self._gen:
            return self._cols
        n = self._arr_len
        mask = self._phase[:n] >= 0
        pod_phase = self._phase[:n][mask].astype(np.int32)
        pod_ready = self._ready[:n][mask].astype(np.int32)
        pod_node = self._node[:n][mask].copy()
        pod_cluster = self._cluster[:n][mask].copy()
        slices: Dict[str, Dict[str, Any]] = {}
        extra: List[Tuple[int, int, int, int]] = []
        for (kind, key), (_anchor, obj) in self._side.items():
            if kind == "slice":
                slices[key] = obj
            elif kind == POD_KIND:
                node = obj.get("node")
                extra.append((
                    POD_PHASE_CODE.get(obj.get("phase") or "Unknown", 0),
                    1 if obj.get("ready") else 0,
                    self.nodes.code(str(node)) if node else -1,
                    self.clusters.code(str(obj.get("cluster") or LOCAL_CLUSTER)),
                ))
        if extra:
            ex = np.asarray(extra, dtype=np.int32)
            pod_phase = np.concatenate([pod_phase, ex[:, 0]])
            pod_ready = np.concatenate([pod_ready, ex[:, 1]])
            pod_node = np.concatenate([pod_node, ex[:, 2]])
            pod_cluster = np.concatenate([pod_cluster, ex[:, 3]])
        self._cols = FleetColumns(
            pod_phase=pod_phase,
            pod_ready=pod_ready,
            pod_node=pod_node,
            pod_cluster=pod_cluster,
            **build_slice_tables(slices, self.nodes, self.clusters),
            nodes=self.nodes,
            clusters=self.clusters,
        )
        self._cols_gen = self._gen
        return self._cols

    def pod_handle(self) -> PodHandle:
        """The health plane's per-pod sequences (see PodHandle), cached
        per dirty generation alongside the columns."""
        self._flush()
        if self._handle is not None and self._handle_gen == self._gen:
            return self._handle
        n = self._arr_len
        row_keys: List[Optional[str]] = [None] * n
        for key, row in self._rows.items():
            row_keys[row] = key
        mask = self._phase[:n] >= 0
        idx = np.flatnonzero(mask).tolist()
        phase_codes = self._phase[:n][mask].tolist()
        node_codes = self._node[:n][mask].tolist()
        node_names = self.nodes.names
        keys = [row_keys[i] for i in idx]
        phases = [POD_PHASES[c] for c in phase_codes]
        nodes = [node_names[c] if c >= 0 else None for c in node_codes]
        slices: List[Dict[str, Any]] = []
        for (kind, key), (_anchor, obj) in self._side.items():
            if kind == "slice":
                slices.append(obj)
            elif kind == POD_KIND:
                keys.append(key)
                phases.append(str(obj.get("phase") or "Unknown"))
                node = obj.get("node")
                nodes.append(str(node) if node else None)
        self._handle = PodHandle(keys=keys, phases=phases, nodes=nodes, slices=slices)
        self._handle_gen = self._gen
        return self._handle

    def federated_entries(self) -> List[Tuple[str, str, str]]:
        """``(kind, global_key, cluster_name)`` for every federated
        object — the merge registry's reseed, straight off the cluster
        column (no object reconstruction). Pod cluster membership reads
        the int column; side entries read their object's field."""
        self._flush()
        out: List[Tuple[str, str, str]] = []
        n = self._arr_len
        cluster_col = self._cluster
        names = self.clusters.names
        for key, row in self._rows.items():
            code = int(cluster_col[row]) if row < n else 0
            if code > 0:
                out.append((POD_KIND, key, names[code]))
        for (kind, key), (_anchor, obj) in self._side.items():
            cluster = obj.get("cluster")
            if cluster:
                out.append((kind, key, str(cluster)))
        return out

    def resident_bytes(self) -> int:
        """O(1) resident estimate for the ``view_resident_bytes`` gauge:
        fragment bytes + key bookkeeping + column capacity + a rough
        bill for the unflushed pending buffer and side objects."""
        arrays = (
            self._phase.nbytes + self._ready.nbytes
            + self._node.nbytes + self._cluster.nbytes
        )
        parts_list = len(self._parts) * 8 + (len(self._parts) - self._dead) * _BYTES_OVERHEAD
        pending = len(self._pending) * 800  # unflushed objects, rough
        side = len(self._side) * 900
        return self._parts_bytes + parts_list + self._keys_bytes + arrays + pending + side

    # -- dict-of-dicts compatibility (Mapping over (kind, key)) -----------

    def __len__(self) -> int:
        return len(self._rows) + self._pending_new + len(self._side)

    def __contains__(self, map_key) -> bool:
        kind, key = map_key
        if kind == POD_KIND and (key in self._rows or key in self._pending):
            return True
        return map_key in self._side

    def get(self, map_key, default=None):
        kind, key = map_key
        if kind == POD_KIND:
            pend = self._pending.get(key)
            if pend is not None:
                return pend
            row = self._rows.get(key)
            if row is not None:
                return _loads(self._parts[row][len(SEP):])
        entry = self._side.get(map_key)
        return entry[1] if entry is not None else default

    def __getitem__(self, map_key):
        obj = self.get(map_key)
        if obj is None:
            raise KeyError(map_key)
        return obj

    def __setitem__(self, map_key, obj) -> None:
        self.upsert(map_key[0], map_key[1], obj)

    def pop(self, map_key, default=None):
        """O(1) removal without reconstruction (the relay fold path)."""
        existed = map_key in self
        self.delete(map_key[0], map_key[1])
        return True if existed and default is None else (default if not existed else True)

    def iter_items(self) -> Iterator[Tuple[Tuple[str, str], Dict[str, Any]]]:
        """``((kind, key), obj)`` in dict insertion order — O(fleet)
        reconstruction; prefer the structural snapshot + the module
        helpers on hot paths."""
        snap = self.snapshot_parts(with_keys=True)
        for kind, key, obj in iter_snapshot_objects(snap):
            yield (kind, key), obj

    def items(self):
        return self.iter_items()

    def keys(self):
        for map_key, _obj in self.iter_items():
            yield map_key

    def __iter__(self):
        return self.keys()

    def values(self):
        for _map_key, obj in self.iter_items():
            yield obj


# -- body assembly / reconstruction (outside the publish lock) -------------


def assemble_json_body(rv: int, instance: str, snap: BodySnapshot) -> bytes:
    """The ``GET /serve/fleet`` JSON body from one structural snapshot —
    byte-identical to ``json.dumps({"rv": rv, "view": instance,
    "objects": [...]})`` over the dict core's object walk (PR-4 golden
    separators), assembled as one join over already-serialized parts."""
    header = ('{"rv": %d, "view": %s, "objects": [' % (rv, _dumps(instance))).encode()
    chunks = _body_chunks(snap)
    # ONE join, one scan: the first non-empty chunk sheds its leading
    # separator up front (tombstones are empty and join away), so the
    # body never pays the strip-and-reconcat double copy of the naive
    # header + joined[2:] + footer shape — at 1M pods those were two
    # extra full-body memcpys per rebuild
    out = [header]
    it = iter(chunks)
    for chunk in it:
        if chunk:
            out.append(chunk[len(SEP):])
            break
    out.extend(it)
    out.append(b"]}")
    return b"".join(out)


def _body_chunks(snap: BodySnapshot) -> List[bytes]:
    """Parts and side fragments interleaved into body order (each chunk
    keeps its leading separator; tombstones are empty and join away)."""
    parts = snap.parts
    if not snap.sides:
        return parts
    chunks: List[bytes] = []
    prev = 0
    for anchor, frag, _kind, _key, obj in snap.sides:
        cut = min(anchor, len(parts))
        if cut > prev:
            chunks.extend(parts[prev:cut])
            prev = cut
        # a None fragment is a non-serializable side object: raise the
        # dict core's exact error here, at JSON-body-build time
        chunks.append(frag if frag is not None else SEP + _fragment(obj))
    chunks.extend(parts[prev:])
    return chunks


def assemble_msgpack_body(rv: int, instance: str, snap: BodySnapshot, packb) -> bytes:
    """The msgpack snapshot body, composed incrementally: the map/array
    headers are written by hand and each element is packed on its own —
    byte-identical to ``packb({"rv": ..., "view": ..., "objects":
    [...]})`` because msgpack is compositional. Pod elements are parsed
    back from their JSON fragments (exact round-trip; anything that
    would not round-trip lives object-shaped in the side table), so this
    path costs O(fleet) like the dict core's — the incremental win is
    JSON's, the wire default."""
    count = snap.count
    if count < 16:
        array_header = bytes([0x90 | count])
    elif count < 1 << 16:
        array_header = b"\xdc" + count.to_bytes(2, "big")
    else:
        array_header = b"\xdd" + count.to_bytes(4, "big")
    out = [
        b"\x83",
        packb("rv"), packb(rv),
        packb("view"), packb(instance),
        packb("objects"), array_header,
    ]
    sep = len(SEP)
    sides = snap.sides
    parts = snap.parts
    prev = 0
    for anchor, _frag, _kind, _key, obj in sides:
        cut = min(anchor, len(parts))
        for i in range(prev, cut):
            part = parts[i]
            if part:
                out.append(packb(_loads(part[sep:])))
        prev = cut
        out.append(packb(obj))
    for i in range(prev, len(parts)):
        part = parts[i]
        if part:
            out.append(packb(_loads(part[sep:])))
    return b"".join(out)


def iter_snapshot_objects(snap: BodySnapshot) -> Iterator[Tuple[str, str, Dict[str, Any]]]:
    """``(kind, key, obj)`` in body order, reconstructed outside the
    lock. Pod dicts parse back from their fragments (fresh dicts, equal
    to what the dict core stored); side objects are the live references."""
    sep = len(SEP)
    parts = snap.parts
    keys = snap.keys
    prev = 0
    for anchor, _frag, kind, key, obj in snap.sides:
        cut = min(anchor, len(parts))
        for i in range(prev, cut):
            part = parts[i]
            if part:
                yield POD_KIND, (keys[i] if keys else ""), _loads(part[sep:])
        prev = cut
        yield kind, key, obj
    for i in range(prev, len(parts)):
        part = parts[i]
        if part:
            yield POD_KIND, (keys[i] if keys else ""), _loads(part[sep:])
