"""Epoll broadcast core: the serving plane's streaming data plane.

PR 4's ``?watch=1`` streams each held one OS thread blocked in
``Condition.wait`` + blocking socket writes — N subscribers cost N
threads, every publish woke all N, and each thread re-encoded every
frame. This module replaces that with a ``selectors``-based event loop
(a small fixed pool of loop threads, ``serve.io_threads``): the HTTP
front still does the handshake — request parse, bearer auth, pre-stream
410 checks, response headers — on its per-connection thread, then hands
the socket off non-blocking to a loop. From there:

- **One wakeup per publish.** The view calls each loop's ``wake`` once
  per applied publish (a self-pipe byte, coalesced while a wake is
  already pending). The loop walks only subscribers with pending deltas
  (``sub.rv < view.rv``) — idle subscribers cost nothing, and scheduling
  is O(active sockets), not O(subscribers).
- **Encode-once delivery.** A pull returns the publish-time frame bytes
  (``FleetView.read_frames_since``); delivering a delta to a subscriber
  is appending the SHARED bytes object to its outbound buffer. Only the
  small per-connection SYNC/COMPACTED/GONE control frames are
  synthesized here.
- **Backpressure, not blocked threads.** A slow client's unsent bytes
  sit in its bounded outbound buffer (``serve.sub_buffer_bytes``);
  partial writes resume from the kernel-accepted offset when the socket
  turns writable again. While the buffer is over budget the loop simply
  stops pulling for that subscriber — its cursor lags, and the next
  pull rides the view's existing read-time latest-wins compaction
  (or 410s past the horizon). No thread ever blocks on a dead peer.
- **Liveness.** SYNC heartbeats keep idle streams' resume tokens fresh;
  a peer close (readable EOF) mid-frame tears the client down and frees
  its subscriber slot immediately; watch-window deadlines close streams
  cleanly with a final SYNC + terminal chunk.

``serve_loop_lag_seconds`` gauges wake-to-service latency;
``serve_fanout_bytes`` counts bytes queued to subscribers.
"""

from __future__ import annotations

import logging
import os
import selectors
import socket
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Union

from k8s_watcher_tpu.serve.view import (
    CODEC_JSON,
    GONE,
    OK,
    FleetView,
    Subscription,
    SubscriptionHub,
    chunk_frame,
)

logger = logging.getLogger(__name__)

#: chunked-transfer end-of-body marker — the clean close of a stream
TERMINAL_CHUNK = b"0\r\n\r\n"
#: idle heartbeat cadence (mirrors the threaded front's SYNC contract)
SYNC_INTERVAL_SECONDS = 2.0
#: a closing client gets this long to drain its final bytes before the
#: socket is torn down anyway (a dead peer must not pin a slot forever)
DRAIN_GRACE_SECONDS = 10.0
#: selector timeout ceiling: timers (SYNC, deadlines) are checked at
#: least this often even with no IO and no publishes
MAX_SELECT_SECONDS = 0.5
#: timer-sweep throttle: the O(clients) SYNC/deadline walk runs at most
#: this often (timer contracts are seconds-scale), so high-rate publish
#: iterations don't pay it each
TIMER_SWEEP_SECONDS = 0.1


class _StreamClient:
    """One handed-off watch stream: socket + cursor + outbound buffer."""

    __slots__ = (
        "sock", "fd", "sub", "limit", "deadline", "hard_deadline",
        "last_frame", "buf", "buf_bytes", "closing", "view_id",
        "want_write", "codec", "fresh", "traced",
    )

    def __init__(
        self,
        sock: socket.socket,
        sub: Subscription,
        *,
        deadline: float,
        limit: Optional[int],
        view_id: str,
        codec: str = CODEC_JSON,
        fresh: bool = False,
        traced: bool = False,
    ):
        self.sock = sock
        self.fd = sock.fileno()
        self.sub = sub
        self.limit = limit
        self.deadline = deadline
        self.hard_deadline = deadline + DRAIN_GRACE_SECONDS
        self.last_frame = time.monotonic()
        # outbound buffer: bytes objects are SHARED frame bytes (never
        # mutated); a partial write replaces the head with a memoryview
        # suffix — zero-copy resume from the kernel-accepted offset
        self.buf: Deque[Union[bytes, memoryview]] = deque()
        self.buf_bytes = 0
        self.closing = False  # terminal bytes queued; close once drained
        self.view_id = view_id
        self.want_write = False
        # negotiated wire codec: frames pulled (and control frames
        # synthesized) in this codec; the per-codec frame arrays are
        # shared across every subscriber on the same codec
        self.codec = codec
        # negotiated freshness stamps (?fresh=1): pulls select the
        # stamped frame variant; control frames never carry stamps
        self.fresh = fresh
        # negotiated trace forwarding (?trace=1): pulls select the
        # trace-forwarding frame variant (always stamped)
        self.traced = traced


class _LoopWorker(threading.Thread):
    """One selector loop: owns a disjoint subset of handed-off sockets."""

    def __init__(self, loop: "BroadcastLoop", index: int):
        super().__init__(name=f"serve-io-{index}", daemon=True)
        self.loop = loop
        self.selector = selectors.DefaultSelector()
        self._rpipe, self._wpipe = os.pipe()
        os.set_blocking(self._rpipe, False)
        os.set_blocking(self._wpipe, False)
        self.selector.register(self._rpipe, selectors.EVENT_READ, None)
        self._inbox: Deque[_StreamClient] = deque()
        self._inbox_lock = threading.Lock()
        self._clients: Dict[int, _StreamClient] = {}
        self._running = True
        self._closed = False  # pipes torn down; wake() must not write
        # wake coalescing: publishes while a wake is already pending
        # don't write another pipe byte (GIL-atomic flag flips)
        self._wake_pending = False
        self._notify_t = 0.0
        # pump scheduling state: the last view rv a full walk serviced,
        # plus fds needing a pull regardless (fresh admissions, buffers
        # that just drained below budget)
        self._pumped_rv = -1
        self._needs_pull: set = set()
        # timer scheduling: O(1) select timeouts off a cached next-due
        # stamp maintained by the (throttled) timer sweep
        self._next_due = float("inf")
        self._last_timer_sweep = 0.0

    # -- cross-thread surface (publish hook / HTTP handler threads) -------

    def wake(self, stamp: float = 0.0) -> None:
        if self._closed:
            return  # torn down: the write fd may have been REUSED by
            # another open — writing would corrupt whatever owns it now
        if stamp and not self._notify_t:
            self._notify_t = stamp
        if not self._wake_pending:
            self._wake_pending = True
            try:
                os.write(self._wpipe, b"x")
            except (BlockingIOError, OSError):
                pass  # pipe full = a wake is already queued

    def submit(self, client: _StreamClient) -> None:
        with self._inbox_lock:
            self._inbox.append(client)
        self.wake()

    @property
    def client_count(self) -> int:
        return len(self._clients)

    def stop(self) -> None:
        self._running = False
        self.wake()

    # -- loop internals (single-threaded from here down) -------------------

    def run(self) -> None:
        try:
            self._run_loop()
        except Exception:  # noqa: BLE001 — a dead loop must be loud
            logger.exception("Broadcast loop %s died", self.name)
        finally:
            self._teardown()

    def _run_loop(self) -> None:
        while self._running:
            events = self.selector.select(self._select_timeout())
            now = time.monotonic()
            woke = False
            for key, mask in events:
                if key.data is None:
                    woke = True
                    continue
                client = key.data
                if mask & selectors.EVENT_READ:
                    self._on_readable(client)
                if mask & selectors.EVENT_WRITE and client.fd in self._clients:
                    self._flush(client)
            if woke:
                # drain FIRST, clear the flag after: a wake landing
                # between the two either finds the flag still True (its
                # publish is serviced by THIS iteration's pump, which
                # reads view.rv below) or writes a fresh byte select
                # returns on. The reverse order could eat a byte written
                # under a True flag and strand the flag True forever —
                # silently degrading every future wake to the 0.5 s
                # select-timeout poll.
                try:
                    while os.read(self._rpipe, 4096):
                        pass
                except (BlockingIOError, OSError):
                    pass
                self._wake_pending = False
                stamp, self._notify_t = self._notify_t, 0.0
                if stamp and self.loop.lag_gauge is not None:
                    self.loop.lag_gauge.set(time.monotonic() - stamp)
            self._admit()
            self._pump()
            self._timers(time.monotonic())

    def _select_timeout(self) -> float:
        # O(1): the timer sweep caches the earliest due stamp; the
        # MAX_SELECT ceiling bounds how stale it can go (a client
        # admitted after a sweep introduces no due sooner than
        # SYNC_INTERVAL anyway). A due stamp inside the sweep-throttle
        # window waits for the window — a timer can fire at most
        # TIMER_SWEEP_SECONDS late, and a due the throttle would skip
        # must not spin select at timeout 0 until the window opens.
        wake_at = max(self._next_due, self._last_timer_sweep + TIMER_SWEEP_SECONDS)
        return max(0.0, min(MAX_SELECT_SECONDS, wake_at - time.monotonic()))

    def _admit(self) -> None:
        while True:
            with self._inbox_lock:
                if not self._inbox:
                    return
                client = self._inbox.popleft()
            try:
                client.sock.setblocking(False)
                self.selector.register(client.sock, selectors.EVENT_READ, client)
            except (OSError, ValueError, KeyError):
                # socket already dead on arrival
                self._drop(client, registered=False)
                continue
            self._clients[client.fd] = client
            self._needs_pull.add(client.fd)  # pull pre-admission backlog
            # opening SYNC carries the resume token (threaded-front parity)
            self._queue_control(
                client,
                {"type": "SYNC", "rv": client.sub.rv, "view": client.view_id},
            )
            self._flush(client)

    def _pump(self) -> None:
        """Deliver pending deltas. A full walk (skipping caught-up and
        over-budget subscribers in O(1) each) runs only when the view rv
        advanced since the last pump — an idle iteration pumps just the
        ``_needs_pull`` stragglers (fresh admissions, buffers that
        drained back below budget), so no-publish wakeups cost
        O(changed), not O(subscribers)."""
        if not self._clients:
            self._needs_pull.clear()
            return
        view_rv = self.loop.view.rv  # one lock acquisition per pump
        if view_rv != self._pumped_rv:
            self._pumped_rv = view_rv
            targets = list(self._clients.values())
        elif self._needs_pull:
            targets = [
                self._clients[fd] for fd in self._needs_pull if fd in self._clients
            ]
        else:
            return
        self._needs_pull.clear()
        budget = self.loop.sub_buffer_bytes
        instance = self.loop.view.instance
        for client in targets:
            if client.closing or client.buf_bytes >= budget:
                # over budget: stop pulling — the cursor lags and the
                # NEXT pull rides read-time latest-wins compaction
                continue
            if client.view_id != instance:
                # the view swapped rv spaces UNDER this stream (a relay
                # re-adopted after its upstream restarted): grafting the
                # new line onto the old cursor would serve wrong deltas —
                # terminate with the documented GONE recovery instead
                self._queue_control(
                    client,
                    {"type": "GONE", "rv": client.sub.rv, "view": instance},
                )
                self._finish(client)
                continue
            if client.sub.rv >= view_rv:
                continue
            result = client.sub.pull_frames(
                limit=client.limit, codec=client.codec, fresh=client.fresh,
                traced=client.traced,
            )
            if result.status == GONE:
                self._queue_control(
                    client,
                    {"type": "GONE", "rv": result.from_rv,
                     "oldest_rv": self.loop.view.oldest_rv},
                )
                self._finish(client)
            elif result.status != OK:
                # INVALID mid-stream = the view restarted under us; the
                # client's documented recovery is the same re-snapshot
                self._queue_control(
                    client,
                    {"type": "GONE", "rv": result.from_rv,
                     "view": self.loop.view.instance},
                )
                self._finish(client)
            elif result.frames:
                if result.compacted:
                    self._queue_control(
                        client,
                        {"type": "COMPACTED", "from_rv": result.from_rv,
                         "to_rv": result.to_rv},
                    )
                self._queue_frames(client, result.frames)
                client.last_frame = time.monotonic()
            elif result.compacted:
                # sparse relay journal: the cursor advanced over an
                # upstream-sanctioned hole with NOTHING to send. The skip
                # must still reach the wire — COMPACTED sanctions the
                # range, the SYNC moves the consumer's resume token past
                # it so the next live delta reads contiguous (a silent
                # advance here would surface downstream as a false gap)
                self._queue_control(
                    client,
                    {"type": "COMPACTED", "from_rv": result.from_rv,
                     "to_rv": result.to_rv},
                )
                self._queue_control(
                    client,
                    {"type": "SYNC", "rv": client.sub.rv, "view": client.view_id},
                )
                client.last_frame = time.monotonic()
            self._flush(client)

    def _timers(self, now: float) -> None:
        # throttled full sweep: timers here have seconds-scale contracts
        # (2 s SYNC cadence, multi-second windows), so sweeping at most
        # every TIMER_SWEEP_SECONDS keeps high-rate publish iterations
        # from paying an O(subscribers) walk each. The sweep also
        # recomputes the cached next-due stamp _select_timeout reads.
        if now - self._last_timer_sweep < TIMER_SWEEP_SECONDS:
            return
        self._last_timer_sweep = now
        next_due = float("inf")
        instance = self.loop.view.instance
        for client in list(self._clients.values()):
            if not client.closing and client.view_id != instance:
                # idle streams see a mid-life view swap here (the pump
                # only walks clients with pending deltas): same GONE →
                # re-snapshot recovery, within one sweep interval
                self._queue_control(
                    client,
                    {"type": "GONE", "rv": client.sub.rv, "view": instance},
                )
                self._finish(client)
                if client.fd not in self._clients:
                    continue
            if client.closing:
                if now >= client.hard_deadline:
                    # peer never drained its final bytes: tear down
                    self._drop(client)
                else:
                    next_due = min(next_due, client.hard_deadline)
                continue
            if now >= client.deadline:
                # clean window end: final SYNC carries the resume token
                self._queue_control(
                    client,
                    {"type": "SYNC", "rv": client.sub.rv, "view": client.view_id},
                )
                self._finish(client)
                if client.fd in self._clients:
                    next_due = min(next_due, client.hard_deadline)
                continue
            if now - client.last_frame >= SYNC_INTERVAL_SECONDS and not client.buf:
                # heartbeat only truly idle streams: a client with bytes
                # still buffered is stalled, not idle — another SYNC
                # would just grow the backlog it is failing to drain
                self._queue_control(
                    client,
                    {"type": "SYNC", "rv": client.sub.rv, "view": client.view_id},
                )
                client.last_frame = now
                self._flush(client)
                if client.fd not in self._clients:
                    continue
            next_due = min(next_due, client.deadline)
            if not client.buf:
                # stalled clients (bytes pending) contribute no SYNC due:
                # writability, not a clock, unblocks them — a past-due
                # stamp they can never clear would spin the select
                next_due = min(next_due, client.last_frame + SYNC_INTERVAL_SECONDS)
        self._next_due = next_due

    # -- client plumbing ---------------------------------------------------

    def _queue_frames(self, client: _StreamClient, frames: List[bytes]) -> None:
        total = 0
        for frame in frames:
            client.buf.append(frame)
            total += len(frame)
        client.buf_bytes += total
        if self.loop.fanout_bytes is not None:
            self.loop.fanout_bytes.inc(total)

    def _queue_control(self, client: _StreamClient, obj: dict) -> None:
        frame = chunk_frame(obj, client.codec)
        client.buf.append(frame)
        client.buf_bytes += len(frame)
        if self.loop.fanout_bytes is not None:
            self.loop.fanout_bytes.inc(len(frame))

    def _finish(self, client: _StreamClient) -> None:
        """Queue the chunked terminal and close once the buffer drains."""
        client.buf.append(TERMINAL_CHUNK)
        client.buf_bytes += len(TERMINAL_CHUNK)
        client.closing = True
        client.hard_deadline = time.monotonic() + DRAIN_GRACE_SECONDS
        self._flush(client)

    def _flush(self, client: _StreamClient) -> None:
        if client.fd not in self._clients:
            return
        while client.buf:
            head = client.buf[0]
            try:
                n = client.sock.send(head)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._drop(client)
                return
            client.buf_bytes -= n
            if n < len(head):
                # kernel buffer full mid-frame: keep the unsent suffix as
                # a memoryview (zero-copy — the underlying bytes object is
                # the shared frame) and resume on the next writable event
                view = head if isinstance(head, memoryview) else memoryview(head)
                client.buf[0] = view[n:]
                break
            client.buf.popleft()
        self._set_write_interest(client, bool(client.buf))
        if not client.buf and client.closing:
            self._drop(client)
        elif (
            not client.closing
            and client.buf_bytes < self.loop.sub_buffer_bytes
            and client.sub.rv < self._pumped_rv
        ):
            # back under budget with deltas still pending: re-arm a pull
            # even if no new publish advances the view meanwhile
            self._needs_pull.add(client.fd)

    def _set_write_interest(self, client: _StreamClient, want: bool) -> None:
        if want == client.want_write or client.fd not in self._clients:
            return
        events = selectors.EVENT_READ
        if want:
            events |= selectors.EVENT_WRITE
        try:
            self.selector.modify(client.sock, events, client)
            client.want_write = want
        except (OSError, ValueError, KeyError):
            self._drop(client)

    def _on_readable(self, client: _StreamClient) -> None:
        # nothing legitimate arrives on an established watch stream;
        # readable means the peer closed (EOF) or reset — either way the
        # subscriber slot and cursor are freed NOW, not at window end
        try:
            data = client.sock.recv(4096)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop(client)
            return
        if not data:
            self._drop(client)
        # stray request bytes on a watch stream are ignored (a stream is
        # not a keep-alive conversation; it ends by close)

    def _drop(self, client: _StreamClient, *, registered: bool = True) -> None:
        if registered:
            self._clients.pop(client.fd, None)
            try:
                self.selector.unregister(client.sock)
            except (OSError, ValueError, KeyError):
                pass
        try:
            client.sock.close()
        except OSError:
            pass
        self.loop.hub.unsubscribe(client.sub)

    def _teardown(self) -> None:
        # refuse wakes BEFORE closing the pipe fds: a publish racing the
        # close could otherwise os.write() into whatever file/socket the
        # kernel hands the recycled fd number to next
        self._closed = True
        for client in list(self._clients.values()):
            self._drop(client)
        with self._inbox_lock:
            stranded = list(self._inbox)
            self._inbox.clear()
        for client in stranded:
            self._drop(client, registered=False)
        try:
            self.selector.unregister(self._rpipe)
        except (OSError, ValueError, KeyError):
            pass
        self.selector.close()
        for fd in (self._rpipe, self._wpipe):
            try:
                os.close(fd)
            except OSError:
                pass


class BroadcastLoop:
    """The fixed pool of loop workers behind the serving plane's streams.

    Sockets are assigned round-robin at handoff; every publish wakes
    each worker once (coalesced). ``serve.io_threads`` sizes the pool —
    one loop drives thousands of streams (the work per publish is
    appends + sends), more loops spread send() syscall load across
    cores for very wide fleets.
    """

    def __init__(
        self,
        view: FleetView,
        hub: SubscriptionHub,
        *,
        threads: int = 1,
        sub_buffer_bytes: int = 1 << 20,
        metrics=None,
    ):
        self.view = view
        self.hub = hub
        self.sub_buffer_bytes = max(4096, int(sub_buffer_bytes))
        self.fanout_bytes = (
            metrics.counter("serve_fanout_bytes") if metrics is not None else None
        )
        self.lag_gauge = (
            metrics.gauge("serve_loop_lag_seconds") if metrics is not None else None
        )
        self._workers = [_LoopWorker(self, i) for i in range(max(1, int(threads)))]
        self._next = 0
        self._started = False
        view.register_wakeup(self.notify)

    def start(self) -> "BroadcastLoop":
        if not self._started:
            self._started = True
            for worker in self._workers:
                worker.start()
        return self

    def stop(self) -> None:
        # stop NOTIFYING before stopping workers: publishes keep flowing
        # during app shutdown, and a notify after the workers close their
        # pipes would write into recycled fds
        self._started = False
        self.view.unregister_wakeup(self.notify)
        for worker in self._workers:
            worker.stop()
        for worker in self._workers:
            worker.join(timeout=2.0)

    def notify(self) -> None:
        """The view's post-publish wakeup: one self-pipe byte per worker
        (coalesced while one is pending) — never a per-subscriber wake."""
        if not self._started:
            return
        stamp = time.monotonic()
        for worker in self._workers:
            worker.wake(stamp)

    def submit(
        self,
        sock: socket.socket,
        sub: Subscription,
        *,
        timeout: float,
        limit: Optional[int],
        view_id: str,
        codec: str = CODEC_JSON,
        fresh: bool = False,
        traced: bool = False,
    ) -> None:
        """Adopt a handed-off socket (headers already written by the HTTP
        front). The loop owns the socket AND the subscription from here —
        including unsubscribe on every exit path."""
        client = _StreamClient(
            sock, sub,
            deadline=time.monotonic() + timeout,
            limit=limit,
            view_id=view_id,
            codec=codec,
            fresh=fresh,
            traced=traced,
        )
        # round-robin across LIVE workers only: a dead loop's inbox is a
        # black hole (stream never admitted, slot never freed) — the
        # HTTP front refuses handoff when no worker is alive, so a raise
        # here is the narrow race between that check and this one
        n = len(self._workers)
        for offset in range(n):
            worker = self._workers[(self._next + offset) % n]
            if worker.is_alive():
                self._next += offset + 1
                worker.submit(client)
                return
        raise RuntimeError("no live broadcast loop worker")

    @property
    def alive(self) -> bool:
        """Fully healthy: every worker running (the /healthz verdict)."""
        return self._started and all(w.is_alive() for w in self._workers)

    @property
    def accepting(self) -> bool:
        """Able to adopt new streams: at least one live worker (submit
        skips dead ones) — degraded-but-serving is still serving."""
        return self._started and any(w.is_alive() for w in self._workers)

    @property
    def threads(self) -> int:
        return len(self._workers)

    @property
    def client_count(self) -> int:
        return sum(w.client_count for w in self._workers)
