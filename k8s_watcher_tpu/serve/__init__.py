"""Fleet-state serving plane: watch-cache materialized view + resumable
snapshot/delta subscriptions (see ARCHITECTURE.md "Serving plane")."""

from k8s_watcher_tpu.serve.server import ServePlane, ServeServer
from k8s_watcher_tpu.serve.view import (
    DELETE,
    GONE,
    INVALID,
    OK,
    UPSERT,
    Delta,
    FleetView,
    ReadResult,
    Subscription,
    SubscriptionHub,
)

__all__ = [
    "DELETE",
    "GONE",
    "INVALID",
    "OK",
    "UPSERT",
    "Delta",
    "FleetView",
    "ReadResult",
    "ServePlane",
    "ServeServer",
    "Subscription",
    "SubscriptionHub",
]
