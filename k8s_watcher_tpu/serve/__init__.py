"""Fleet-state serving plane: watch-cache materialized view + resumable
snapshot/delta subscriptions over an encode-once broadcast core (see
ARCHITECTURE.md "Serving plane")."""

from k8s_watcher_tpu.serve.broadcast import BroadcastLoop
from k8s_watcher_tpu.serve.server import ServePlane, ServeServer
from k8s_watcher_tpu.serve.view import (
    CODEC_JSON,
    CODEC_MSGPACK,
    CODECS,
    DELETE,
    GONE,
    INVALID,
    JSON_CONTENT_TYPE,
    MSGPACK_CONTENT_TYPE,
    OK,
    UPSERT,
    Delta,
    FleetView,
    FrameReadResult,
    ReadResult,
    Subscription,
    SubscriptionHub,
    chunk_frame,
    frame_body,
    frame_payload,
    msgpack_available,
)

__all__ = [
    "CODEC_JSON",
    "CODEC_MSGPACK",
    "CODECS",
    "DELETE",
    "GONE",
    "INVALID",
    "JSON_CONTENT_TYPE",
    "MSGPACK_CONTENT_TYPE",
    "OK",
    "UPSERT",
    "BroadcastLoop",
    "Delta",
    "FleetView",
    "FrameReadResult",
    "ReadResult",
    "ServePlane",
    "ServeServer",
    "Subscription",
    "SubscriptionHub",
    "chunk_frame",
    "frame_body",
    "frame_payload",
    "msgpack_available",
]
