"""Connection setup: kubeconfig parsing and in-cluster credentials.

Covers the reference's three auth modes (pod_watcher.py:110-157):

1. in-cluster service-account credentials (``use_incluster_config``),
2. an explicit kubeconfig path (with existence check),
3. the default kubeconfig (``~/.kube/config`` or ``$KUBECONFIG``).

Implemented natively (no ``kubernetes`` SDK): the kubeconfig subset parsed is
clusters (server, CA data/file, insecure-skip-tls-verify), users (token,
client cert/key as data or file), contexts and current-context — everything
the bundled mock kubeconfig (reference assets/config) and standard GKE
kubeconfigs use, minus exec/auth-provider plugins which raise a clear error.
"""

from __future__ import annotations

import base64
import dataclasses
import logging
import os
import tempfile
from pathlib import Path
from typing import Any, Dict, Optional, Tuple, Union

import yaml

logger = logging.getLogger(__name__)

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeconfigError(Exception):
    """Unreadable/unsupported kubeconfig or in-cluster environment."""


@dataclasses.dataclass
class K8sConnection:
    """Everything needed to open an authenticated session to an API server."""

    server: str
    token: Optional[str] = None
    ca_file: Optional[str] = None
    client_cert: Optional[Tuple[str, str]] = None  # (certfile, keyfile)
    verify_tls: bool = True

    @property
    def verify(self) -> Union[bool, str]:
        """The ``requests`` verify parameter."""
        if not self.verify_tls:
            return False
        return self.ca_file if self.ca_file else True


def _materialize(data_b64: Optional[str], file_path: Optional[str], label: str) -> Optional[str]:
    """Return a filesystem path for cert material given either inline base64
    data or a path; inline data is written to a private temp file."""
    if file_path:
        return file_path
    if not data_b64:
        return None
    try:
        raw = base64.b64decode(data_b64)
    except Exception as exc:
        raise KubeconfigError(f"invalid base64 in kubeconfig {label}") from exc
    fd, path = tempfile.mkstemp(prefix=f"kwt-{label}-", suffix=".pem")
    with os.fdopen(fd, "wb") as fh:
        fh.write(raw)
    return path


def _index_by_name(items: Any, label: str) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for item in items or []:
        if isinstance(item, dict) and "name" in item:
            out[item["name"]] = item
    if not out:
        raise KubeconfigError(f"kubeconfig has no {label}")
    return out


def load_kubeconfig(path: Union[str, os.PathLike], context: Optional[str] = None) -> K8sConnection:
    """Parse a kubeconfig file into a ``K8sConnection``."""
    path = Path(path)
    if not path.exists():
        raise KubeconfigError(f"Kubeconfig file not found: {path}")
    try:
        doc = yaml.safe_load(path.read_text()) or {}
    except yaml.YAMLError as exc:
        raise KubeconfigError(f"Malformed kubeconfig {path}: {exc}") from exc

    contexts = _index_by_name(doc.get("contexts"), "contexts")
    clusters = _index_by_name(doc.get("clusters"), "clusters")
    users = _index_by_name(doc.get("users"), "users")

    ctx_name = context or doc.get("current-context")
    if not ctx_name or ctx_name not in contexts:
        raise KubeconfigError(f"kubeconfig {path}: unknown context {ctx_name!r}")
    ctx = contexts[ctx_name].get("context") or {}

    cluster_entry = clusters.get(ctx.get("cluster", ""))
    if cluster_entry is None:
        raise KubeconfigError(f"kubeconfig {path}: context references unknown cluster {ctx.get('cluster')!r}")
    cluster = cluster_entry.get("cluster") or {}
    server = cluster.get("server")
    if not server:
        raise KubeconfigError(f"kubeconfig {path}: cluster has no server URL")

    user_entry = users.get(ctx.get("user", "")) or {"user": {}}
    user = user_entry.get("user") or {}
    if "exec" in user or "auth-provider" in user:
        raise KubeconfigError(
            f"kubeconfig {path}: exec/auth-provider credential plugins are not supported; "
            "use a token or client-certificate kubeconfig"
        )

    ca_file = _materialize(cluster.get("certificate-authority-data"), cluster.get("certificate-authority"), "ca")
    cert_file = _materialize(user.get("client-certificate-data"), user.get("client-certificate"), "cert")
    key_file = _materialize(user.get("client-key-data"), user.get("client-key"), "key")
    client_cert = (cert_file, key_file) if cert_file and key_file else None

    return K8sConnection(
        server=server.rstrip("/"),
        token=user.get("token"),
        ca_file=ca_file,
        client_cert=client_cert,
        verify_tls=not cluster.get("insecure-skip-tls-verify", False),
    )


def load_incluster(sa_dir: Union[str, os.PathLike] = SERVICE_ACCOUNT_DIR) -> K8sConnection:
    """Build a connection from the pod's mounted service-account credentials."""
    sa_dir = Path(sa_dir)
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_path = sa_dir / "token"
    if not host or not token_path.exists():
        raise KubeconfigError(
            "Not running in a cluster: KUBERNETES_SERVICE_HOST unset or service-account token missing"
        )
    ca_path = sa_dir / "ca.crt"
    return K8sConnection(
        server=f"https://{host}:{port}",
        token=token_path.read_text().strip(),
        ca_file=str(ca_path) if ca_path.exists() else None,
    )


def load_connection(
    *,
    use_incluster: bool = False,
    config_file: Optional[str] = None,
    verify_tls: bool = True,
) -> K8sConnection:
    """Resolve a connection with the reference's precedence
    (pod_watcher.py:115-134): in-cluster, explicit kubeconfig, default
    kubeconfig (``$KUBECONFIG`` or ``~/.kube/config``)."""
    if use_incluster:
        logger.info("Using in-cluster configuration")
        conn = load_incluster()
    elif config_file:
        logger.info("Loading kubeconfig from: %s", config_file)
        conn = load_kubeconfig(config_file)
    else:
        default = os.environ.get("KUBECONFIG", str(Path.home() / ".kube" / "config"))
        logger.info("Using default kubeconfig: %s", default)
        conn = load_kubeconfig(default)
    if not verify_tls:
        conn.verify_tls = False
    return conn
