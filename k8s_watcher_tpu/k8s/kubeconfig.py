"""Connection setup: kubeconfig parsing and in-cluster credentials.

Covers the reference's three auth modes (pod_watcher.py:110-157):

1. in-cluster service-account credentials (``use_incluster_config``),
2. an explicit kubeconfig path (with existence check),
3. the default kubeconfig (``~/.kube/config`` or ``$KUBECONFIG``).

Implemented natively (no ``kubernetes`` SDK): the kubeconfig subset parsed is
clusters (server, CA data/file, insecure-skip-tls-verify), users (token,
client cert/key as data or file, exec credential plugins per the
client.authentication.k8s.io contract), contexts and current-context —
everything the bundled mock kubeconfig (reference assets/config) and
standard GKE kubeconfigs (including ``gke-gcloud-auth-plugin``) use. The
reference got exec support implicitly from the SDK's ``load_kube_config``
(pod_watcher.py:129); here the plugin protocol is implemented directly:
run the command, parse the ExecCredential JSON, cache the token, refresh
on ``expirationTimestamp``. Only interactive plugins (and the legacy
``auth-provider`` stanza) raise.
"""

from __future__ import annotations

import base64
import dataclasses
import datetime
import json
import logging
import os
import subprocess
import tempfile
import threading
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple, Union

import yaml

logger = logging.getLogger(__name__)

SERVICE_ACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeconfigError(Exception):
    """Unreadable/unsupported kubeconfig or in-cluster environment."""


# refresh this long before expirationTimestamp so a token never expires
# mid-request (matches client-go's expiry delta)
_EXEC_EXPIRY_SKEW_S = 60.0


class ExecCredential:
    """A ``users[].user.exec`` credential plugin (client.authentication.k8s.io).

    Runs the configured command, parses the ExecCredential JSON it prints,
    caches the token, and re-runs the plugin when ``expirationTimestamp``
    (minus a skew) passes. Thread-safe: one plugin run at a time, shared by
    the pod- and node-plane clients that share a ``K8sConnection``.
    """

    def __init__(
        self,
        command: str,
        args: Optional[List[str]] = None,
        env: Optional[List[Dict[str, str]]] = None,
        api_version: str = "client.authentication.k8s.io/v1beta1",
        provide_cluster_info: bool = False,
        cluster_info: Optional[Dict[str, Any]] = None,
        timeout: float = 60.0,
    ):
        self.command = command
        self.args = list(args or [])
        self.env = list(env or [])
        self.api_version = api_version
        self.provide_cluster_info = provide_cluster_info
        self.cluster_info = cluster_info or {}
        self.timeout = timeout
        self._lock = threading.Lock()
        self._token: Optional[str] = None
        self._expires_at: Optional[float] = None  # unix seconds

    def token(self) -> str:
        with self._lock:
            if self._token is not None and not self._expired():
                return self._token
            self._refresh_locked()
            return self._token  # type: ignore[return-value]

    def invalidate(self) -> None:
        """Drop the cached token (e.g. after a 401): next use re-runs the
        plugin even if expirationTimestamp hasn't passed."""
        with self._lock:
            self._token = None
            self._expires_at = None

    def _expired(self) -> bool:
        if self._expires_at is None:
            return False  # no expirationTimestamp: cache for process life
        import time

        return time.time() >= self._expires_at - _EXEC_EXPIRY_SKEW_S

    def _refresh_locked(self) -> None:
        env = dict(os.environ)
        for entry in self.env:
            name = entry.get("name")
            if name:
                env[name] = entry.get("value", "")
        exec_info: Dict[str, Any] = {
            "apiVersion": self.api_version,
            "kind": "ExecCredential",
            "spec": {"interactive": False},
        }
        if self.provide_cluster_info:
            exec_info["spec"]["cluster"] = self.cluster_info
        env["KUBERNETES_EXEC_INFO"] = json.dumps(exec_info)
        try:
            proc = subprocess.run(
                [self.command, *self.args],
                env=env,
                capture_output=True,
                text=True,
                timeout=self.timeout,
            )
        except FileNotFoundError as exc:
            raise KubeconfigError(
                f"exec credential plugin {self.command!r} not found on PATH"
            ) from exc
        except subprocess.TimeoutExpired as exc:
            raise KubeconfigError(
                f"exec credential plugin {self.command!r} timed out after {self.timeout:.0f}s"
            ) from exc
        if proc.returncode != 0:
            raise KubeconfigError(
                f"exec credential plugin {self.command!r} failed "
                f"(rc={proc.returncode}): {proc.stderr.strip()[:500]}"
            )
        try:
            doc = json.loads(proc.stdout)
        except json.JSONDecodeError as exc:
            raise KubeconfigError(
                f"exec credential plugin {self.command!r} printed invalid JSON"
            ) from exc
        status = doc.get("status") or {}
        token = status.get("token")
        if not token:
            if status.get("clientCertificateData"):
                raise KubeconfigError(
                    f"exec credential plugin {self.command!r} returned a client "
                    "certificate; only token-based exec credentials are supported"
                )
            raise KubeconfigError(
                f"exec credential plugin {self.command!r} returned no status.token"
            )
        self._token = token
        self._expires_at = _parse_rfc3339(status.get("expirationTimestamp"))


def _parse_rfc3339(value: Optional[str]) -> Optional[float]:
    """RFC3339 timestamp -> unix seconds, or None (bad/missing → None, so
    the token is cached for the process lifetime per the exec contract)."""
    if not value:
        return None
    try:
        text = value.replace("Z", "+00:00")
        return datetime.datetime.fromisoformat(text).timestamp()
    except ValueError:
        logger.warning("exec credential: unparseable expirationTimestamp %r", value)
        return None


# bound service-account tokens are rotated on disk by the kubelet; re-read
# at most this often (client-go uses a similar period for file reloads)
_TOKEN_FILE_TTL_S = 60.0


@dataclasses.dataclass
class K8sConnection:
    """Everything needed to open an authenticated session to an API server."""

    server: str
    token: Optional[str] = None
    ca_file: Optional[str] = None
    client_cert: Optional[Tuple[str, str]] = None  # (certfile, keyfile)
    verify_tls: bool = True
    exec_credential: Optional[ExecCredential] = None
    # re-read this file for the token (in-cluster bound SA tokens rotate
    # ~hourly; a once-read token would 401 a long-lived watcher mid-life)
    token_file: Optional[str] = None

    @property
    def dynamic_auth(self) -> bool:
        """True when the token can change mid-process (exec plugin or
        rotating token file) and a 401 is worth an invalidate-and-retry."""
        return self.exec_credential is not None or self.token_file is not None

    def auth_token(self) -> Optional[str]:
        """The bearer token to send right now: exec plugins re-run on
        expiry, token files re-read on a TTL, static tokens pass through."""
        if self.exec_credential is not None:
            return self.exec_credential.token()
        if self.token_file:
            import time

            cached = getattr(self, "_file_token_cache", None)
            if cached is None or time.monotonic() - cached[1] > _TOKEN_FILE_TTL_S:
                try:
                    self.token = Path(self.token_file).read_text().strip()
                except OSError as exc:
                    logger.warning("Could not re-read token file %s: %s", self.token_file, exc)
                self._file_token_cache = (self.token, time.monotonic())
        return self.token

    def invalidate_token(self) -> None:
        """Drop cached credentials after a 401 so the next request
        re-derives them (plugin re-run / token-file re-read)."""
        if self.exec_credential is not None:
            self.exec_credential.invalidate()
        self._file_token_cache = None

    @property
    def verify(self) -> Union[bool, str]:
        """The ``requests`` verify parameter."""
        if not self.verify_tls:
            return False
        return self.ca_file if self.ca_file else True


def _materialize(data_b64: Optional[str], file_path: Optional[str], label: str) -> Optional[str]:
    """Return a filesystem path for cert material given either inline base64
    data or a path; inline data is written to a private temp file."""
    if file_path:
        return file_path
    if not data_b64:
        return None
    try:
        raw = base64.b64decode(data_b64)
    except Exception as exc:
        raise KubeconfigError(f"invalid base64 in kubeconfig {label}") from exc
    fd, path = tempfile.mkstemp(prefix=f"kwt-{label}-", suffix=".pem")
    with os.fdopen(fd, "wb") as fh:
        fh.write(raw)
    return path


def _index_by_name(items: Any, label: str) -> Dict[str, Dict[str, Any]]:
    out: Dict[str, Dict[str, Any]] = {}
    for item in items or []:
        if isinstance(item, dict) and "name" in item:
            out[item["name"]] = item
    if not out:
        raise KubeconfigError(f"kubeconfig has no {label}")
    return out


def load_kubeconfig(path: Union[str, os.PathLike], context: Optional[str] = None) -> K8sConnection:
    """Parse a kubeconfig file into a ``K8sConnection``."""
    path = Path(path)
    if not path.exists():
        raise KubeconfigError(f"Kubeconfig file not found: {path}")
    try:
        doc = yaml.safe_load(path.read_text()) or {}
    except yaml.YAMLError as exc:
        raise KubeconfigError(f"Malformed kubeconfig {path}: {exc}") from exc

    contexts = _index_by_name(doc.get("contexts"), "contexts")
    clusters = _index_by_name(doc.get("clusters"), "clusters")
    users = _index_by_name(doc.get("users"), "users")

    ctx_name = context or doc.get("current-context")
    if not ctx_name or ctx_name not in contexts:
        raise KubeconfigError(f"kubeconfig {path}: unknown context {ctx_name!r}")
    ctx = contexts[ctx_name].get("context") or {}

    cluster_entry = clusters.get(ctx.get("cluster", ""))
    if cluster_entry is None:
        raise KubeconfigError(f"kubeconfig {path}: context references unknown cluster {ctx.get('cluster')!r}")
    cluster = cluster_entry.get("cluster") or {}
    server = cluster.get("server")
    if not server:
        raise KubeconfigError(f"kubeconfig {path}: cluster has no server URL")

    user_entry = users.get(ctx.get("user", "")) or {"user": {}}
    user = user_entry.get("user") or {}
    if "auth-provider" in user:
        # legacy stanza removed in client-go 1.26; its gcp/azure providers
        # were interactive-or-SDK-bound, so there is nothing to run headless
        raise KubeconfigError(
            f"kubeconfig {path}: legacy auth-provider credential plugins are not "
            "supported; migrate to an exec plugin (e.g. gke-gcloud-auth-plugin) "
            "or a token/client-certificate kubeconfig"
        )

    exec_credential = None
    if "exec" in user:
        # an empty/null exec stanza must fail HERE with a clear message,
        # not connect anonymously and 401 later
        exec_spec = user.get("exec") or {}
        if exec_spec.get("interactiveMode") == "Always":
            raise KubeconfigError(
                f"kubeconfig {path}: exec plugin requires interactiveMode=Always, "
                "which a headless watcher cannot satisfy"
            )
        command = exec_spec.get("command")
        if not command:
            raise KubeconfigError(f"kubeconfig {path}: exec stanza has no command")
        if os.sep in command and not os.path.isabs(command):
            # client-go contract: relative plugin paths resolve against the
            # kubeconfig's directory, not the process CWD
            command = str(path.parent / command)
        exec_credential = ExecCredential(
            command=command,
            args=exec_spec.get("args"),
            env=exec_spec.get("env"),
            api_version=exec_spec.get("apiVersion", "client.authentication.k8s.io/v1beta1"),
            provide_cluster_info=bool(exec_spec.get("provideClusterInfo")),
            cluster_info={
                "server": server,
                "certificate-authority-data": cluster.get("certificate-authority-data"),
                "insecure-skip-tls-verify": bool(cluster.get("insecure-skip-tls-verify", False)),
            },
        )

    ca_file = _materialize(cluster.get("certificate-authority-data"), cluster.get("certificate-authority"), "ca")
    cert_file = _materialize(user.get("client-certificate-data"), user.get("client-certificate"), "cert")
    key_file = _materialize(user.get("client-key-data"), user.get("client-key"), "key")
    client_cert = (cert_file, key_file) if cert_file and key_file else None

    return K8sConnection(
        server=server.rstrip("/"),
        token=user.get("token"),
        ca_file=ca_file,
        client_cert=client_cert,
        verify_tls=not cluster.get("insecure-skip-tls-verify", False),
        exec_credential=exec_credential,
    )


def load_incluster(sa_dir: Union[str, os.PathLike] = SERVICE_ACCOUNT_DIR) -> K8sConnection:
    """Build a connection from the pod's mounted service-account credentials."""
    sa_dir = Path(sa_dir)
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    token_path = sa_dir / "token"
    if not host or not token_path.exists():
        raise KubeconfigError(
            "Not running in a cluster: KUBERNETES_SERVICE_HOST unset or service-account token missing"
        )
    ca_path = sa_dir / "ca.crt"
    return K8sConnection(
        server=f"https://{host}:{port}",
        token=token_path.read_text().strip(),
        ca_file=str(ca_path) if ca_path.exists() else None,
        # bound SA tokens rotate on disk ~hourly; keep re-reading
        token_file=str(token_path),
    )


def load_connection(
    *,
    use_incluster: bool = False,
    config_file: Optional[str] = None,
    verify_tls: bool = True,
) -> K8sConnection:
    """Resolve a connection with the reference's precedence
    (pod_watcher.py:115-134): in-cluster, explicit kubeconfig, default
    kubeconfig (``$KUBECONFIG`` or ``~/.kube/config``)."""
    if use_incluster:
        logger.info("Using in-cluster configuration")
        conn = load_incluster()
    elif config_file:
        logger.info("Loading kubeconfig from: %s", config_file)
        conn = load_kubeconfig(config_file)
    else:
        default = os.environ.get("KUBECONFIG", str(Path.home() / ".kube" / "config"))
        logger.info("Using default kubeconfig: %s", default)
        conn = load_kubeconfig(default)
    if not verify_tls:
        conn.verify_tls = False
    return conn
