"""Minimal Kubernetes REST client.

Implements exactly the API surface the watcher uses — the reference got this
from the SDK's ``CoreV1Api`` (pod_watcher.py:137-148, 264):

- ``get_api_version``        GET /version          (connection smoke test)
- ``list_namespaces``        GET /api/v1/namespaces
- ``list_pods``              GET /api/v1/pods  (all namespaces) or
                             GET /api/v1/namespaces/{ns}/pods
- ``watch_pods``             the same endpoints with ``watch=true``, streamed
                             as JSON-lines over chunked HTTP

Watch semantics follow the Kubernetes API contract: events resume from
``resourceVersion``, bookmarks are requested so resume versions stay fresh,
and a 410 Gone (either as HTTP status or as an in-stream ERROR event)
raises ``K8sGoneError`` so the caller can relist.

HTTP(S)_PROXY/NO_PROXY are honored via requests' default ``trust_env``
(tests/test_proxy.py proves the LIST and the streamed WATCH both traverse
a forward proxy); the notify plane's hand-rolled client supplies the same
contract itself (notify/client.py:proxy_for).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Any, Dict, Iterator, List, Optional

import requests
import urllib3

from k8s_watcher_tpu.k8s.kubeconfig import K8sConnection
from k8s_watcher_tpu.watch.sharded import parse_shard_selector

logger = logging.getLogger(__name__)


class K8sApiError(Exception):
    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


class K8sGoneError(K8sApiError):
    """resourceVersion too old (HTTP 410) — caller must relist.

    ``token_expiry`` is True only when a paged LIST exhausted its restarts
    on expired continue tokens; a 410 from a watch or from the FIRST page
    of a list attempt (anomalous — no token was in play) leaves it False,
    so callers' log lines don't misattribute the failure."""

    token_expiry: bool = False


class K8sConflictError(K8sApiError):
    """HTTP 409 — create raced another writer, or update had a stale
    resourceVersion. Leader election treats this as "lost the race"."""


class K8sNotFoundError(K8sApiError):
    """HTTP 404 — object does not exist."""


def decode_watch_chunks(
    chunks: Iterator[bytes], scanner, shard=None
) -> Iterator[Dict[str, Any]]:
    """The watch decode hot path: raw chunked-transfer byte chunks ->
    watch-event dicts, with ``scanner.scan_chunk`` running BEFORE any
    ``json.loads`` so non-significant frames (no accelerator key;
    foreign-shard uids when ``shard=(i, n)``) skip the parse entirely and
    surface as coalesced rv-only PREFILTERED markers.

    Factored out of the HTTP client so every consumer of raw frame bytes —
    the live watch (``K8sClient._watch``), the multi-process shard readers'
    replay seam, and the bench's A/B legs — decodes through the IDENTICAL
    code. Frame boundaries are ours to find (they don't align with HTTP
    chunks): the unconsumed tail of each chunk is prepended to the next;
    a non-empty tail at end-of-stream is the final (unterminated) frame.
    """
    scan_chunk = scanner.scan_chunk
    tail = b""
    for chunk in chunks:
        if not chunk:
            continue
        buf = tail + chunk if tail else chunk
        records, consumed = scan_chunk(buf, shard=shard)
        tail = buf[consumed:]
        # skip-runs arrive pre-coalesced from the scanner; merge runs
        # that continue across chunk boundaries so a non-TPU event storm
        # costs one marker per chunk at most
        skip_rv, skipped = None, 0
        for start, length, rv, count in records:
            if rv is not None:
                skip_rv, skipped = rv, skipped + count
                continue
            if skipped:
                yield K8sClient._prefiltered_marker(skip_rv, skipped)
                skip_rv, skipped = None, 0
            yield K8sClient._parse_frame(buf[start : start + length])
        if skipped:
            yield K8sClient._prefiltered_marker(skip_rv, skipped)
    if tail.strip():
        # stream closed mid-line without a trailing newline: the tail is
        # the final frame
        scan = scanner.scan(tail)
        if scan.skippable or (shard is not None and scan.foreign_shard(*shard)):
            yield K8sClient._prefiltered_marker(scan.resource_version)
        else:
            yield K8sClient._parse_frame(tail)


class K8sClient:
    def __init__(self, connection: K8sConnection, *, request_timeout: float = 30.0):
        self.connection = connection
        self.request_timeout = request_timeout
        self.session = requests.Session()
        # static tokens install once; dynamic credentials (exec plugins,
        # rotating token files) resolve lazily per request — running a
        # subprocess in a constructor would block init and crash callers
        # on transient plugin failures
        if connection.token and not connection.dynamic_auth:
            self.session.headers["Authorization"] = f"Bearer {connection.token}"
        if connection.client_cert:
            self.session.cert = connection.client_cert
        self.session.verify = connection.verify
        self._active_watch_response = None  # live watch stream, for abort_watch()
        self._watch_aborted = False  # sticky: this client is shutting down

    def abort_watch(self) -> None:
        """Close the in-flight watch stream (thread-safe-enough: called from
        a signal/stop path while another thread blocks reading it). The
        blocked read then errors out promptly instead of waiting out the
        server-side watch window — this is what makes SIGTERM shutdown fast
        on a quiet cluster.

        The abort is STICKY: a watch that is mid-connect when this runs (so
        there is no response to close yet) still terminates, because
        watch_pods re-checks the flag right after the connect."""
        self._watch_aborted = True
        response = self._active_watch_response
        if response is not None:
            try:
                response.close()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass

    # -- plumbing ----------------------------------------------------------

    def _refresh_auth(self) -> None:
        """(Re)install the bearer token. Static tokens are a one-time set;
        exec-plugin credentials (kubeconfig.ExecCredential) are re-checked
        per request so a token past its expirationTimestamp is replaced
        before it can 401 a long-lived watcher.

        Plugin failures surface as K8sApiError so the watch/leader retry
        loops treat them like any other transient API failure (backoff and
        reconnect) instead of dying on an uncaught KubeconfigError."""
        if not self.connection.dynamic_auth:
            return  # static auth installed at construction
        try:
            token = self.connection.auth_token()
        except Exception as exc:
            raise K8sApiError(f"credential refresh failed: {exc}") from exc
        if token:
            self.session.headers["Authorization"] = f"Bearer {token}"

    def _handle_401(self, response) -> bool:
        """A 401 under dynamic auth means the cached token was revoked or
        rotated early: drop it so the next attempt re-derives it (re-run
        the exec plugin / re-read the token file — client-go behavior).
        Returns True when a retry is worth it."""
        if response.status_code != 401 or not self.connection.dynamic_auth:
            return False
        logger.warning("API server returned 401; re-deriving credentials")
        self.connection.invalidate_token()
        return True

    def _url(self, path: str) -> str:
        return f"{self.connection.server}{path}"

    def _request(
        self,
        method: str,
        path: str,
        params: Optional[Dict[str, Any]] = None,
        json_body: Optional[Dict[str, Any]] = None,
        **kwargs,
    ) -> requests.Response:
        for retry_401 in (True, False):
            self._refresh_auth()
            try:
                response = self.session.request(
                    method, self._url(path), params=params, json=json_body, timeout=self.request_timeout, **kwargs
                )
            except requests.RequestException as exc:
                raise K8sApiError(f"{method} {path} failed: {exc}") from exc
            if retry_401 and self._handle_401(response):
                continue  # token re-minted; one retry
            break
        if response.status_code == 404:
            raise K8sNotFoundError(f"{method} {path}: not found", status=404)
        if response.status_code == 409:
            raise K8sConflictError(f"{method} {path}: conflict: {response.text[:300]}", status=409)
        if response.status_code == 410:
            raise K8sGoneError(f"{method} {path}: resourceVersion expired (410 Gone)", status=410)
        if response.status_code >= 400:
            raise K8sApiError(
                f"{method} {path}: HTTP {response.status_code}: {response.text[:300]}", status=response.status_code
            )
        return response

    def _get(self, path: str, params: Optional[Dict[str, Any]] = None, **kwargs) -> requests.Response:
        return self._request("GET", path, params, **kwargs)

    # -- API surface -------------------------------------------------------

    def get_api_version(self) -> str:
        """Server version string, e.g. ``v1.31`` (smoke test; parity with
        ``get_api_version`` at pod_watcher.py:140)."""
        info = self._get("/version").json()
        major, minor = info.get("major", "?"), info.get("minor", "?")
        return f"v{major}.{minor}"

    def list_namespaces(self, limit: Optional[int] = None) -> List[str]:
        params: Dict[str, Any] = {}
        if limit:
            params["limit"] = limit
        body = self._get("/api/v1/namespaces", params).json()
        return [(item.get("metadata") or {}).get("name", "") for item in body.get("items", [])]

    def _pods_path(self, namespace: Optional[str]) -> str:
        return f"/api/v1/namespaces/{namespace}/pods" if namespace else "/api/v1/pods"

    # -- coordination.k8s.io/v1 Leases (leader election) -------------------

    @staticmethod
    def _leases_path(namespace: str, name: Optional[str] = None) -> str:
        base = f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases"
        return f"{base}/{name}" if name else base

    def get_lease(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        """The Lease object, or None if it does not exist."""
        try:
            return self._get(self._leases_path(namespace, name)).json()
        except K8sNotFoundError:
            return None

    def create_lease(self, namespace: str, name: str, spec: Dict[str, Any]) -> Dict[str, Any]:
        """POST a new Lease; raises K8sConflictError if it already exists
        (another candidate won the creation race)."""
        body = {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": name, "namespace": namespace},
            "spec": spec,
        }
        return self._request("POST", self._leases_path(namespace), json_body=body).json()

    def replace_lease(self, namespace: str, name: str, lease: Dict[str, Any]) -> Dict[str, Any]:
        """PUT a full Lease object; the server enforces optimistic concurrency
        on ``metadata.resourceVersion`` (stale write -> K8sConflictError)."""
        return self._request("PUT", self._leases_path(namespace, name), json_body=lease).json()

    def list_pods(
        self,
        namespace: Optional[str] = None,
        *,
        limit: Optional[int] = None,
        label_selector: Optional[str] = None,
        continue_token: Optional[str] = None,
        shard_selector: Optional[str] = None,
    ) -> Dict[str, Any]:
        """One page of pods; returns the raw PodList body (items +
        metadata.resourceVersion, the resume point for a subsequent watch,
        + metadata.continue when more pages remain). Pass the previous
        page's ``metadata.continue`` as ``continue_token`` to fetch the
        next page; an expired token raises K8sGoneError (410) and the
        caller must restart the list (see ``list_pods_paged``).

        ``shard_selector`` ("i/n", watch/sharded.py) asks the server to
        return only pods whose uid-hash lands on shard i. The in-repo mock
        apiserver honors it (each shard's LIST pages 1/n of the cluster,
        with its own continue-token chain); a stock apiserver ignores the
        unknown param and the caller's client-side ownership filter keeps
        correctness."""
        params: Dict[str, Any] = {}
        if limit:
            params["limit"] = limit
        if label_selector:
            params["labelSelector"] = label_selector
        if continue_token:
            params["continue"] = continue_token
        if shard_selector:
            params["shard"] = shard_selector
        return self._get(self._pods_path(namespace), params).json()

    def _list_paged(self, fetch_page, max_restarts: int):
        """Shared pagination driver: ``fetch_page(continue_token) -> body``.

        Yields ``(attempt, page_body)``. ``attempt`` increments when an
        expired continue token (410 mid-pagination: the snapshot was
        compacted away under us) forces the list to restart from scratch —
        the consumer must then RESET anything accumulated from earlier
        pages of the aborted attempt, because the new attempt is a new
        snapshot at a new resourceVersion (k8s/watch.py resets its
        listed-uid set; acting on a mixed-snapshot union would synthesize
        wrong tombstones). Pages within one attempt share their snapshot's
        resourceVersion. Raises K8sGoneError after ``max_restarts``
        restarts (a pathologically churning cluster needs operator eyes,
        not an infinite list loop)."""
        attempt = 0
        while True:
            token: Optional[str] = None
            try:
                while True:
                    page = fetch_page(token)
                    yield attempt, page
                    token = (page.get("metadata") or {}).get("continue")
                    if not token:
                        return
            except K8sGoneError as exc:
                if token is None:
                    # the FIRST page 410'd: no continue token was in play,
                    # so this is not token expiry (even on attempt > 0,
                    # where restarts may well remain — a fresh unpaged LIST
                    # 410ing needs operator eyes, not another restart)
                    exc.token_expiry = False
                    raise
                attempt += 1
                if attempt > max_restarts:
                    exc.token_expiry = True
                    raise
                logger.warning(
                    "LIST continue token expired (410) mid-pagination; "
                    "restarting the list (attempt %d/%d)", attempt, max_restarts,
                )

    def list_pods_paged(
        self,
        namespace: Optional[str] = None,
        *,
        page_size: int = 500,
        label_selector: Optional[str] = None,
        max_restarts: int = 2,
        shard_selector: Optional[str] = None,
    ):
        """Stream a large pod LIST in bounded pages (``limit``+``continue``
        — the SDK-provided behavior at reference pod_watcher.py:264 that
        the from-scratch client must supply itself; without it every
        relist of a large cluster is one unbounded response). Contract:
        see ``_list_paged``; ``shard_selector``: see ``list_pods``."""
        return self._list_paged(
            lambda token: self.list_pods(
                namespace,
                limit=page_size,
                label_selector=label_selector,
                continue_token=token,
                shard_selector=shard_selector,
            ),
            max_restarts,
        )

    @staticmethod
    def _prefetch_iter(source):
        """One-ahead prefetch: a helper thread pulls the NEXT page (HTTP
        round trip + server-side serialization + JSON decode) while the
        consumer processes the current one — the fetch/process overlap
        that makes a paged relist's wall time max(fetch, process) per page
        instead of their sum. Exceptions (410 token expiry included)
        re-raise in the consumer, in order. The consumer abandoning early
        sets ``cancel``; the helper notices at its next hand-off."""
        import queue as _queue

        out: "_queue.Queue" = _queue.Queue(maxsize=1)
        done = object()
        cancel = threading.Event()

        def put_cancellable(item) -> bool:
            """Bounded put that gives up once the consumer abandoned us —
            EVERY pump-side put must go through this, the terminal
            sentinels included, or an early-exiting consumer leaves the
            pump thread blocked forever holding a full LIST page."""
            while not cancel.is_set():
                try:
                    out.put(item, timeout=0.2)
                    return True
                except _queue.Full:
                    continue
            return False

        def pump() -> None:
            try:
                for item in source:
                    if not put_cancellable(item):
                        return
                put_cancellable(done)
            except BaseException as exc:  # noqa: BLE001 — forwarded, not handled
                put_cancellable(("__exc__", exc))

        thread = threading.Thread(target=pump, name="list-page-prefetch", daemon=True)
        thread.start()
        try:
            while True:
                item = out.get()
                if item is done:
                    return
                if isinstance(item, tuple) and len(item) == 2 and item[0] == "__exc__":
                    raise item[1]
                yield item
        finally:
            cancel.set()

    @staticmethod
    def iter_list_pages(pages, *, metrics=None, metric_prefix: str = "relist", prefetch: bool = False):
        """Consume a ``_list_paged`` stream page by page, yielding
        ``(rv, items, attempt_changed)`` while recording the shared relist
        cost metrics (``<prefix>s``/``<prefix>_pages``/
        ``<prefix>_restarts`` counters + the ``<prefix>_duration``
        histogram). Duration records in ``finally`` — an ABORTED relist
        (paging exhaustion) is the most expensive kind and must stay
        visible in its own cost metrics. ``attempt_changed`` is True on
        the first page of a RESTARTED attempt (new snapshot): consumers
        must reset anything accumulated from the aborted attempt's pages
        (both relist consumers reset their tombstone bookkeeping — the
        invariants live HERE so the pod and node paths can't drift).
        ``prefetch`` overlaps the next page's fetch with the current
        page's processing (see ``_prefetch_iter``)."""
        import time

        if prefetch:
            pages = K8sClient._prefetch_iter(pages)
        t0 = time.monotonic()
        if metrics is not None:
            metrics.counter(f"{metric_prefix}s").inc()
        last_attempt = 0
        try:
            for attempt, body in pages:
                changed = attempt != last_attempt
                if changed:
                    last_attempt = attempt
                    if metrics is not None:
                        metrics.counter(f"{metric_prefix}_restarts").inc()
                if metrics is not None:
                    metrics.counter(f"{metric_prefix}_pages").inc()
                yield (
                    (body.get("metadata") or {}).get("resourceVersion"),
                    body.get("items", []),
                    changed,
                )
        finally:
            if metrics is not None:
                metrics.histogram(f"{metric_prefix}_duration").observe_since(t0)

    def list_nodes_paged(
        self,
        *,
        page_size: int = 500,
        label_selector: Optional[str] = None,
        max_restarts: int = 2,
    ):
        """Stream a node LIST in bounded pages — the node plane
        (nodes/watcher.py) and the remediation budget adoption
        (remediate/actuator.py) relist nodes too, and a several-thousand-
        node cluster deserves the same memory bound as pods. Contract:
        see ``_list_paged``."""
        return self._list_paged(
            lambda token: self.list_nodes(
                limit=page_size,
                label_selector=label_selector,
                continue_token=token,
            ),
            max_restarts,
        )

    def list_nodes(
        self,
        *,
        label_selector: Optional[str] = None,
        limit: Optional[int] = None,
        continue_token: Optional[str] = None,
    ) -> Dict[str, Any]:
        """One page of nodes; raw NodeList body (items + resourceVersion,
        + metadata.continue when more pages remain — same paging contract
        as ``list_pods``)."""
        params: Dict[str, Any] = {}
        if label_selector:
            params["labelSelector"] = label_selector
        if limit:
            params["limit"] = limit
        if continue_token:
            params["continue"] = continue_token
        return self._get("/api/v1/nodes", params).json()

    def get_node(self, name: str) -> Dict[str, Any]:
        """One Node object (raises K8sNotFoundError if absent)."""
        return self._get(f"/api/v1/nodes/{name}").json()

    def patch_node(self, name: str, patch: Dict[str, Any]) -> Dict[str, Any]:
        """JSON merge-patch (RFC 7386) a Node — the write the remediation
        plane uses to cordon (``spec.unschedulable``) and taint
        (``spec.taints``) a suspect node. Merge-patch replaces lists
        wholesale, so taint edits are read-modify-write on the caller side
        (the same contract ``kubectl taint`` uses)."""
        return self._request(
            "PATCH",
            f"/api/v1/nodes/{name}",
            json_body=patch,
            headers={"Content-Type": "application/merge-patch+json"},
        ).json()

    # -- write surface (integration/chaos tooling) -------------------------
    # The watcher itself is read-only; these drive REAL create/delete churn
    # through the watch->pipeline path in the acceptance write tier
    # (tests/test_integration_cluster.py) without shelling out to kubectl —
    # the same calls work against kind, GKE, and the in-repo mock apiserver.

    def create_pod(self, namespace: str, pod: Dict[str, Any]) -> Dict[str, Any]:
        """POST a Pod manifest; raises K8sConflictError if it exists."""
        return self._request("POST", self._pods_path(namespace), json_body=pod).json()

    def delete_pod(self, namespace: str, name: str) -> Dict[str, Any]:
        """DELETE a pod (raises K8sNotFoundError if absent)."""
        return self._request("DELETE", f"{self._pods_path(namespace)}/{name}").json()

    def create_namespace(self, name: str) -> Dict[str, Any]:
        body = {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": name}}
        return self._request("POST", "/api/v1/namespaces", json_body=body).json()

    def delete_namespace(self, name: str) -> Dict[str, Any]:
        return self._request("DELETE", f"/api/v1/namespaces/{name}").json()

    def watch_pods(
        self,
        namespace: Optional[str] = None,
        *,
        resource_version: Optional[str] = None,
        timeout_seconds: int = 300,
        allow_bookmarks: bool = True,
        label_selector: Optional[str] = None,
        scanner=None,  # native.scanner.FrameScanner — hot-loop prefilter
        shard_selector: Optional[str] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Stream raw pod watch events (``{"type": ..., "object": ...}``)
        until the server closes the bounded watch or an error occurs.

        With a ``scanner``, frames that provably cannot request the
        accelerator resource are skipped WITHOUT a JSON parse and surface as
        lightweight ``{"type": "PREFILTERED"}`` markers carrying only the
        resourceVersion (the hot loop's dominant cost in a mostly-non-TPU
        cluster is decoding pods the resource filter then discards).

        ``shard_selector`` ("i/n") asks the server to stream only shard
        i's pods (the mock apiserver honors it). Against a server that
        ignores it, frames whose uid the scanner can extract are dropped
        pre-parse when they hash to another shard — the same PREFILTERED
        contract, so the resume version still advances."""
        return self._watch(
            self._pods_path(namespace),
            resource_version=resource_version,
            timeout_seconds=timeout_seconds,
            allow_bookmarks=allow_bookmarks,
            label_selector=label_selector,
            scanner=scanner,
            shard_selector=shard_selector,
        )

    def watch_nodes(
        self,
        *,
        resource_version: Optional[str] = None,
        timeout_seconds: int = 300,
        allow_bookmarks: bool = True,
        label_selector: Optional[str] = None,
    ) -> Iterator[Dict[str, Any]]:
        """Stream raw node watch events (same contract as ``watch_pods``;
        no prefilter — node streams are tiny next to pod streams).

        NOTE: one client carries at most one live watch (``abort_watch``
        closes it); run the node watch on its OWN ``K8sClient``."""
        return self._watch(
            "/api/v1/nodes",
            resource_version=resource_version,
            timeout_seconds=timeout_seconds,
            allow_bookmarks=allow_bookmarks,
            label_selector=label_selector,
            scanner=None,
        )

    def _watch(
        self,
        path: str,
        *,
        resource_version: Optional[str],
        timeout_seconds: int,
        allow_bookmarks: bool,
        label_selector: Optional[str],
        scanner,
        shard_selector: Optional[str] = None,
    ) -> Iterator[Dict[str, Any]]:
        params: Dict[str, Any] = {"watch": "true", "timeoutSeconds": timeout_seconds}
        if resource_version:
            params["resourceVersion"] = resource_version
        if allow_bookmarks:
            params["allowWatchBookmarks"] = "true"
        if label_selector:
            params["labelSelector"] = label_selector
        if shard_selector:
            params["shard"] = shard_selector

        # Read timeout must outlast the server-side watch window or we'd kill
        # healthy idle watches; +30 s of slack over timeoutSeconds.
        response = None
        self._refresh_auth()
        try:
            try:
                response = self.session.get(
                    self._url(path),
                    params=params,
                    stream=True,
                    timeout=(self.request_timeout, timeout_seconds + 30),
                )
            except requests.RequestException as exc:
                raise K8sApiError(f"watch connect failed: {exc}") from exc
            # register BEFORE any body read: reading an error body below
            # can block on a stalled stream for the full read timeout, and
            # an unregistered response is invisible to abort_watch() — a
            # SIGTERM landing there would wedge shutdown past any grace
            # period
            self._active_watch_response = response
            if response.status_code == 410:
                raise K8sGoneError("watch: resourceVersion expired (410 Gone)", status=410)
            if response.status_code >= 400:
                # a 401 with an exec credential: invalidate so the watch
                # loop's normal backoff-reconnect re-runs the plugin
                self._handle_401(response)
                raise K8sApiError(
                    f"watch: HTTP {response.status_code}: {response.text[:300]}", status=response.status_code
                )
            if self._watch_aborted:
                # abort_watch() ran while we were connecting: there was no
                # response for it to close, so honor the abort here
                raise K8sApiError("watch aborted during connect")
            shard = parse_shard_selector(shard_selector) if shard_selector else None
            yield from self._decode_watch_stream(response, scanner, shard)
        except (requests.RequestException, urllib3.exceptions.HTTPError, OSError) as exc:
            # urllib3/socket errors surface directly on the raw-chunk fast
            # path (iter_lines would have wrapped them in requests types)
            raise K8sApiError(f"watch stream broken: {exc}") from exc
        except (AttributeError, ValueError) as exc:
            # abort_watch() closing the response mid-read surfaces as
            # AttributeError (fp=None) or ValueError (read on closed file)
            # from urllib3, not as a socket error. Only translate when an
            # abort was actually requested — otherwise these are real bugs
            # that must not be laundered into silent reconnects.
            if self._watch_aborted:
                raise K8sApiError(f"watch stream closed by abort: {exc}") from exc
            raise
        finally:
            self._active_watch_response = None
            if response is not None:
                response.close()

    # -- watch-stream decoding ---------------------------------------------

    @staticmethod
    def _parse_frame(line: bytes) -> Dict[str, Any]:
        try:
            event = json.loads(line)
        except json.JSONDecodeError as exc:
            raise K8sApiError(f"watch: malformed event frame: {line[:200]!r}") from exc
        if event.get("type") == "ERROR":
            obj = event.get("object") or {}
            if obj.get("code") == 410:
                raise K8sGoneError(f"watch: {obj.get('message', '410 Gone')}", status=410)
            raise K8sApiError(f"watch: server error event: {obj}", status=obj.get("code"))
        return event

    @staticmethod
    def _prefiltered_marker(resource_version: Optional[str], count: int = 1) -> Dict[str, Any]:
        """rv-only stand-in for ``count`` consecutive skipped frames (only
        the LAST resume version of a skipped run matters — rv is monotonic)."""
        return {
            "type": "PREFILTERED",
            "count": count,
            "object": {"metadata": {"resourceVersion": resource_version}},
        }

    def _decode_watch_stream(self, response, scanner, shard=None) -> Iterator[Dict[str, Any]]:
        """Turn the chunked HTTP body into watch events.

        Three paths, fastest first:
        - scanner with ``scan_chunk`` (native fastscan): whole received
          chunks are frame-split and scanned in one C call; skipped frames'
          bytes are never touched by the interpreter;
        - per-frame scanner: iter_lines + scan before parse;
        - no scanner: iter_lines + parse (reference-equivalent behavior).

        ``shard`` (``(i, n)``) adds the client-side shard ownership skip on
        BOTH scanner paths: a frame whose scanned uid hashes to another
        shard becomes an rv-only PREFILTERED marker without a JSON parse
        (the chunk path computes the verdict natively — crc32 in C). A
        frame with no extractable uid full-parses and is dropped by the
        watch source's post-parse ownership filter — correctness is always
        the source's filter; the scanner is only the fast path.
        """
        if scanner is None:
            for line in response.iter_lines():
                if line:
                    yield self._parse_frame(line)
            return

        # the raw-chunk path needs Transfer-Encoding: chunked (the real
        # apiserver always streams watches that way): urllib3 then yields
        # each transfer chunk as it lands. On a close-delimited body a
        # fixed-size read would block until the buffer fills, so fall back
        # to the per-frame path there.
        if getattr(scanner, "scan_chunk", None) is not None and getattr(
            response.raw, "chunked", False
        ):
            yield from decode_watch_chunks(
                response.raw.stream(64 * 1024, decode_content=True),
                scanner,
                shard,
            )
            return
        for line in response.iter_lines():
            if not line:
                continue
            scan = scanner.scan(line)
            if scan.skippable or (shard is not None and scan.foreign_shard(*shard)):
                yield self._prefiltered_marker(scan.resource_version)
            else:
                yield self._parse_frame(line)
