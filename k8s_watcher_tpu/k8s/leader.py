"""Lease-based leader election for the watcher singleton.

The watcher is a cluster-external singleton (ARCHITECTURE.md probe-plane
diagram); the reference ran exactly one process with no HA story — a crashed
watcher meant no notifications until something restarted it. This module lets
N replicas run with exactly one active: the standard Kubernetes leader
election protocol over ``coordination.k8s.io/v1`` Lease objects (the same
algorithm as client-go's ``leaderelection`` package, which kube-scheduler and
kube-controller-manager use):

- a candidate tries to create the Lease; on 409 someone else holds it;
- the holder renews ``renewTime`` every ``retry_period``;
- a non-holder acquires iff ``renewTime + leaseDurationSeconds`` has passed
  (the holder died without releasing) — optimistic concurrency via
  ``metadata.resourceVersion`` ensures only one stealer wins;
- a holder that cannot renew within ``renew_deadline`` steps down;
- a clean ``stop()`` releases the Lease (empty ``holderIdentity``) so
  standbys take over immediately instead of waiting out the lease.

Wall-clock caveat (same as client-go): expiry is judged by comparing the
OBSERVER's clock against the renewTime written by the holder, so it assumes
bounded clock skew between replicas; ``lease_duration`` must comfortably
exceed worst-case skew plus one renew period.
"""

from __future__ import annotations

import logging
import threading
import time
from datetime import datetime, timezone
from typing import Callable, Optional

from k8s_watcher_tpu.config.schema import leader_timing_error
from k8s_watcher_tpu.k8s.client import K8sApiError, K8sClient, K8sConflictError

logger = logging.getLogger(__name__)

_MICROTIME = "%Y-%m-%dT%H:%M:%S.%fZ"  # k8s metav1.MicroTime wire format


def _now() -> datetime:
    return datetime.now(timezone.utc)


def _format_time(dt: datetime) -> str:
    return dt.astimezone(timezone.utc).strftime(_MICROTIME)


def _parse_time(raw: Optional[str]) -> Optional[datetime]:
    if not raw:
        return None
    text = raw.strip().replace("z", "Z")
    if text.endswith("Z"):
        text = text[:-1] + "+00:00"
    try:
        return datetime.fromisoformat(text)
    except ValueError:
        return None


class LeaderElector:
    """Run-for-leadership state machine; owns one background thread.

    Callbacks fire on the elector thread: ``on_started_leading`` once per
    term, ``on_stopped_leading`` when a held leadership is lost or released.
    """

    def __init__(
        self,
        client: K8sClient,
        *,
        # IMPORTANT: give the elector a client whose request_timeout is well
        # under renew_deadline (see elector_client()). A renew RPC that can
        # block longer than the deadline would keep is_leader true past the
        # point a standby may legally steal the lease — split-brain.
        lease_namespace: str,
        lease_name: str,
        identity: str,
        lease_duration_seconds: float = 15.0,
        renew_deadline_seconds: float = 10.0,
        retry_period_seconds: float = 2.0,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
    ):
        error = leader_timing_error(lease_duration_seconds, renew_deadline_seconds, retry_period_seconds)
        if error:
            raise ValueError(error)
        self.client = client
        self.lease_namespace = lease_namespace
        self.lease_name = lease_name
        self.identity = identity
        self.lease_duration = lease_duration_seconds
        self.renew_deadline = renew_deadline_seconds
        self.retry_period = retry_period_seconds
        self._on_started = on_started_leading
        self._on_stopped = on_stopped_leading
        self._leader = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._observed_lease: Optional[dict] = None

    # -- public API --------------------------------------------------------

    @property
    def is_leader(self) -> bool:
        return self._leader.is_set()

    def start(self) -> "LeaderElector":
        self._thread = threading.Thread(target=self._run, name="leader-elector", daemon=True)
        self._thread.start()
        return self

    def wait_for_leadership(self, timeout: Optional[float] = None) -> bool:
        """Block until this instance leads (True) or timeout/stop (False)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self._stop.is_set():
            remaining = 0.2 if deadline is None else min(0.2, deadline - time.monotonic())
            if remaining <= 0:
                return self._leader.is_set()
            if self._leader.wait(timeout=remaining):
                return True
        return False

    def stop(self) -> None:
        """Stop campaigning; if leading, release the Lease for fast failover.

        Deliberate shutdown does NOT fire ``on_stopped_leading`` — the owner
        initiated it and a "lost leadership" reaction would be spurious."""
        self._stop.set()
        self._on_stopped = None
        if self._thread is not None:
            self._thread.join(timeout=self.retry_period * 2 + 2.0)
        if self._leader.is_set():
            self._release()
            self._set_leading(False)

    # -- state machine -----------------------------------------------------

    def _set_leading(self, leading: bool) -> None:
        was = self._leader.is_set()
        if leading and not was:
            self._leader.set()
            logger.info("Acquired leadership of %s/%s as %s", self.lease_namespace, self.lease_name, self.identity)
            if self._on_started:
                self._on_started()
        elif not leading and was:
            self._leader.clear()
            if self._stop.is_set():
                # deliberate shutdown, not an incident — keep WARNING-level
                # logs meaningful for alerting on real involuntary losses
                logger.info("Stepped down from leadership of %s/%s", self.lease_namespace, self.lease_name)
            else:
                logger.warning("Lost leadership of %s/%s", self.lease_namespace, self.lease_name)
            if self._on_stopped:
                self._on_stopped()

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._leader.is_set():
                # the local validity deadline is judged on the MONOTONIC
                # clock (client-go does the same): a wall-clock step must
                # not extend how long an unrenewed leader believes it still
                # leads, or two replicas could both act as leader
                renewed_at = time.monotonic()
                # renew until it fails past the deadline; the acquisition
                # write just happened, so the first renew waits a period
                while not self._stop.is_set():
                    if self._stop.wait(self.retry_period):
                        return
                    if self._try_acquire_or_renew():
                        renewed_at = time.monotonic()
                    elif time.monotonic() - renewed_at >= self.renew_deadline:
                        # involuntary loss: step down and RETIRE this elector
                        # (client-go's elector returns too). Re-campaigning
                        # here could re-take the lease while the owning app
                        # is mid-shutdown, blocking the healthy standby.
                        self._set_leading(False)
                        return
            else:
                if self._try_acquire_or_renew():
                    self._set_leading(True)
                    continue  # go straight into the renew loop
                if self._stop.wait(self.retry_period):
                    return

    def _spec(self, transitions: int, acquire_time: Optional[str] = None) -> dict:
        now = _format_time(_now())
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_duration),
            "acquireTime": acquire_time or now,
            "renewTime": now,
            "leaseTransitions": transitions,
        }

    def _try_acquire_or_renew(self) -> bool:
        """One protocol step; True iff we hold a freshly-renewed lease."""
        try:
            lease = self.client.get_lease(self.lease_namespace, self.lease_name)
            if self._stop.is_set():
                # stop() may already have released the lease while this
                # thread was blocked in the GET above — do not write, or a
                # half-dead elector would take the released lease back
                return False
            if lease is None:
                self._observed_lease = self.client.create_lease(
                    self.lease_namespace, self.lease_name, self._spec(transitions=0)
                )
                return True

            spec = lease.get("spec") or {}
            holder = spec.get("holderIdentity") or ""
            if holder and holder != self.identity:
                renew = _parse_time(spec.get("renewTime"))
                duration = float(spec.get("leaseDurationSeconds") or self.lease_duration)
                if renew is not None and (_now() - renew).total_seconds() < duration:
                    self._observed_lease = lease
                    return False  # held and fresh
                logger.info("Lease %s/%s held by %s is expired; attempting takeover",
                            self.lease_namespace, self.lease_name, holder)

            transitions = int(spec.get("leaseTransitions") or 0)
            if holder != self.identity:
                transitions += 1  # leadership changes hands
            acquire_time = spec.get("acquireTime") if holder == self.identity else None
            lease["spec"] = self._spec(transitions, acquire_time)
            # resourceVersion from the GET above makes this a compare-and-swap:
            # if another candidate stole it first, the PUT 409s and we yield
            self._observed_lease = self.client.replace_lease(self.lease_namespace, self.lease_name, lease)
            return True

        except K8sConflictError:
            return False  # raced another candidate; they won this round
        except Exception as exc:  # noqa: BLE001 — the elector thread must survive
            # any failure mode of the API path (malformed JSON from a proxy,
            # unexpected response shape, ...): a dead elector thread would
            # leave a standby that never leads and never alerts
            logger.warning("Leader election step failed: %s", exc)
            return False

    def _release(self) -> None:
        # retried on conflict: an in-flight renew PUT from the (possibly
        # still-draining) elector thread can land between our GET and PUT;
        # re-reading picks up its resourceVersion so the release still wins
        for _ in range(3):
            try:
                lease = self.client.get_lease(self.lease_namespace, self.lease_name)
                if lease is None or (lease.get("spec") or {}).get("holderIdentity") != self.identity:
                    return
                lease["spec"]["holderIdentity"] = ""
                lease["spec"]["renewTime"] = _format_time(_now())
                self.client.replace_lease(self.lease_namespace, self.lease_name, lease)
                logger.info("Released lease %s/%s", self.lease_namespace, self.lease_name)
                return
            except K8sConflictError:
                continue
            except K8sApiError as exc:
                logger.warning("Failed to release lease (standbys will wait out the term): %s", exc)
                return
        logger.warning("Failed to release lease after retries (standbys will wait out the term)")


def default_identity() -> str:
    import os
    import socket

    return f"{socket.gethostname()}-{os.getpid()}"


def elector_client(client: K8sClient, renew_deadline_seconds: float, lease_duration_seconds: float) -> K8sClient:
    """A dedicated lease client with a bounded per-RPC timeout.

    The watch client's request_timeout (30 s default) can exceed the renew
    deadline; a single stalled renew RPC would then pin the elector thread
    past the point a standby legally steals the lease, leaving two replicas
    both acting as leader. Bound each lease RPC so the deadline check always
    runs with margin before lease expiry (client-go bounds renews the same
    way).
    """
    timeout = max(1.0, min(renew_deadline_seconds / 2.0, (lease_duration_seconds - renew_deadline_seconds) / 2.0))
    return K8sClient(client.connection, request_timeout=min(timeout, client.request_timeout))
