"""In-process mock Kubernetes API server.

The reference's mock story (SURVEY.md §2.13, §4) pointed a bundled
kubeconfig at "a mock k8s API server at http://localhost:9988" — but the
server binary itself was never in the repo, so the mock tier could not
actually run. This module ships that server: a small threaded HTTP server
implementing the exact API subset ``K8sClient`` consumes:

- ``GET /version``
- ``GET /api/v1/namespaces``
- ``GET /api/v1/pods`` and ``GET /api/v1/namespaces/{ns}/pods``
  (list, and ``watch=true`` streaming with resourceVersion resume,
  equality-based ``labelSelector``, BOOKMARK frames on idle when
  ``allowWatchBookmarks`` is set, and 410-Gone on expired versions)

Test hooks: ``MockCluster.add/modify/delete_pod`` drive the event stream;
``compact()`` expires old resourceVersions to exercise the relist path;
``fail_next(n)`` injects transient HTTP 500s to exercise backoff.

The server also exposes the clusterapi NOTIFY surface (``GET /health``,
``POST /api/pods/update`` and the batched ``POST /api/pods/update_batch``
— payloads land in ``MockCluster.status_updates``), so egress-plane
integration tests drive the real ``ClusterApiClient`` against it without
a second server implementation.
"""

from __future__ import annotations

import base64
import bisect
import json
import threading
import time
from http.server import BaseHTTPRequestHandler

from k8s_watcher_tpu.metrics.server import QuietThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse


def _decode_continue(token: Optional[str]) -> Tuple[Optional[str], Tuple[str, str]]:
    """``(snapshot_rv, after_key)`` from an opaque continue token; raises
    ValueError for any malformed shape (the caller maps it to 400)."""
    if not token:
        return None, ("", "")
    try:
        decoded = json.loads(base64.b64decode(token.encode()).decode())
        # validate the full shape HERE: a decodable token with a non-int
        # rv or non-string keys must 400, not 500 later
        snapshot_rv = str(int(decoded["rv"]))
        after = (decoded["ns"], decoded["name"])
        if not (isinstance(after[0], str) and isinstance(after[1], str)):
            raise TypeError("cursor keys must be strings")
    except (ValueError, KeyError, TypeError) as exc:
        raise ValueError(f"malformed continue token: {exc}") from exc
    return snapshot_rv, after


def _encode_continue(rv: int, ns: str, name: str) -> str:
    return base64.b64encode(
        json.dumps({"rv": rv, "ns": ns, "name": name}).encode()
    ).decode()


def _expired_continue_status() -> Tuple[int, Dict[str, Any]]:
    return 410, {
        "kind": "Status", "code": 410, "reason": "Expired",
        "message": "The provided continue parameter is too old",
    }


def _parse_label_selector(selector: Optional[str]) -> List[Tuple[str, Optional[str]]]:
    """Equality-based selector subset: ``k=v``, ``k==v``, bare ``k``."""
    out: List[Tuple[str, Optional[str]]] = []
    for part in (selector or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "==" in part:
            k, v = part.split("==", 1)
        elif "=" in part:
            k, v = part.split("=", 1)
        else:
            k, v = part, None
        out.append((k.strip(), v.strip() if v is not None else None))
    return out


def _matches_selector(pod: Dict[str, Any], selector: List[Tuple[str, Optional[str]]]) -> bool:
    labels = (pod.get("metadata") or {}).get("labels") or {}
    for key, value in selector:
        if key not in labels:
            return False
        if value is not None and labels[key] != value:
            return False
    return True


def _parse_shard(param: Optional[str]) -> Optional[Tuple[int, int]]:
    """``(shard, shards)`` from the ``shard=i/n`` query param the sharded
    ingest sends (watch/sharded.py wire format), or None. A malformed
    selector is IGNORED (None), matching a stock apiserver's treatment of
    unknown/garbage query params — the client's ownership filter keeps
    correctness either way."""
    if not param:
        return None
    from k8s_watcher_tpu.watch.sharded import parse_shard_selector

    return parse_shard_selector(param)


def _matches_shard(obj: Dict[str, Any], shard: Optional[Tuple[int, int]]) -> bool:
    """Server-side shard push-down: uid-hash partition, the same
    ``shard_of`` the client uses (the whole point is that both sides
    compute the identical stable partition)."""
    if shard is None:
        return True
    from k8s_watcher_tpu.watch.sharded import shard_of

    uid = (obj.get("metadata") or {}).get("uid") or ""
    return shard_of(uid, shard[1]) == shard[0]


class _PreserializedList(dict):
    """A list-response body whose items are already JSON text.

    ``_Handler._json`` splices ``items_json`` into the encoded body
    instead of re-serializing every object: the per-object JSON is built
    (and cached) once on the cluster side — the mock's analogue of the
    real apiserver's serialized watch cache. Without it a paged LIST
    deep-copied and double-encoded every pod per page, and at 10k+ pods
    the MOCK dominated the relist benches this server exists to serve.

    Direct in-process consumers (tests calling ``cluster.list_pods``
    without HTTP) still read ``body["items"]``: the list materializes
    lazily from the cached text on first access — same decoupled-copy
    guarantee the old per-object deep copy gave.
    """

    def __getitem__(self, key):
        if key == "items" and not dict.__contains__(self, "items"):
            dict.__setitem__(
                self, "items", [json.loads(t) for t in dict.__getitem__(self, "items_json")]
            )
        return dict.__getitem__(self, key)

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def encode(self) -> bytes:
        items_json = self.pop("items_json")
        self.pop("items", None)  # drop any lazily materialized copy
        head = json.dumps(self)
        return (head[:-1] + ',"items":[' + ",".join(items_json) + "]}").encode()


class MockCluster:
    """Shared cluster state + event journal."""

    def __init__(self):
        self._lock = threading.Condition()
        self._rv = 0
        self._pods: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._nodes: Dict[str, Dict[str, Any]] = {}
        # per-collection event journal as PARALLEL rv/event arrays; one
        # cluster-global rv space, like the real apiserver, so each
        # collection's rv list is strictly increasing and a watch poll
        # resumes by BISECT — O(log n + results) per poll, not the
        # O(whole-journal) list-comprehension rescan every long-poll
        # round used to pay (at 10k-pod churn each 0.25 s wakeup walked
        # every event ever journaled)
        self._journal_rvs: Dict[str, List[int]] = {}
        self._journal_events: Dict[str, List[Dict[str, Any]]] = {}
        self._oldest_rv = 0  # journal entries <= this are compacted away
        self._fail_next = 0
        self._fail_status = 500
        # hold_watch: events with rv above this stay invisible to
        # events_since until released (None = delivering normally)
        self._watch_hold_rv: Optional[int] = None
        self.namespaces = ["default", "kube-system"]
        self._leases: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # sorted-key cache per collection, keyed on the rv it was built
        # at: any mutation bumps _rv, invalidating it. Without this every
        # page re-sorted and re-filtered the WHOLE map — O(n^2/page_size)
        # across a paged list, 22 s for a 50k-pod relist
        self._sorted_keys: Dict[str, Tuple[int, list]] = {}
        # per-pod serialized-JSON cache (key -> (rv, json_text)), the
        # mock's analogue of the apiserver's serialized watch cache:
        # LIST pages splice cached text instead of deep-copy + re-encode
        # per pod per page. rv-validated, entries dropped on delete.
        self._pod_json: Dict[Tuple[str, str], Tuple[str, str]] = {}
        # per-shard sorted-key partition, keyed on (collection, shards)
        # and the rv it was built at. Without it every sharded LIST page
        # rescanned the WHOLE key space computing a crc32 per pod to find
        # its 1/n matches — O(shards x n_pods x pages) of GIL-bound work
        # that made a 4-shard concurrent relist SLOWER than one serial
        # page chain (bench r06: shard_speedup 0.6)
        self._shard_keys: Dict[Tuple[str, int], Tuple[int, List[list]]] = {}
        # clusterapi-surface test hook: status updates POSTed to
        # /api/pods/update[_batch] (the mock doubles as a notify target so
        # the egress plane can be integration-tested without a second
        # server implementation)
        self.status_updates: List[Dict[str, Any]] = []

    def _sorted_collection_keys(self, collection: str, mapping) -> list:
        """Sorted key list for ``mapping``, cached until the next
        mutation. Call under ``self._lock``."""
        cached = self._sorted_keys.get(collection)
        if cached is not None and cached[0] == self._rv:
            return cached[1]
        keys = sorted(mapping)
        self._sorted_keys[collection] = (self._rv, keys)
        return keys

    def _shard_partition_keys(
        self, collection: str, mapping, shard: int, shards: int
    ) -> list:
        """Shard ``shard``'s sorted key list under the uid-hash partition,
        cached until the next mutation — one O(n) crc32 sweep per (rv,
        shard count) instead of one per scanned key per page. Call under
        ``self._lock``."""
        cached = self._shard_keys.get((collection, shards))
        if cached is None or cached[0] != self._rv:
            from k8s_watcher_tpu.watch.sharded import shard_of

            parts: List[list] = [[] for _ in range(shards)]
            for key in self._sorted_collection_keys(collection, mapping):
                obj = mapping.get(key)
                uid = ((obj or {}).get("metadata") or {}).get("uid") or ""
                parts[shard_of(uid, shards)].append(key)
            cached = (self._rv, parts)
            self._shard_keys[(collection, shards)] = cached
        return cached[1][shard]

    def _cursor_page(self, collection: str, mapping, after, limit, match, keys=None) -> list:
        """Cursor scan shared by the paged LISTs: up to ``limit+1``
        (key, obj) pairs with key > ``after`` satisfying ``match(key,
        obj)`` (limit+1 so _page_body can detect "more remain"). Call
        under ``self._lock``. ``keys``: pre-restricted sorted key list
        (shard partitions); defaults to the whole collection."""
        if keys is None:
            keys = self._sorted_collection_keys(collection, mapping)
        want = limit + 1 if limit else None
        matches = []
        for key in keys[bisect.bisect_right(keys, after):]:
            obj = mapping.get(key)
            if obj is None:
                # deleted since the cache was built: delete_* pops the map
                # and bumps _rv in two separate lock holds, so a list
                # landing between them sees a momentarily-stale cache
                continue
            if not match(key, obj):
                continue
            matches.append((key, obj))
            if want is not None and len(matches) >= want:
                break
        return matches

    # -- state mutation (test hooks) --------------------------------------

    def _record(self, event_type: str, obj: Dict[str, Any], collection: str = "pods") -> int:
        with self._lock:
            self._rv += 1
            obj.setdefault("metadata", {})["resourceVersion"] = str(self._rv)
            self._journal_rvs.setdefault(collection, []).append(self._rv)
            self._journal_events.setdefault(collection, []).append(
                {"type": event_type, "object": json.loads(json.dumps(obj))}
            )
            self._lock.notify_all()
            return self._rv

    def add_pod(self, pod: Dict[str, Any]) -> int:
        meta = pod.get("metadata") or {}
        key = (meta.get("namespace", "default"), meta.get("name", ""))
        with self._lock:
            self._pods[key] = pod
        return self._record("ADDED", pod)

    def modify_pod(self, pod: Dict[str, Any]) -> int:
        meta = pod.get("metadata") or {}
        key = (meta.get("namespace", "default"), meta.get("name", ""))
        with self._lock:
            self._pods[key] = pod
        return self._record("MODIFIED", pod)

    def delete_pod(self, namespace: str, name: str) -> Optional[int]:
        key = (namespace, name)
        with self._lock:
            pod = self._pods.pop(key, None)
            self._pod_json.pop(key, None)
        if pod is None:
            return None
        return self._record("DELETED", pod)

    # -- REST write surface (K8sClient.create_pod/delete_pod/...) ----------
    # The test hooks above mutate state directly; these enforce the
    # apiserver's status contract (201/409/404) so the acceptance write
    # tier can drive REAL create/delete churn through HTTP on hosts
    # without Docker/kind.

    def create_pod(self, namespace: str, pod: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        meta = pod.setdefault("metadata", {})
        meta["namespace"] = namespace
        name = meta.get("name", "")
        if not name:
            return 400, {"kind": "Status", "code": 400, "message": "pod has no name"}
        pod.setdefault("status", {}).setdefault("phase", "Pending")
        # uniqueness check + insert + response snapshot under ONE lock hold
        # (the Condition's RLock is re-entrant, so the nested add_pod/_record
        # acquisitions are fine) — a check-then-insert window would let two
        # concurrent POSTs both 201 and journal a phantom duplicate ADDED,
        # and serializing the live stored dict outside the lock would race a
        # concurrent set_phase/modify_pod mutating it mid-iteration
        with self._lock:
            if namespace not in self.namespaces:
                # parity with the real apiserver: pods can't land in a
                # namespace that doesn't exist (or was just deleted)
                return 404, {"kind": "Status", "code": 404, "message": f"namespaces \"{namespace}\" not found"}
            if (namespace, name) in self._pods:
                return 409, {"kind": "Status", "code": 409, "message": f"pods \"{name}\" already exists"}
            self.add_pod(pod)
            return 201, json.loads(json.dumps(pod))

    def remove_pod(self, namespace: str, name: str) -> Tuple[int, Dict[str, Any]]:
        rv = self.delete_pod(namespace, name)
        if rv is None:
            return 404, {"kind": "Status", "code": 404, "message": f"pods \"{name}\" not found"}
        return 200, {"kind": "Status", "code": 200, "status": "Success"}

    def create_namespace(self, name: str) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            if name in self.namespaces:
                return 409, {"kind": "Status", "code": 409, "message": f"namespaces \"{name}\" already exists"}
            self.namespaces.append(name)
        return 201, {"kind": "Namespace", "metadata": {"name": name}}

    def delete_namespace(self, name: str) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            if name not in self.namespaces:
                return 404, {"kind": "Status", "code": 404, "message": f"namespaces \"{name}\" not found"}
            self.namespaces.remove(name)
            # evict under the SAME lock hold (re-entrant): a create racing
            # the delete must either land before the eviction sweep or be
            # rejected by create_pod's namespace check — never orphaned.
            # DELETED events flow to watchers, like the apiserver's cascade
            for ns, pod_name in [key for key in self._pods if key[0] == name]:
                self.delete_pod(ns, pod_name)
        return 200, {"kind": "Status", "code": 200, "status": "Success"}

    def set_phase(self, namespace: str, name: str, phase: str) -> Optional[int]:
        with self._lock:
            pod = self._pods.get((namespace, name))
            if pod is None:
                return None
            pod.setdefault("status", {})["phase"] = phase
        return self._record("MODIFIED", pod)

    # -- node state (the nodes collection mirrors the pods hooks) ----------

    def add_node(self, node: Dict[str, Any]) -> int:
        name = (node.get("metadata") or {}).get("name", "")
        with self._lock:
            self._nodes[name] = node
        return self._record("ADDED", node, collection="nodes")

    def modify_node(self, node: Dict[str, Any]) -> int:
        name = (node.get("metadata") or {}).get("name", "")
        with self._lock:
            self._nodes[name] = node
        return self._record("MODIFIED", node, collection="nodes")

    def delete_node(self, name: str) -> Optional[int]:
        with self._lock:
            node = self._nodes.pop(name, None)
            self._pod_json.pop(("", name), None)  # node cache key (ns "")
        if node is None:
            return None
        return self._record("DELETED", node, collection="nodes")

    def set_node_ready(self, name: str, ready: bool, reason: str = "") -> Optional[int]:
        """Flip the node's Ready condition (the kubelet-heartbeat signal)."""
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                return None
            conditions = node.setdefault("status", {}).setdefault("conditions", [])
            for c in conditions:
                if c.get("type") == "Ready":
                    c["status"] = "True" if ready else "False"
                    c["reason"] = reason or ("KubeletReady" if ready else "KubeletNotReady")
                    break
            else:
                conditions.append({
                    "type": "Ready",
                    "status": "True" if ready else "False",
                    "reason": reason or ("KubeletReady" if ready else "KubeletNotReady"),
                })
        return self._record("MODIFIED", node, collection="nodes")

    def get_node(self, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            node = self._nodes.get(name)
            return json.loads(json.dumps(node)) if node else None

    @staticmethod
    def _merge_patch(target: Dict[str, Any], patch: Dict[str, Any]) -> Dict[str, Any]:
        """RFC 7386 JSON merge patch: dicts merge recursively, ``null``
        deletes a key, everything else (including lists) replaces."""
        for key, value in patch.items():
            if value is None:
                target.pop(key, None)
            elif isinstance(value, dict) and isinstance(target.get(key), dict):
                MockCluster._merge_patch(target[key], value)
            else:
                target[key] = json.loads(json.dumps(value))
        return target

    def patch_node(self, name: str, patch: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        """(status, body) for ``PATCH /api/v1/nodes/{name}`` with
        merge-patch semantics; journals a MODIFIED node event, so the
        node-plane watch observes cordons the remediation plane applies.

        A patch carrying ``metadata.resourceVersion`` is an optimistic-
        concurrency write (same apiserver contract the lease path honors):
        stale rv -> 409 Conflict, so read-modify-write callers (the
        remediation actuator's taint edits) can detect a concurrent editor
        instead of clobbering it."""
        with self._lock:
            node = self._nodes.get(name)
            if node is None:
                return 404, {"kind": "Status", "code": 404, "message": f"nodes \"{name}\" not found"}
            sent_rv = (patch.get("metadata") or {}).get("resourceVersion")
            if sent_rv is not None and sent_rv != node["metadata"]["resourceVersion"]:
                return 409, {
                    "kind": "Status", "code": 409,
                    "message": f"Operation cannot be fulfilled on nodes \"{name}\": "
                               "the object has been modified",
                }
            # the server owns resourceVersion: never merge a client-sent one
            patch = json.loads(json.dumps(patch))
            if "metadata" in patch and isinstance(patch["metadata"], dict):
                patch["metadata"].pop("resourceVersion", None)
            self._merge_patch(node, patch)
            self.modify_node(node)
            return 200, json.loads(json.dumps(node))

    def list_nodes(
        self,
        label_selector: Optional[str] = None,
        limit: Optional[int] = None,
        continue_token: Optional[str] = None,
    ) -> Tuple[int, Dict[str, Any]]:
        """(status, body) for ``GET /api/v1/nodes`` with the same
        limit+continue contract as ``list_pods`` (node keys have no
        namespace; the cursor's ns field stays "")."""
        selector = _parse_label_selector(label_selector)
        try:
            snapshot_rv, after = _decode_continue(continue_token)
        except ValueError:
            return 400, {"kind": "Status", "code": 400, "message": "malformed continue token"}
        with self._lock:
            if snapshot_rv is not None and int(snapshot_rv) < self._oldest_rv:
                return _expired_continue_status()
            if after[0]:
                # node tokens encode ns "" — a foreign-namespace cursor
                # sorts above every ("", name) key, i.e. no results
                # (tuple-compare behavior of the pre-cache implementation)
                return 200, self._page_body("NodeList", [], limit, snapshot_rv)
            matches = [
                (("", name), node)
                for name, node in self._cursor_page(
                    "nodes", self._nodes, after[1], limit,
                    lambda _name, node: _matches_selector(node, selector),
                )
            ]
            return 200, self._page_body("NodeList", matches, limit, snapshot_rv)

    def _serialized(self, key: Tuple[str, str], obj: Dict[str, Any]) -> str:
        """Cached JSON text for one object (the dumps IS the under-lock
        snapshot a deep copy used to provide). Call under ``self._lock``."""
        rv = str((obj.get("metadata") or {}).get("resourceVersion", ""))
        cached = self._pod_json.get(key)
        if cached is not None and cached[0] == rv:
            return cached[1]
        text = json.dumps(obj)
        self._pod_json[key] = (rv, text)
        return text

    def _page_body(
        self,
        kind: str,
        matches: list,
        limit: Optional[int],
        snapshot_rv: Optional[str],
    ) -> Dict[str, Any]:
        """One page + metadata (rv pinned to the list's snapshot, continue
        token when more remain). Call under ``self._lock``. The returned
        body carries pre-serialized items (see ``_PreserializedList``)."""
        rv = snapshot_rv if snapshot_rv is not None else str(self._rv)
        next_token = None
        if limit and len(matches) > limit:
            matches = matches[:limit]
            last_ns, last_name = matches[-1][0]
            next_token = _encode_continue(int(rv), last_ns, last_name)
        metadata: Dict[str, Any] = {"resourceVersion": rv}
        if next_token:
            metadata["continue"] = next_token
        return _PreserializedList(
            kind=kind,
            apiVersion="v1",
            metadata=metadata,
            items_json=[self._serialized(key, obj) for key, obj in matches],
        )

    def compact(self) -> None:
        """Forget journal history: any watch resuming below the current rv
        gets 410 Gone (simulates apiserver etcd compaction)."""
        with self._lock:
            self._oldest_rv = self._rv
            self._journal_rvs.clear()
            self._journal_events.clear()

    def fail_next(self, n: int = 1, status: int = 500) -> None:
        """Make the next ``n`` HTTP requests fail with ``status``
        (backoff and auth-retry tests)."""
        with self._lock:
            self._fail_next = n
            self._fail_status = status

    def hold_watch(self, hold: bool = True) -> None:
        """Freeze watch delivery at the CURRENT rv: state keeps mutating
        (rv advances, LISTs serve fresh pages) but ``events_since`` stops
        returning anything newer until released — the "lagging apiserver"
        fault (a wedged/backed-up watch cache) the health-plane chaos
        drill scripts. Releasing notifies every parked watcher, so the
        held window floods out at once, exactly like a real cache
        catching up."""
        with self._lock:
            self._watch_hold_rv = self._rv if hold else None
            if not hold:
                self._lock.notify_all()

    def consume_failure(self) -> int:
        """The injected failure status for this request, or 0 for none."""
        with self._lock:
            if self._fail_next > 0:
                self._fail_next -= 1
                return self._fail_status
            return 0

    # -- reads -------------------------------------------------------------

    def list_pods(
        self,
        namespace: Optional[str],
        limit: Optional[int],
        label_selector: Optional[str] = None,
        continue_token: Optional[str] = None,
        shard: Optional[str] = None,
    ) -> Tuple[int, Dict[str, Any]]:
        """(status, body) for ``GET .../pods`` with ``limit``+``continue``
        pagination (the apiserver contract the paged client consumes):

        - every page of one list reports the resourceVersion of the
          snapshot the list STARTED at (the client's watch-resume point),
          not the rv at page-serve time;
        - ``metadata.continue`` is an opaque cursor (snapshot rv + last
          key served); compaction past that rv expires it -> 410 Gone,
          exactly how etcd compaction expires real continue tokens.

        Pages after the first are served from the CURRENT pod map at the
        cursor key — the mock doesn't retain historical snapshots — which
        matches the observable client contract: anything that changes
        between pages is journaled at rv > snapshot and arrives via the
        resumed watch."""
        selector = _parse_label_selector(label_selector)
        shard_sel = _parse_shard(shard)
        try:
            snapshot_rv, after = _decode_continue(continue_token)
        except ValueError:
            return 400, {"kind": "Status", "code": 400, "message": "malformed continue token"}
        with self._lock:
            if snapshot_rv is not None and int(snapshot_rv) < self._oldest_rv:
                return _expired_continue_status()
            shard_keys = None
            if shard_sel is not None:
                # pre-partitioned key list: the scan touches only this
                # shard's pods, no per-key hash (see _shard_partition_keys)
                shard_keys = self._shard_partition_keys(
                    "pods", self._pods, shard_sel[0], shard_sel[1]
                )
            matches = self._cursor_page(
                "pods", self._pods, after, limit,
                lambda key, pod: (namespace is None or key[0] == namespace)
                and _matches_selector(pod, selector),
                keys=shard_keys,
            )
            return 200, self._page_body("PodList", matches, limit, snapshot_rv)

    # -- clusterapi notify surface (egress-plane integration target) -------

    def record_status_update(self, payload: Dict[str, Any]) -> bool:
        """Accept one ``update_pod_status`` POST (clusterapi contract).
        Always succeeds; the payload lands in ``status_updates`` for
        assertions."""
        with self._lock:
            self.status_updates.append(payload)
        return True

    def record_status_updates(self, payloads: List[Any]) -> List[bool]:
        """Accept one ``update_pod_statuses`` batch POST; per-item results
        (a non-dict item is rejected, the rest of the batch still lands —
        the per-item result list is the point of the batch wire shape)."""
        results = []
        with self._lock:
            for payload in payloads:
                if isinstance(payload, dict):
                    self.status_updates.append(payload)
                    results.append(True)
                else:
                    results.append(False)
        return results

    def events_since(self, rv: int, deadline: float, collection: str = "pods") -> Optional[List[Dict[str, Any]]]:
        """Block until there are journal events > rv in ``collection`` or the
        deadline passes. Returns None if rv has been compacted away (client
        must relist)."""
        with self._lock:
            while True:
                if rv < self._oldest_rv:
                    return None  # compacted (possibly while we were waiting)
                rvs = self._journal_rvs.get(collection)
                if rvs:
                    # the collection's rv list is strictly increasing
                    # (appends under the cluster-global rv), so the resume
                    # point is a bisect and the batch is one tail slice
                    idx = bisect.bisect_right(rvs, rv)
                    end = len(rvs)
                    if self._watch_hold_rv is not None:
                        # lagging-apiserver fault: deliver nothing past
                        # the hold point (see hold_watch)
                        end = bisect.bisect_right(rvs, self._watch_hold_rv)
                    if idx < end:
                        return self._journal_events[collection][idx:end]
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._lock.wait(timeout=min(remaining, 0.25))

    def latest_rv(self) -> int:
        with self._lock:
            return self._rv

    # -- coordination.k8s.io/v1 Leases (leader election) -------------------

    def get_lease(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            lease = self._leases.get((namespace, name))
            return json.loads(json.dumps(lease)) if lease else None

    def create_lease(self, namespace: str, name: str, body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        """(status, body): 201 on create, 409 if the Lease already exists."""
        with self._lock:
            if (namespace, name) in self._leases:
                return 409, {"kind": "Status", "code": 409, "message": f"leases \"{name}\" already exists"}
            self._rv += 1
            lease = json.loads(json.dumps(body))
            lease.setdefault("metadata", {}).update(
                {"name": name, "namespace": namespace, "resourceVersion": str(self._rv)}
            )
            self._leases[(namespace, name)] = lease
            return 201, json.loads(json.dumps(lease))

    def replace_lease(self, namespace: str, name: str, body: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        """(status, body): 200 on replace, 404 if missing, 409 on a stale
        metadata.resourceVersion (optimistic-concurrency contract — this is
        what makes leader-election takeover a compare-and-swap)."""
        with self._lock:
            current = self._leases.get((namespace, name))
            if current is None:
                return 404, {"kind": "Status", "code": 404, "message": f"leases \"{name}\" not found"}
            sent_rv = (body.get("metadata") or {}).get("resourceVersion")
            if sent_rv != current["metadata"]["resourceVersion"]:
                return 409, {"kind": "Status", "code": 409, "message": "the object has been modified"}
            self._rv += 1
            lease = json.loads(json.dumps(body))
            lease.setdefault("metadata", {}).update(
                {"name": name, "namespace": namespace, "resourceVersion": str(self._rv)}
            )
            self._leases[(namespace, name)] = lease
            return 200, json.loads(json.dumps(lease))


def _parse_lease_path(path: str) -> Optional[Tuple[str, Optional[str]]]:
    """``(namespace, name-or-None)`` for coordination/v1 lease routes."""
    prefix = "/apis/coordination.k8s.io/v1/namespaces/"
    if not path.startswith(prefix):
        return None
    rest = path[len(prefix):].split("/")
    if len(rest) == 2 and rest[1] == "leases":
        return rest[0], None
    if len(rest) == 3 and rest[1] == "leases" and rest[2]:
        return rest[0], rest[2]
    return None


# sentinel distinguishing "limit was malformed, 400 already sent" from a
# legitimately absent limit (None)
_BAD_LIMIT = object()


class _Handler(BaseHTTPRequestHandler):
    # HTTP/1.1 with Transfer-Encoding: chunked on the watch stream — the
    # real kube-apiserver's framing, which is also what lets clients see
    # each event the moment its chunk arrives (a close-delimited body would
    # make fixed-size reads block until the buffer fills or the watch ends).
    protocol_version = "HTTP/1.1"
    # Nagle + delayed-ACK would add ~40 ms to every streamed watch frame
    disable_nagle_algorithm = True
    cluster: MockCluster  # injected by MockApiServer
    server_ref = None  # the owning MockApiServer, for header recording

    def log_message(self, fmt, *args):  # silence default stderr spam
        pass

    def parse_request(self):
        ok = super().parse_request()
        if ok and self.server_ref is not None:
            self.server_ref.request_headers.append(
                {"Authorization": self.headers.get("Authorization"), "path": self.path}
            )
        return ok

    def _parse_limit(self, params: Dict[str, str]):
        """``limit`` as int, None when absent, or ``_BAD_LIMIT`` after
        responding 400 — a non-integer limit gets the same Status body a
        malformed continue token does, not a 500 traceback."""
        if "limit" not in params:
            return None
        try:
            limit = int(params["limit"])
        except ValueError:
            self._json(400, {"kind": "Status", "code": 400, "message": "malformed limit"})
            return _BAD_LIMIT
        if limit < 0:
            # a negative limit would slice matches[:limit] empty and then
            # IndexError building the continue token — same 400 contract
            self._json(400, {"kind": "Status", "code": 400, "message": "malformed limit"})
            return _BAD_LIMIT
        return limit

    def _json(self, status: int, body: Dict[str, Any]) -> None:
        data = body.encode() if isinstance(body, _PreserializedList) else json.dumps(body).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (stdlib naming)
        fail = self.cluster.consume_failure()
        if fail:
            self._json(fail, {"kind": "Status", "code": fail, "message": "injected failure"})
            return

        parsed = urlparse(self.path)
        params = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        path = parsed.path

        if path == "/version":
            self._json(200, {"major": "1", "minor": "31", "gitVersion": "v1.31.0-mock"})
            return
        if path == "/health":
            # clusterapi-surface health endpoint (ClusterApiClient.health_check)
            self._json(200, {"ok": True})
            return
        if path == "/api/v1/namespaces":
            items = [{"metadata": {"name": ns}} for ns in self.cluster.namespaces]
            self._json(200, {"kind": "NamespaceList", "items": items})
            return

        lease = _parse_lease_path(path)
        if lease is not None:
            namespace, name = lease
            if name is None:
                self._json(400, {"kind": "Status", "code": 400, "message": "lease collection GET not supported"})
                return
            found = self.cluster.get_lease(namespace, name)
            if found is None:
                self._json(404, {"kind": "Status", "code": 404, "message": f"leases \"{name}\" not found"})
            else:
                self._json(200, found)
            return

        if path == "/api/v1/nodes":
            if params.get("watch") == "true":
                self._serve_watch(None, params, collection="nodes")
            else:
                limit = self._parse_limit(params)
                if limit is _BAD_LIMIT:
                    return
                status, body = self.cluster.list_nodes(
                    params.get("labelSelector"), limit, params.get("continue")
                )
                self._json(status, body)
            return
        if path.startswith("/api/v1/nodes/"):
            name = path[len("/api/v1/nodes/"):]
            node = self.cluster.get_node(name)
            if node is None:
                self._json(404, {"kind": "Status", "code": 404, "message": f"nodes \"{name}\" not found"})
            else:
                self._json(200, node)
            return

        namespace: Optional[str] = None
        if path == "/api/v1/pods":
            pass
        elif path.startswith("/api/v1/namespaces/") and path.endswith("/pods"):
            namespace = path[len("/api/v1/namespaces/"):-len("/pods")]
        else:
            self._json(404, {"kind": "Status", "code": 404, "message": f"no route {path}"})
            return

        if params.get("watch") == "true":
            self._serve_watch(namespace, params)
        else:
            limit = self._parse_limit(params)
            if limit is _BAD_LIMIT:
                return
            status, body = self.cluster.list_pods(
                namespace, limit, params.get("labelSelector"), params.get("continue"),
                shard=params.get("shard"),
            )
            self._json(status, body)

    def _read_body(self) -> Optional[Dict[str, Any]]:
        try:
            length = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, json.JSONDecodeError):
            self._json(400, {"kind": "Status", "code": 400, "message": "malformed request body"})
            return None

    def do_POST(self):  # noqa: N802 (stdlib naming)
        # read the body BEFORE any early response: unread body bytes would
        # be parsed as the next request line on this keep-alive connection
        body = self._read_body()
        if body is None:
            return
        fail = self.cluster.consume_failure()
        if fail:
            self._json(fail, {"kind": "Status", "code": fail, "message": "injected failure"})
            return
        path = urlparse(self.path).path
        if path == "/api/pods/update":
            # clusterapi notify surface: one status-update payload
            self.cluster.record_status_update(body)
            self._json(200, {"ok": True})
            return
        if path == "/api/pods/update_batch":
            # batched notify (ClusterApiClient.update_pod_statuses wire
            # shape); malformed batch envelope (non-dict body included)
            # -> 400, per-item verdicts ride back in "results"
            updates = body.get("updates") if isinstance(body, dict) else None
            if not isinstance(updates, list):
                self._json(400, {"kind": "Status", "code": 400, "message": "updates must be a list"})
                return
            self._json(200, {"results": self.cluster.record_status_updates(updates)})
            return
        lease = _parse_lease_path(path)
        if lease is not None and lease[1] is None:  # POST to the collection creates
            namespace = lease[0]
            name = (body.get("metadata") or {}).get("name", "")
            status, out = self.cluster.create_lease(namespace, name, body)
            self._json(status, out)
            return
        if path == "/api/v1/namespaces":
            status, out = self.cluster.create_namespace((body.get("metadata") or {}).get("name", ""))
            self._json(status, out)
            return
        if path.startswith("/api/v1/namespaces/") and path.endswith("/pods"):
            namespace = path[len("/api/v1/namespaces/"):-len("/pods")]
            status, out = self.cluster.create_pod(namespace, body)
            self._json(status, out)
            return
        self._json(404, {"kind": "Status", "code": 404, "message": f"no route {self.path}"})

    def do_DELETE(self):  # noqa: N802 (stdlib naming)
        fail = self.cluster.consume_failure()
        if fail:
            self._json(fail, {"kind": "Status", "code": fail, "message": "injected failure"})
            return
        path = urlparse(self.path).path
        parts = path.strip("/").split("/")
        # /api/v1/namespaces/{ns}/pods/{name}
        if len(parts) == 6 and parts[:2] == ["api", "v1"] and parts[2] == "namespaces" and parts[4] == "pods":
            status, out = self.cluster.remove_pod(parts[3], parts[5])
            self._json(status, out)
            return
        # /api/v1/namespaces/{name}
        if len(parts) == 4 and parts[:3] == ["api", "v1", "namespaces"]:
            status, out = self.cluster.delete_namespace(parts[3])
            self._json(status, out)
            return
        self._json(404, {"kind": "Status", "code": 404, "message": f"no route {self.path}"})

    def do_PATCH(self):  # noqa: N802 (stdlib naming)
        body = self._read_body()
        if body is None:
            return
        fail = self.cluster.consume_failure()
        if fail:
            self._json(fail, {"kind": "Status", "code": fail, "message": "injected failure"})
            return
        path = urlparse(self.path).path
        if path.startswith("/api/v1/nodes/"):
            status, out = self.cluster.patch_node(path[len("/api/v1/nodes/"):], body)
            self._json(status, out)
            return
        self._json(404, {"kind": "Status", "code": 404, "message": f"no route {self.path}"})

    def do_PUT(self):  # noqa: N802 (stdlib naming)
        body = self._read_body()
        if body is None:
            return
        fail = self.cluster.consume_failure()
        if fail:
            self._json(fail, {"kind": "Status", "code": fail, "message": "injected failure"})
            return
        lease = _parse_lease_path(urlparse(self.path).path)
        if lease is not None and lease[1] is not None:
            namespace, name = lease
            status, out = self.cluster.replace_lease(namespace, name, body)
            self._json(status, out)
            return
        self._json(404, {"kind": "Status", "code": 404, "message": f"no route {self.path}"})

    def _serve_watch(self, namespace: Optional[str], params: Dict[str, str], collection: str = "pods") -> None:
        try:
            rv = int(params.get("resourceVersion", "0") or "0")
        except ValueError:
            rv = 0
        timeout_s = min(int(params.get("timeoutSeconds", "30") or "30"), 300)
        deadline = time.monotonic() + timeout_s
        selector = _parse_label_selector(params.get("labelSelector"))
        shard_sel = _parse_shard(params.get("shard")) if collection == "pods" else None
        send_bookmarks = params.get("allowWatchBookmarks") == "true"
        last_frame = time.monotonic()

        first = self.cluster.events_since(rv, time.monotonic(), collection)  # non-blocking compaction check
        if first is None:
            self._json(410, {"kind": "Status", "code": 410, "message": "too old resource version"})
            return

        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

        def write_frame(payload: Dict[str, Any]) -> None:
            data = (json.dumps(payload) + "\n").encode()
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            self.wfile.flush()

        try:
            while time.monotonic() < deadline:
                batch = self.cluster.events_since(rv, min(deadline, time.monotonic() + 0.5), collection)
                if batch is None:
                    # compacted mid-stream: emit the in-band 410 ERROR event
                    write_frame({"type": "ERROR", "object": {"kind": "Status", "code": 410, "message": "too old resource version"}})
                    break
                if not batch and send_bookmarks and time.monotonic() - last_frame >= 1.0:
                    # idle stream: k8s sends BOOKMARK frames so clients can
                    # advance their resume version without real events. Use
                    # the handler-local rv (not latest_rv()): an event
                    # recorded in the race window must not be marked seen
                    # before it is delivered.
                    write_frame({
                        "type": "BOOKMARK",
                        "object": {"kind": "Pod", "metadata": {"resourceVersion": str(rv)}},
                    })
                    last_frame = time.monotonic()
                for event in batch:
                    obj = event.get("object") or {}
                    obj_ns = (obj.get("metadata") or {}).get("namespace")
                    erv = int((obj.get("metadata") or {}).get("resourceVersion", "0"))
                    rv = max(rv, erv)
                    if namespace is not None and obj_ns != namespace:
                        continue
                    if selector and not _matches_selector(obj, selector):
                        continue
                    if not _matches_shard(obj, shard_sel):
                        continue
                    write_frame(event)
                    last_frame = time.monotonic()
            # terminal chunk: clean end of the bounded watch window
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass


class MockApiServer:
    """Owns the HTTP server thread; use as a context manager in tests."""

    def __init__(self, cluster: Optional[MockCluster] = None, host: str = "127.0.0.1", port: int = 0):
        self.cluster = cluster or MockCluster()
        # auth-relevant headers per request, for credential-plumbing tests
        self.request_headers: List[Dict[str, Optional[str]]] = []
        handler = type(
            "BoundHandler", (_Handler,), {"cluster": self.cluster, "server_ref": self}
        )
        self._server = QuietThreadingHTTPServer((host, port), handler)
        self._server.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "MockApiServer":
        self._thread = threading.Thread(target=self._server.serve_forever, name="mock-k8s-api", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=2.0)

    def __enter__(self) -> "MockApiServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
