"""Resilient list+watch source.

The reference's loop died on any stream error (pod_watcher.py:273-275 —
re-raise, no reconnect, no resume; SURVEY.md §2 defect #4). This source
delivers the capability its dead retry config promised:

- initial LIST synthesizes ADDED events for existing pods (the same
  observable behavior as the SDK's list+watch at pod_watcher.py:264), then
  WATCH resumes from the list's resourceVersion;
- every event advances the resume version; BOOKMARK events keep it fresh
  on quiet streams;
- stream errors reconnect with exponential backoff (config-driven,
  ``watcher.retry``);
- 410 Gone triggers a full relist; the phase tracker downstream dedupes the
  re-ADDED pods so subscribers see no spurious transitions;
- an optional checkpoint store persists the resume version across restarts
  (SURVEY.md §5 checkpoint/resume — ABSENT in the reference).
"""

from __future__ import annotations

import logging
import threading
from typing import Iterator, Optional

from k8s_watcher_tpu.config.schema import RetryPolicy
from k8s_watcher_tpu.k8s.client import K8sApiError, K8sClient, K8sGoneError
from k8s_watcher_tpu.state.dirty import DirtyKeys
from k8s_watcher_tpu.watch.sharded import shard_of
from k8s_watcher_tpu.watch.source import EventType, WatchEvent

logger = logging.getLogger(__name__)


class KubernetesWatchSource:
    def __init__(
        self,
        client: K8sClient,
        *,
        namespace: Optional[str] = None,
        label_selector: Optional[str] = None,
        retry: Optional[RetryPolicy] = None,
        watch_timeout_seconds: int = 300,
        resource_version: Optional[str] = None,
        checkpoint=None,  # state.checkpoint.CheckpointStore, optional
        max_reconnects: Optional[int] = None,  # None = retry forever
        heartbeat=None,  # Callable[[], None]: stamped on any apiserver contact
        scanner=None,  # native.scanner.FrameScanner: skip-parse prefilter
        metrics=None,  # metrics.MetricsRegistry, optional
        list_page_size: int = 500,  # LIST pagination (limit+continue)
        shard: int = 0,  # this stream's shard index (uid-hash partition)
        shards: int = 1,  # total shard streams; 1 = whole cluster
    ):
        if not 0 <= shard < shards:
            raise ValueError(f"shard {shard} out of range for {shards} shards")
        self.client = client
        self.namespace = namespace
        self.label_selector = label_selector
        self.list_page_size = list_page_size
        self.retry = retry or RetryPolicy()
        self.watch_timeout_seconds = watch_timeout_seconds
        self.resource_version = resource_version
        self.checkpoint = checkpoint
        self.max_reconnects = max_reconnects
        self.heartbeat = heartbeat or (lambda: None)
        self.scanner = scanner
        self.metrics = metrics
        self.shard = shard
        self.shards = shards
        # pushed to the server (mock apiserver / shard-aware proxy honor
        # it; a stock apiserver ignores it and the client-side ownership
        # checks below keep the partition correct)
        self.shard_selector = f"{shard}/{shards}" if shards > 1 else None
        self._stop = threading.Event()
        # uid -> pod SKELETON of live pods, so a relist can synthesize
        # DELETED events for pods that vanished while the watch was
        # disconnected (a plain relist only re-ADDs survivors, which would
        # leak dead members in downstream phase/slice trackers). The
        # skeleton keeps labels/annotations/nodeName/container resources —
        # a bare {name, namespace} tombstone would be DROPPED by the
        # accelerator resource filter and carry no slice identity, so the
        # slice tracker could never remove the member (the leak this map
        # exists to prevent, resurfacing one stage downstream). Restored
        # from the checkpoint so tombstones survive restarts that land past
        # the apiserver's compaction window.
        self._known: dict = {}
        # uids whose _known entry changed since the last drain — the
        # checkpoint's delta hint (JournaledMapStore), so a steady-state
        # flush journals only the churn instead of rewriting the whole
        # map. Entries restored from the checkpoint are NOT dirty: they
        # are already on disk. Bounded (state/dirty.py): collapses to
        # "everything changed" instead of growing forever when no
        # checkpoint ever drains it.
        self._dirty = DirtyKeys()
        if checkpoint is not None:
            for uid, entry in (checkpoint.get("known_pods") or {}).items():
                if shards > 1 and shard_of(uid, shards) != shard:
                    # not ours: a ShardCheckpointView pre-filters, but a raw
                    # store handed to a shard source must not make this
                    # shard tombstone the other shards' pods after restart
                    continue
                if isinstance(entry, dict):
                    self._known[uid] = entry
                    continue
                if not isinstance(entry, (list, tuple)):
                    # garbage entry (null/number/string from a foreign
                    # writer — strings would iterate into characters): a
                    # corrupt checkpoint degrades, never crashes or invents
                    logger.warning("Discarding malformed known_pods entry for uid %s", uid)
                    continue
                # pre-skeleton checkpoint format: [name, namespace, phase];
                # pad positionally so a truncated entry gets the RIGHT
                # defaults for the missing fields
                defaults = ["", "default", "Unknown"]
                entry = list(entry)[:3]
                name, namespace, phase = entry + defaults[len(entry):]
                self._known[uid] = {
                    "metadata": {"name": name, "namespace": namespace, "uid": uid},
                    "spec": {},
                    "status": {"phase": phase},
                    # no resource spec exists to reconstruct, so the
                    # eventual tombstone must be flagged past the
                    # accelerator filter. Stored IN the entry so it
                    # survives checkpoint round-trips across further
                    # restarts; unspoofable because _skeleton builds
                    # entries from fixed keys only — pod content can never
                    # plant a top-level key here. Cleared naturally when a
                    # relist replaces the entry with a fresh skeleton.
                    "legacy_tombstone": True,
                }

    # annotation values this long are blobs (kubectl's
    # last-applied-configuration can be the whole manifest) — skeletons
    # exist for identity, and every tracked pod's skeleton lands in the
    # checkpoint JSON on each flush, so bound them
    _SKELETON_ANNOTATION_MAX = 256

    @classmethod
    def _skeleton(cls, pod: dict) -> dict:
        """The minimal pod that downstream stages treat like the original:
        identity + labels/annotations (slice identity inference), node
        placement, container resources (accelerator filter — init
        containers included, same as the filter itself), and phase."""
        meta = pod.get("metadata") or {}
        spec = pod.get("spec") or {}
        # resourceVersion rides along so _track can prove "unchanged
        # object" on the next relist and skip the rebuild + dirty churn
        skel_meta = {
            k: meta[k]
            for k in ("name", "namespace", "uid", "labels", "resourceVersion")
            if meta.get(k)
        }
        annotations = {
            k: v for k, v in (meta.get("annotations") or {}).items()
            if isinstance(v, str) and len(v) <= cls._SKELETON_ANNOTATION_MAX
        }
        if annotations:
            skel_meta["annotations"] = annotations
        skel_spec: dict = {
            k: spec[k] for k in ("nodeName", "nodeSelector") if spec.get(k)
        }
        for field in ("containers", "initContainers"):
            kept = [
                {"name": c.get("name", ""), "resources": c["resources"]}
                for c in (spec.get(field) or [])
                if c.get("resources")
            ]
            if kept:
                skel_spec[field] = kept
        return {
            "metadata": skel_meta,
            "spec": skel_spec,
            "status": {"phase": (pod.get("status") or {}).get("phase", "Unknown")},
        }

    def known_pods(self) -> dict:
        """JSON-serializable live-pod skeleton map for the checkpoint.

        A SHALLOW copy is sound only because entries are never mutated in
        place after insertion — ``_track`` replaces whole entries and
        ``_relist`` strips the legacy flag from a copy. Keep it that way:
        a throttled CheckpointStore may hold this snapshot (and its shared
        inner dicts) until a later flush."""
        return dict(self._known)

    def drain_dirty_uids(self) -> Optional[set]:
        """Uids whose entry changed since the last drain (incl. deletes),
        or None for "unknown — persist everything"; clears the
        accumulator. Call BEFORE ``known_pods()``: a change landing
        between the drain and the snapshot journals its newer value this
        flush AND stays marked for the next — never the reverse order,
        where a change after the snapshot would be drained away while its
        value never made it to disk."""
        return self._dirty.drain()

    def stop(self) -> None:
        self._stop.set()
        # wake a consumer blocked in the stream read: on a quiet cluster the
        # next frame could be minutes away, far past any SIGTERM grace period
        self.client.abort_watch()

    # -- internals ---------------------------------------------------------

    def _save_rv(self, rv: Optional[str]) -> None:
        if rv:
            self.resource_version = rv
            if self.checkpoint is not None:
                self.checkpoint.update_resource_version(rv)

    def _track(self, event_type: str, pod: dict) -> None:
        meta = pod.get("metadata") or {}
        uid = meta.get("uid")
        if not uid:
            return
        if event_type == EventType.DELETED:
            self._known.pop(uid, None)
        else:
            rv = meta.get("resourceVersion")
            prev = self._known.get(uid)
            if (
                prev is not None
                and rv
                and (prev.get("metadata") or {}).get("resourceVersion") == rv
            ):
                # same object version we already track (the dominant case
                # across a relist — most pods didn't change during the
                # disconnect): identical skeleton, so skip the rebuild AND
                # the dirty mark. Before this, every relist marked every
                # uid dirty and forced a whole-map checkpoint compaction.
                return
            self._known[uid] = self._skeleton(pod)
        self._dirty.mark(uid, len(self._known))

    def _relist(self) -> Iterator[WatchEvent]:
        """LIST current pods: ADDED for each, synthetic DELETED for pods
        that vanished during the disconnect gap, then set the resume version.

        The LIST is paged (``limit``+``continue``, page size
        ``list_page_size``) so a relist of a large cluster streams bounded
        responses instead of one unbounded PodList — each page's events are
        yielded before the next page is fetched, so peak memory is one page
        plus the skeleton map. Tombstone synthesis runs only after the LAST
        page: only then is "absent from the list" meaningful. When an
        expired continue token forces the paged client to restart (new
        snapshot, new rv), the listed-uid set resets with it — a union
        across two snapshots would suppress tombstones for pods that
        vanished between them (re-ADDs of pods from the aborted attempt
        are harmless: downstream phase tracking dedupes, same as any
        relist)."""
        rv = None
        listed_uids: set = set()
        shards = self.shards
        for page_rv, items, restarted in K8sClient.iter_list_pages(
            self.client.list_pods_paged(
                self.namespace,
                page_size=self.list_page_size,
                label_selector=self.label_selector,
                shard_selector=self.shard_selector,
            ),
            metrics=self.metrics,
            # overlap the next page's fetch+decode with this page's
            # skeleton tracking/yields — relist wall time becomes
            # max(fetch, process) per page, not their sum. Sharded
            # streams prefetch too (round 7): each chain's synchronous
            # request->decode->track loop otherwise stalls a GIL-switch
            # interval per page handoff, and with N concurrent chains
            # those bubbles convoy — measured as sharded relist running
            # SLOWER than one serial chain (r06 shard_speedup 0.6); the
            # in-flight page per chain hides the handoff inside decode
            prefetch=True,
        ):
            if self._stop.is_set():
                # shutdown mid-pagination: abort WITHOUT the tombstone
                # sweep or rv save below — synthesizing DELETED for every
                # not-yet-listed pod would be wrong, and the partial list
                # must not become the resume point. Bounds shutdown at one
                # in-flight page request instead of the whole relist.
                return
            if restarted:
                listed_uids.clear()
            rv = page_rv or rv
            for pod in items:
                uid = (pod.get("metadata") or {}).get("uid")
                if shards > 1 and shard_of(uid or "", shards) != self.shard:
                    # server ignored the shard selector (stock apiserver):
                    # this shard must neither track nor emit pods another
                    # shard owns — the ownership filter IS the partition
                    continue
                listed_uids.add(uid)
                self._track(EventType.ADDED, pod)
                yield WatchEvent(type=EventType.ADDED, pod=pod, resource_version=rv)
        for uid in [u for u in self._known if u not in listed_uids]:
            tombstone = self._known.pop(uid)
            self._dirty.mark(uid, len(self._known))
            legacy = bool(tombstone.get("legacy_tombstone", False))
            if legacy:
                # strip the marker from a COPY — a pending throttled
                # checkpoint snapshot (known_pods() is a shallow copy) may
                # still reference this entry, and popping in place would
                # persist it flag-less: after a crash the restart would
                # re-synthesize this DELETED without the flag, the
                # accelerator filter would drop it, and the pod would leak
                # in the phase/slice trackers — the exact leak the flag
                # exists to prevent
                tombstone = {k: v for k, v in tombstone.items() if k != "legacy_tombstone"}
            meta = tombstone.get("metadata") or {}
            logger.info(
                "Relist: pod %s/%s vanished during disconnect; emitting DELETED",
                meta.get("namespace", "default"), meta.get("name", ""),
            )
            yield WatchEvent(
                type=EventType.DELETED, pod=tombstone, resource_version=rv,
                legacy_tombstone=legacy,
            )
        self._save_rv(rv)

    def events(self) -> Iterator[WatchEvent]:
        """Yield events forever (until ``stop()``), reconnecting as needed."""
        backoff = self.retry.delay_seconds
        reconnects = 0
        # consecutive watch-phase 410s with no delivered frame or clean
        # window expiry in between: the first is normal recovery (relist
        # immediately), repeats mean the relist itself keeps outlasting
        # the watch cache — those must back off and count, or the loop
        # degenerates into unbounded back-to-back full-cluster LISTs
        gone_streak = 0

        if self.resource_version is None and self.checkpoint is not None:
            self.resource_version = self.checkpoint.resource_version()
            if self.resource_version:
                logger.info("Resuming watch from checkpointed resourceVersion %s", self.resource_version)

        need_list = self.resource_version is None

        def backoff_or_raise(exc, what: str) -> bool:
            """Count one failure against max_reconnects (raising ``exc`` on
            exhaustion), back off, and return True when stop() interrupted
            the wait."""
            nonlocal backoff, reconnects
            reconnects += 1
            if self.max_reconnects is not None and reconnects > self.max_reconnects:
                logger.error("%s failed after %d attempts: %s", what, reconnects - 1, exc)
                raise exc
            logger.warning(
                "%s error (%s); retrying in %.1fs (attempt %d)", what, exc, backoff, reconnects
            )
            stopped = self._stop.wait(backoff)
            backoff = min(backoff * self.retry.backoff_multiplier, self.retry.max_delay_seconds)
            return stopped

        while not self._stop.is_set():
            # The LIST phase has its OWN handlers, outside the watch try
            # below: a K8sGoneError escaping the paged LIST means the
            # continue tokens kept expiring max_restarts times (churning
            # cluster) — letting the watch-phase 410 handler catch it
            # would relist IMMEDIATELY in a tight full-LIST loop against
            # an already-stressed apiserver, and nothing would ever bound
            # it. Both list failure modes back off and count against
            # max_reconnects instead.
            if need_list:
                try:
                    yield from self._relist()
                    if self._stop.is_set():
                        return
                    need_list = False
                    self.heartbeat()
                    # a completed relist is proof of a healthy apiserver:
                    # transient blips must not accumulate across days into
                    # max_reconnects exhaustion (or a forever-escalated
                    # backoff) on an otherwise-recovering stream
                    backoff = self.retry.delay_seconds
                    reconnects = 0
                except (K8sGoneError, K8sApiError) as exc:
                    if self._stop.is_set():
                        return
                    what = (
                        "Paged LIST (continue tokens kept expiring)"
                        if getattr(exc, "token_expiry", False)
                        else "LIST"
                    )
                    if backoff_or_raise(exc, what):
                        return
                    continue

            try:
                for raw in self.client.watch_pods(
                    self.namespace,
                    resource_version=self.resource_version,
                    timeout_seconds=self.watch_timeout_seconds,
                    label_selector=self.label_selector,
                    scanner=self.scanner,
                    shard_selector=self.shard_selector,
                ):
                    if self._stop.is_set():
                        return
                    self.heartbeat()  # any frame (incl. bookmarks) = live apiserver link
                    obj = raw.get("object") or {}
                    meta = obj.get("metadata") or {}
                    rv = meta.get("resourceVersion")
                    event_type = raw.get("type", "")
                    if event_type == EventType.BOOKMARK or event_type == EventType.PREFILTERED:
                        # rv-only frames: bookmarks, and frames the native
                        # prefilter dropped unparsed (no accelerator key —
                        # the pipeline's resource filter would drop them too;
                        # one marker may stand for a coalesced run of them)
                        if event_type == EventType.PREFILTERED and self.metrics is not None:
                            self.metrics.counter("events_prefiltered").inc(raw.get("count", 1))
                        # a delivered frame proves the stream is healthy — in
                        # an all-non-TPU cluster these may be the ONLY frames,
                        # so backoff must reset here too or one blip escalates
                        # every later reconnect to max_delay forever
                        backoff = self.retry.delay_seconds
                        reconnects = 0
                        gone_streak = 0
                        self._save_rv(rv)
                        continue
                    if (
                        self.shards > 1
                        and shard_of(meta.get("uid") or "", self.shards) != self.shard
                    ):
                        # another shard's pod reached us (stock apiserver
                        # ignored the shard selector and the scanner could
                        # not skip it pre-parse): rv-only treatment, same
                        # as a prefiltered frame — the resume point must
                        # still advance or a quiet shard would replay these
                        if self.metrics is not None:
                            self.metrics.counter("events_other_shard").inc()
                        backoff = self.retry.delay_seconds
                        reconnects = 0
                        gone_streak = 0
                        self._save_rv(rv)
                        continue
                    event = WatchEvent(type=event_type, pod=obj, resource_version=rv)
                    self._track(event_type, obj)
                    backoff = self.retry.delay_seconds  # healthy stream resets backoff
                    reconnects = 0
                    gone_streak = 0
                    yield event
                    # checkpoint only after the consumer processed the event
                    # (generator resumes here on next()) — a crash mid-event
                    # then replays it instead of silently skipping it
                    self._save_rv(rv)
                # bounded watch expired normally -> reconnect immediately.
                # Surviving a whole window (even frameless — the bookmark
                # hint is advisory and a fully-prefiltered stream can be
                # silent) proves both the link and the resume rv, so it
                # resets the same counters a delivered frame does:
                # otherwise unrelated blips accumulate across days into
                # max_reconnects exhaustion on a healthy quiet cluster
                self.heartbeat()
                backoff = self.retry.delay_seconds
                reconnects = 0
                gone_streak = 0
                logger.debug("Watch window expired; reconnecting from rv=%s", self.resource_version)

            except K8sGoneError as exc:
                logger.warning("resourceVersion %s expired (410 Gone); relisting", self.resource_version)
                self.resource_version = None
                need_list = True
                gone_streak += 1
                if gone_streak > 1:
                    # relist -> watch 410 -> relist with nothing healthy in
                    # between: the relist keeps outlasting the watch cache.
                    # Its OWN escalation and bound (the shared counters
                    # deliberately reset on every successful relist, which
                    # this cycle contains by construction) — without them
                    # this loop would hammer full-cluster LISTs forever.
                    if (
                        self.max_reconnects is not None
                        and gone_streak - 1 > self.max_reconnects
                    ):
                        logger.error(
                            "Watch 410d immediately after %d consecutive relists; giving up",
                            gone_streak,
                        )
                        raise
                    delay = min(
                        self.retry.delay_seconds
                        * self.retry.backoff_multiplier ** (gone_streak - 2),
                        self.retry.max_delay_seconds,
                    )
                    logger.warning(
                        "Watch 410d again right after a relist (streak %d); backing off %.1fs",
                        gone_streak, delay,
                    )
                    if self._stop.wait(delay):
                        return

            except K8sApiError as exc:
                if self._stop.is_set():
                    # the abort_watch() teardown path surfaces as a stream
                    # error; a clean shutdown must not log a scary
                    # "reconnecting" warning on every SIGTERM
                    return
                if backoff_or_raise(exc, "Watch stream"):
                    return
