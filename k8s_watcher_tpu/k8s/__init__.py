"""Native Kubernetes API layer.

The reference depended on the ``kubernetes`` Python SDK (requirements.txt:1)
for kubeconfig loading, the CoreV1 client, and the watch stream
(pod_watcher.py:110-157, 264). This framework implements that surface
natively over HTTP (``requests``): a minimal kubeconfig/in-cluster loader,
a REST client for the few endpoints the watcher needs, and a resilient
list+watch source with resourceVersion resume, exponential backoff and
410-Gone relist — the capability the reference's dead retry config promised
but never delivered (SURVEY.md §2 defect #4).
"""

from k8s_watcher_tpu.k8s.kubeconfig import K8sConnection, load_connection  # noqa: F401
from k8s_watcher_tpu.k8s.client import K8sApiError, K8sClient  # noqa: F401
from k8s_watcher_tpu.k8s.watch import KubernetesWatchSource  # noqa: F401
