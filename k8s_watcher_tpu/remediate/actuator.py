"""Node actuator: the write half of the remediation plane.

The probe plane *detects* faults and maps them to nodes (probe/links.py
suspect triangulation + probe/device.py host identity); the RUNBOOK tells a
human to drain. This module closes that loop for the cases that are safe to
automate: **quarantine** a suspect node by cordoning it
(``spec.unschedulable``) and applying a NoSchedule taint, so the scheduler
stops placing new TPU workloads there while the operator investigates. It
deliberately does NOT evict running pods (no NoExecute by default, no drain)
— killing a live training job is a human decision.

Every destructive capability is fenced:

- **dry-run by default**: the actuator logs, audits, and notifies exactly
  what it would do, without touching the cluster — the recommended first
  deployment mode, and what ``config/production.yaml`` ships with;
- **per-node cooldown**: one action per node per ``cooldown_seconds``;
- **global rate limit**: at most ``max_actions_per_hour`` real actions in
  any sliding hour, counting both cordons and releases;
- **quarantine budget**: never more than ``max_quarantined_nodes``
  simultaneously quarantined BY US — a policy bug (or a fabric-wide event
  that makes every node look suspect) must not cordon a whole pool. Nodes
  found already carrying our taint (e.g. applied before a watcher restart)
  count against the budget.

The reference has no counterpart (its notify path was read-only and
disabled, SURVEY.md §2.8); this is net-new TPU-ops capability.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from typing import Any, Deque, Dict, List, Optional

from k8s_watcher_tpu.config.schema import VALID_TAINT_EFFECTS
from k8s_watcher_tpu.k8s.client import (
    K8sApiError,
    K8sClient,
    K8sConflictError,
    K8sNotFoundError,
)

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class ActionRecord:
    """One quarantine/release decision — applied, simulated, or refused."""

    node: str
    action: str  # "quarantine" | "release"
    ok: bool  # the action was applied (or would be, in dry-run)
    dry_run: bool
    reason: str  # why the policy asked for it / why the actuator refused
    applied: bool = False  # a real PATCH landed on the apiserver
    adopted: bool = False  # node was already quarantined; nothing written
    error: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class NodeActuator:
    """Cordon + taint suspect nodes, inside hard safety fences."""

    def __init__(
        self,
        client,
        *,
        dry_run: bool = True,
        cordon: bool = True,
        taint_key: str = "k8s-watcher-tpu/ici-fault",
        taint_value: str = "suspect",
        taint_effect: str = "NoSchedule",
        cooldown_seconds: float = 3600.0,
        max_actions_per_hour: int = 4,
        max_quarantined_nodes: int = 2,
        metrics=None,
        clock=time.monotonic,
    ):
        if taint_effect not in VALID_TAINT_EFFECTS:
            raise ValueError(f"taint_effect must be one of {VALID_TAINT_EFFECTS}, got {taint_effect!r}")
        self.client = client
        self.dry_run = dry_run
        self.cordon = cordon
        self.taint_key = taint_key
        self.taint_value = taint_value
        self.taint_effect = taint_effect
        self.cooldown_seconds = cooldown_seconds
        self.max_actions_per_hour = max_actions_per_hour
        self.max_quarantined_nodes = max_quarantined_nodes
        self.metrics = metrics
        self._clock = clock
        self._lock = threading.Lock()
        self._last_action: Dict[str, float] = {}  # node -> last action ts
        self._action_times: Deque[float] = collections.deque()
        self._quarantined: set = set()  # nodes quarantined by us (this process)

    # -- fences ------------------------------------------------------------

    def _refuse(self, node: str, action: str, reason: str) -> ActionRecord:
        logger.warning("Remediation refused for node %s (%s): %s", node, action, reason)
        if self.metrics is not None:
            self.metrics.counter("remediation_refusals").inc()
        return ActionRecord(node=node, action=action, ok=False, dry_run=self.dry_run, reason=reason)

    _BUDGET_REFUSAL = "quarantine budget exhausted"
    _ADOPT_PAGE_SIZE = 500  # adoption taint-scan LIST page size

    def _reconcile_quarantined(self) -> None:
        """Drop budget entries that no longer hold, so the budget reflects
        reality rather than this process's memory. Called only when the
        budget is about to refuse — the slow path.

        Real mode: an operator releasing a node out-of-band
        (``remediate_ctl.py release``, or plain ``kubectl uncordon`` +
        ``kubectl taint ... -``) removes our taint on the apiserver; a GET
        per remembered node notices and frees the slot — otherwise external
        releases would never free budget and the actuator would refuse
        forever after ``max_quarantined_nodes`` lifetime quarantines.
        The GETs run OUTSIDE the lock (each can take a full request
        timeout; holding the lock through them would block every other
        decision, /debug snapshot, and notify path for their duration) —
        membership is snapshotted first and expirations re-intersected
        against the live set when applied.

        Dry-run mode: nothing was ever written, so there is no cluster
        state to consult; decisions age out after ``cooldown_seconds`` so a
        week of review-mode traffic keeps showing fresh would-quarantine
        decisions instead of degenerating into budget refusals.
        """
        with self._lock:
            members = list(self._quarantined)
            if self.dry_run:
                now = self._clock()
                expired = {
                    n for n in members
                    if now - self._last_action.get(n, now) >= self.cooldown_seconds
                }
                if expired:
                    logger.info(
                        "Remediation budget reconciled: %s aged out (dry-run)", sorted(expired)
                    )
                    self._quarantined -= expired
                return
        expired = set()
        for n in members:  # network I/O — deliberately outside the lock
            try:
                spec = (self.client.get_node(n) or {}).get("spec") or {}
            except K8sNotFoundError:
                expired.add(n)  # the node itself is gone
                continue
            except K8sApiError:
                continue  # can't verify: keep the conservative entry
            if not any(t.get("key") == self.taint_key for t in spec.get("taints") or []):
                expired.add(n)
        if expired:
            logger.info("Remediation budget reconciled: %s no longer quarantined", sorted(expired))
            with self._lock:
                self._quarantined -= expired

    def _fence_check(self, node: str, action: str) -> Optional[str]:
        """The refusal reason, or None when the action may proceed.
        Call with the lock held."""
        now = self._clock()
        last = self._last_action.get(node)
        if last is not None and now - last < self.cooldown_seconds:
            return (
                f"cooldown: last action on {node} was {now - last:.0f}s ago "
                f"(cooldown {self.cooldown_seconds:.0f}s)"
            )
        while self._action_times and self._action_times[0] <= now - 3600.0:
            self._action_times.popleft()
        if len(self._action_times) >= self.max_actions_per_hour:
            return f"rate limit: {len(self._action_times)} actions in the last hour (max {self.max_actions_per_hour})"
        if action == "quarantine" and node not in self._quarantined and len(self._quarantined) >= self.max_quarantined_nodes:
            return (
                f"{self._BUDGET_REFUSAL}: {sorted(self._quarantined)} already "
                f"quarantined (max {self.max_quarantined_nodes}) — a fleet-wide "
                "signal needs a human, not more cordons"
            )
        return None

    def _consume(self, node: str) -> float:
        """Record an allowed action against the fences (lock held);
        returns the timestamp recorded, for exact refund."""
        now = self._clock()
        self._last_action[node] = now
        self._action_times.append(now)
        return now

    def _drop_rate_slot_locked(self, consumed_ts: float) -> None:
        """Remove exactly the rate-window entry recorded by this call's
        `_consume` (lock held) — popping the tail instead could evict a
        DIFFERENT in-flight action's timestamp under concurrency, leaving
        the older one in the sliding-hour window and skewing accounting."""
        try:
            self._action_times.remove(consumed_ts)
        except ValueError:
            pass  # already expired out of the hour window

    def _refund_locked(self, node: str, prior_last_action: Optional[float], consumed_ts: float) -> None:
        """Undo one `_consume` (lock held): a transient GET/PATCH failure
        must not burn the fences — a consumed cooldown would lock a
        CONFIRMED-faulty node out of remediation for cooldown_seconds over
        an apiserver blip, and a burned rate slot would starve retries."""
        if prior_last_action is None:
            self._last_action.pop(node, None)
        else:
            self._last_action[node] = prior_last_action
        self._drop_rate_slot_locked(consumed_ts)

    # -- actions -----------------------------------------------------------

    def _our_taint(self) -> Dict[str, str]:
        return {"key": self.taint_key, "value": self.taint_value, "effect": self.taint_effect}

    def quarantine(self, node: str, reason: str) -> ActionRecord:
        """Cordon + taint ``node``; returns what happened and why.

        Idempotent: a node already carrying our taint (and cordoned, when
        cordoning is on) reports ok without a write — and is adopted into
        the budget set, so pre-restart quarantines still count against
        ``max_quarantined_nodes``.
        """
        def check_and_consume():
            """Atomically pass the fences and consume them; returns
            ``(refusal, prior_last_action, consumed_ts, was_quarantined)``."""
            with self._lock:
                refusal = self._fence_check(node, "quarantine")
                if refusal:
                    return refusal, None, 0.0, False
                # consume fences inside the lock; the PATCH itself runs
                # outside (a slow apiserver must not serialize every other
                # decision)
                prior = self._last_action.get(node)
                was = node in self._quarantined
                ts = self._consume(node)
                self._quarantined.add(node)
                return None, prior, ts, was

        refusal, prior_last_action, consumed_ts, was_quarantined = check_and_consume()
        if refusal is not None and refusal.startswith(self._BUDGET_REFUSAL):
            # the budget may be stale (out-of-band releases, aged dry-run
            # decisions): reconcile against reality — outside any lock —
            # and re-run the fences once
            self._reconcile_quarantined()
            refusal, prior_last_action, consumed_ts, was_quarantined = check_and_consume()
        if refusal is not None:
            return self._refuse(node, "quarantine", refusal)
        record = self._apply_quarantine(node, reason)
        with self._lock:
            if not record.ok:
                # Only evict the node from the budget if THIS call added it
                # — a failed re-quarantine of a node that is already
                # genuinely cordoned must keep occupying its slot
                if not was_quarantined:
                    self._quarantined.discard(node)
                self._refund_locked(node, prior_last_action, consumed_ts)
            elif record.adopted:
                # adoption wrote nothing: refund the hourly rate slot so
                # no-op confirmations can't starve real actions (the
                # per-node cooldown stays consumed — it is what stops the
                # policy re-GETting the node every probe cycle)
                self._drop_rate_slot_locked(consumed_ts)
            n_quarantined = len(self._quarantined)
        if self.metrics is not None and record.ok:
            if not record.adopted:  # adoption wrote nothing — not an action
                self.metrics.counter("remediation_actions").inc()
            self.metrics.gauge("remediation_quarantined_nodes").set(n_quarantined)
        return record

    # Taint edits are read-modify-write over the WHOLE spec.taints list (a
    # JSON merge-patch replaces the list wholesale), so every write carries
    # the read's metadata.resourceVersion — the apiserver rejects a stale
    # write with 409 instead of silently clobbering a taint another
    # controller added between our GET and PATCH — and the RMW retries on
    # conflict with a fresh read.
    _RMW_ATTEMPTS = 3

    def _apply_quarantine(self, node: str, reason: str) -> ActionRecord:
        for attempt in range(self._RMW_ATTEMPTS):
            try:
                current = self.client.get_node(node)
            except K8sNotFoundError:
                return ActionRecord(
                    node=node, action="quarantine", ok=False, dry_run=self.dry_run,
                    reason=reason, error=f"node {node} not found",
                )
            except K8sApiError as exc:
                return ActionRecord(
                    node=node, action="quarantine", ok=False, dry_run=self.dry_run,
                    reason=reason, error=f"get_node failed: {exc}",
                )
            spec = current.get("spec") or {}
            taints: List[Dict[str, Any]] = list(spec.get("taints") or [])
            have_taint = any(t.get("key") == self.taint_key for t in taints)
            cordoned = bool(spec.get("unschedulable"))
            if have_taint and (cordoned or not self.cordon):
                logger.info("Node %s already quarantined (adopting): %s", node, reason)
                return ActionRecord(
                    node=node, action="quarantine", ok=True, dry_run=self.dry_run,
                    reason=f"already quarantined; {reason}", adopted=True,
                )
            if not have_taint:
                taints.append(self._our_taint())
            patch: Dict[str, Any] = {"spec": {"taints": taints}}
            rv = (current.get("metadata") or {}).get("resourceVersion")
            if rv:
                patch["metadata"] = {"resourceVersion": rv}
            if self.cordon:
                patch["spec"]["unschedulable"] = True
            if self.dry_run:
                logger.warning(
                    "[DRY-RUN] would quarantine node %s (cordon=%s, taint %s=%s:%s): %s",
                    node, self.cordon, self.taint_key, self.taint_value, self.taint_effect, reason,
                )
                return ActionRecord(node=node, action="quarantine", ok=True, dry_run=True, reason=reason)
            try:
                self.client.patch_node(node, patch)
            except K8sConflictError:
                logger.info(
                    "Node %s changed between read and write (attempt %d/%d); re-reading",
                    node, attempt + 1, self._RMW_ATTEMPTS,
                )
                continue
            except K8sApiError as exc:
                return ActionRecord(
                    node=node, action="quarantine", ok=False, dry_run=False,
                    reason=reason, error=f"patch_node failed: {exc}",
                )
            logger.warning(
                "QUARANTINED node %s (cordon=%s, taint %s=%s:%s): %s",
                node, self.cordon, self.taint_key, self.taint_value, self.taint_effect, reason,
            )
            return ActionRecord(node=node, action="quarantine", ok=True, dry_run=False, reason=reason, applied=True)
        return ActionRecord(
            node=node, action="quarantine", ok=False, dry_run=False, reason=reason,
            error=f"patch_node conflicted {self._RMW_ATTEMPTS} times (node spec churning)",
        )

    def release(self, node: str, reason: str = "operator release") -> ActionRecord:
        """Uncordon + remove OUR taint (other taints are preserved).

        The inverse of ``quarantine``, for the operator path (RUNBOOK) once
        the hardware is cleared or swapped. Subject to the rate limit but
        not the cooldown (releasing a node we just cordoned by mistake must
        not wait an hour).
        """
        with self._lock:
            now = self._clock()
            while self._action_times and self._action_times[0] <= now - 3600.0:
                self._action_times.popleft()
            if len(self._action_times) >= self.max_actions_per_hour:
                return self._refuse(
                    node, "release",
                    f"rate limit: {len(self._action_times)} actions in the last hour (max {self.max_actions_per_hour})",
                )
            prior_last_action = self._last_action.get(node)
            consumed_ts = self._consume(node)
            ours = node in self._quarantined
        record = self._apply_release(node, reason, quarantined_by_us=ours)
        with self._lock:
            if record.ok:
                self._quarantined.discard(node)
                if record.adopted:
                    # no-op release (nothing to untaint or uncordon) wrote
                    # nothing: refund the FULL consume — rate slot AND the
                    # per-node last-action stamp. Unlike quarantine
                    # adoption (where the kept cooldown stops the policy
                    # re-GETting a genuinely-quarantined node every
                    # cycle), a kept stamp here would make _fence_check
                    # refuse a REAL quarantine of this node for
                    # cooldown_seconds after an operator's harmless no-op
                    # release — locking a confirmed-faulty node in service
                    # over a write that never happened.
                    self._refund_locked(node, prior_last_action, consumed_ts)
            else:
                self._refund_locked(node, prior_last_action, consumed_ts)
            n_quarantined = len(self._quarantined)
        if record.ok and self.metrics is not None:
            if not record.adopted:  # a no-op release is not an action...
                self.metrics.counter("remediation_actions").inc()
            # ...but it can still shrink _quarantined (out-of-band cleanup
            # noticed here), so the gauge must always track the set
            self.metrics.gauge("remediation_quarantined_nodes").set(n_quarantined)
        return record

    def _apply_release(self, node: str, reason: str, *, quarantined_by_us: bool = False) -> ActionRecord:
        for attempt in range(self._RMW_ATTEMPTS):
            try:
                current = self.client.get_node(node)
            except (K8sNotFoundError, K8sApiError) as exc:
                return ActionRecord(
                    node=node, action="release", ok=False, dry_run=self.dry_run,
                    reason=reason, error=str(exc),
                )
            spec = current.get("spec") or {}
            all_taints = spec.get("taints") or []
            had_our_taint = any(t.get("key") == self.taint_key for t in all_taints)
            taints = [t for t in all_taints if t.get("key") != self.taint_key]
            # Only undo a cordon WE are responsible for (our taint present,
            # or the node is in this actuator's quarantined set). A node an
            # operator cordoned for unrelated maintenance — no remediation
            # taint — must stay cordoned: releasing it would silently undo
            # the operator's work.
            uncordon = (had_our_taint or quarantined_by_us) and bool(spec.get("unschedulable"))
            if not had_our_taint and not uncordon:
                # nothing to untaint, nothing to uncordon: a semantically
                # empty PATCH would still burn a rate slot, bump the node's
                # rv, and wake the node-plane watch — mirror quarantine's
                # adoption early-return instead (the caller refunds the slot)
                logger.info(
                    "Release of node %s: no %s taint and no cordon of ours; "
                    "nothing to do", node, self.taint_key,
                )
                return ActionRecord(
                    node=node, action="release", ok=True, dry_run=self.dry_run,
                    reason=f"nothing to release; {reason}", adopted=True,
                )
            patch: Dict[str, Any] = {"spec": {"taints": taints or None}}
            rv = (current.get("metadata") or {}).get("resourceVersion")
            if rv:
                patch["metadata"] = {"resourceVersion": rv}
            if uncordon:
                patch["spec"]["unschedulable"] = None
            if self.dry_run:
                logger.warning("[DRY-RUN] would release node %s (uncordon=%s): %s", node, uncordon, reason)
                return ActionRecord(node=node, action="release", ok=True, dry_run=True, reason=reason)
            try:
                self.client.patch_node(node, patch)
            except K8sConflictError:
                logger.info(
                    "Node %s changed between read and write (attempt %d/%d); re-reading",
                    node, attempt + 1, self._RMW_ATTEMPTS,
                )
                continue
            except K8sApiError as exc:
                return ActionRecord(
                    node=node, action="release", ok=False, dry_run=False,
                    reason=reason, error=f"patch_node failed: {exc}",
                )
            logger.warning(
                "RELEASED node %s (taint %s removed%s): %s",
                node, self.taint_key, ", uncordoned" if uncordon else ", cordon left alone", reason,
            )
            return ActionRecord(node=node, action="release", ok=True, dry_run=False, reason=reason, applied=True)
        return ActionRecord(
            node=node, action="release", ok=False, dry_run=False, reason=reason,
            error=f"patch_node conflicted {self._RMW_ATTEMPTS} times (node spec churning)",
        )

    def quarantined_nodes(self) -> List[str]:
        with self._lock:
            return sorted(self._quarantined)

    def adopt_existing(self) -> List[str]:
        """Seed the budget set from the cluster: every node already carrying
        our taint counts as quarantined-by-us. Call once at arming time —
        a restarted actuator otherwise starts with empty memory, and the
        ``max_quarantined_nodes`` fence would not count pre-restart
        quarantines until each happened to be re-confirmed, letting the
        fleet exceed the budget across restarts. Dry-run mode writes
        nothing, so there is nothing to adopt. Best-effort: an unreachable
        apiserver leaves memory empty (the conservative reconcile path
        still adopts lazily on re-confirmation)."""
        if self.dry_run:
            return []
        adopted = []
        try:
            # paged scan (limit+continue) through the shared consumption
            # driver, so the adoption scan's cost (pages/restarts/duration)
            # lands in metrics under its own prefix — a slow or
            # restart-looping startup scan must be visible. Only
            # taint-carrying names are kept, so memory stays one page even
            # on multi-thousand-node pools. A mid-scan snapshot restart
            # (attempt_changed) resets nothing — the union across attempts
            # over-adopts at worst, and over-adoption only makes the
            # budget more conservative.
            for _rv, items, _attempt_changed in K8sClient.iter_list_pages(
                self.client.list_nodes_paged(page_size=self._ADOPT_PAGE_SIZE),
                metrics=self.metrics,
                metric_prefix="adopt_scan",
            ):
                for node in items:
                    name = (node.get("metadata") or {}).get("name", "")
                    if name and any(
                        t.get("key") == self.taint_key
                        for t in ((node.get("spec") or {}).get("taints") or [])
                    ):
                        adopted.append(name)
        except K8sApiError as exc:
            # keep the PARTIAL set: names already scanned are genuinely
            # quarantined, and discarding them would let the budget permit
            # a full complement of NEW cordons on top of unseen existing
            # ones — the exact cross-restart overrun adoption exists to
            # prevent. Under-counting is the only unsafe direction here.
            logger.warning(
                "Quarantine adoption scan failed mid-pagination (%s); adopting "
                "the %d node(s) scanned so far (the budget reconcile path "
                "adopts stragglers lazily on re-confirmation)", exc, len(adopted),
            )
        adopted = sorted(set(adopted))
        if adopted:
            logger.info("Adopting pre-existing quarantines into the budget: %s", adopted)
            with self._lock:
                self._quarantined.update(adopted)
            if self.metrics is not None:
                self.metrics.gauge("remediation_quarantined_nodes").set(len(self._quarantined))
        return adopted
