"""Remediation plane: act on confirmed probe findings (net-new vs the
reference, whose notify path was read-only and disabled —
clusterapi_client.py via SURVEY.md §2.8)."""

from typing import Any, Callable, Dict, Optional

from k8s_watcher_tpu.remediate.actuator import ActionRecord, NodeActuator
from k8s_watcher_tpu.remediate.policy import ProbeRemediationPolicy

__all__ = [
    "ActionRecord",
    "NodeActuator",
    "ProbeRemediationPolicy",
    "build_actuator",
    "build_policy",
]


def build_actuator(client, tpu_config, *, metrics=None, adopt: bool = True, **overrides) -> NodeActuator:
    """The one place ``tpu.remediation.*`` config maps onto NodeActuator
    kwargs — the watcher (app.py), the standalone slice agent
    (scripts/probe_agent.py), and the operator CLI (scripts/remediate_ctl.py)
    all build through here so a new knob can't silently diverge between
    them. ``overrides`` replace individual fields (the CLI relaxes the
    fences: the operator is the rate limiter for manual actions).

    ``adopt`` seeds the budget from nodes already carrying our taint
    (restart continuity; see ``NodeActuator.adopt_existing``). Pass False
    when this actuator is NOT the cluster's sole remediation actor — a
    multi-controller slice agent adopting taints that OTHER actors applied
    would fill its per-agent budget with foreign quarantines and refuse
    its own local findings — or for one-shot CLI invocations, where a
    cluster-wide node LIST buys nothing.
    """
    kwargs: Dict[str, Any] = dict(
        dry_run=tpu_config.remediation_dry_run,
        cordon=tpu_config.remediation_cordon,
        taint_key=tpu_config.remediation_taint_key,
        taint_value=tpu_config.remediation_taint_value,
        taint_effect=tpu_config.remediation_taint_effect,
        cooldown_seconds=tpu_config.remediation_cooldown_seconds,
        max_actions_per_hour=tpu_config.remediation_max_actions_per_hour,
        max_quarantined_nodes=tpu_config.remediation_max_quarantined_nodes,
    )
    kwargs.update(overrides)
    actuator = NodeActuator(client, metrics=metrics, **kwargs)
    if adopt:
        actuator.adopt_existing()
    return actuator


def build_policy(
    actuator: NodeActuator,
    tpu_config,
    *,
    dispatcher=None,
    sink: Optional[Callable[[Dict[str, Any]], None]] = None,
    metrics=None,
    environment: str = "",
) -> ProbeRemediationPolicy:
    """Policy from config. Pass ``dispatcher`` to notify through the async
    dispatch queue (the standard path: payloads become ``kind="remediation"``
    notifications), or a raw ``sink`` callable for custom delivery."""
    if dispatcher is not None:
        if sink is not None:
            raise ValueError("pass dispatcher or sink, not both")
        import time

        from k8s_watcher_tpu.pipeline.pipeline import Notification

        def sink(payload, _submit=dispatcher.submit):  # noqa: F811 — the derived sink
            _submit(Notification(payload, time.monotonic(), kind="remediation"))

    return ProbeRemediationPolicy(
        actuator,
        confirm_cycles=tpu_config.remediation_confirm_cycles,
        sink=sink,
        metrics=metrics,
        environment=environment,
    )
