"""Remediation plane: act on confirmed probe findings (net-new vs the
reference, whose notify path was read-only and disabled —
clusterapi_client.py via SURVEY.md §2.8)."""

from k8s_watcher_tpu.remediate.actuator import ActionRecord, NodeActuator
from k8s_watcher_tpu.remediate.policy import ProbeRemediationPolicy

__all__ = ["ActionRecord", "NodeActuator", "ProbeRemediationPolicy"]
