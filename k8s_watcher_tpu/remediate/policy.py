"""Remediation policy: when is a probe finding actionable?

The probe plane emits per-cycle findings (suspect devices from the link
walk, dead local chips from the liveness check). A single cycle is not
grounds to cordon a node — ARCHITECTURE.md documents real per-cycle noise,
and the link prober's own docstring warns that one suspect link implicates
the link, not a chip. ``ProbeRemediationPolicy`` requires the SAME node to
be implicated in ``confirm_cycles`` **consecutive** probe reports before
asking the actuator to quarantine it; one clean cycle resets the count
(a transient congestion event that clears is exactly what must not cordon).

Node mapping: a suspect device id resolves to its ``process_index`` through
the report's device inventory, then to a k8s node through the report's
``hosts`` identity map (probe/device.py:host_identity_map — the
``NODE_NAME`` downward-API join). A suspect whose process has no
``node_name`` is counted and logged but never acted on: guessing a node to
cordon is worse than paging a human.

Multi-controller: process 0 acts on the full picture; every OTHER process
acts only on LOCAL-visibility findings naming its OWN node. The split
follows who can see what: chip liveness, MXU/HBM integrity, and link
triangulations of a process's own chips (only the owner observes >=2 of a
chip's links) exist solely in that host's report — gating them on
process 0 would silently drop remote hardware faults — while findings
multiple processes could derive stay process-0-only, so no two actuators
ever confirm the same node and multiply the fences.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Dict, List, Optional

import jax

from k8s_watcher_tpu.remediate.actuator import ActionRecord, NodeActuator

logger = logging.getLogger(__name__)


class ProbeRemediationPolicy:
    def __init__(
        self,
        actuator: NodeActuator,
        *,
        confirm_cycles: int = 3,
        sink: Optional[Callable[[Dict[str, Any]], None]] = None,
        metrics=None,
        environment: str = "",
    ):
        if confirm_cycles < 1:
            raise ValueError("confirm_cycles must be >= 1")
        self.actuator = actuator
        self.confirm_cycles = confirm_cycles
        self.sink = sink
        self.metrics = metrics
        self.environment = environment
        self._lock = threading.Lock()
        self._streaks: Dict[str, int] = {}  # node -> consecutive implicated cycles
        self._reasons: Dict[str, List[str]] = {}  # node -> last cycle's evidence

    # -- evidence extraction ----------------------------------------------

    @staticmethod
    def _implicated(report) -> Dict[str, List[str]]:
        """``node_name -> [(scope, evidence), ...]`` for this report, where
        scope is ``"slice"`` (cross-host findings like the link walk, which
        appear in MULTIPLE processes' reports) or ``"local"`` (findings
        only this process's report can contain: its own chips' liveness,
        MXU/HBM integrity). Pure function of the report payload shape
        (probe/report.py); the scope drives the multi-controller actor
        split in ``observe_report``."""
        devices = (report.devices or {}).get("devices") or []
        id_to_process = {d.get("id"): d.get("process_index") for d in devices}
        hosts = report.hosts or {}

        def node_of(process_index) -> Optional[str]:
            identity = hosts.get(str(process_index)) or {}
            return identity.get("node_name")

        out: Dict[str, List] = {}
        unmapped: List[str] = []

        def implicate(process_index, evidence: str, scope: str = "slice") -> None:
            node = node_of(process_index)
            if node:
                out.setdefault(node, []).append((scope, evidence))
            else:
                unmapped.append(evidence)

        links = report.links
        if links is not None and links.error is None:
            # Re-triangulate from MEASURED defects only (slow RTT, corrupt
            # checksum). links.suspect_devices also counts error/"skipped"
            # records — right for reporting, wrong for actuation: when one
            # process fails preparation, EVERY cross-process link on every
            # process becomes an error-suspect, and acting on those would
            # cordon healthy peers' nodes over an agent-infrastructure
            # failure no probe ever measured.
            endpoint_counts: Dict[Any, int] = {}
            for s in links.suspect_links:
                if s.get("reason") in ("slow", "corrupt"):
                    for device_id in s.get("device_ids", ()):
                        endpoint_counts[device_id] = endpoint_counts.get(device_id, 0) + 1
            reporting_pidx = (report.devices or {}).get("process_index")
            for device_id, count in sorted(endpoint_counts.items()):
                if count >= 2:
                    owner_pidx = id_to_process.get(device_id)
                    # Triangulating device d needs >=2 of d's links in ONE
                    # walk, and only d's OWN process observes more than one
                    # (a peer shares at most one torus edge with d) — so a
                    # triangulation of MY device is a local-visibility
                    # finding its host must act on itself; a remote-device
                    # triangulation (single-controller walks, exotic
                    # topologies) is slice-scope for process 0.
                    implicate(
                        owner_pidx,
                        f"link probe: device {device_id} is the common endpoint of "
                        f"{count} measured-suspect links",
                        scope="local" if owner_pidx == reporting_pidx else "slice",
                    )
        # DCN pair-walk suspects (probe/multislice.py): a slice implicated
        # as the common endpoint of >=2 suspect DCN pairs maps to its
        # MEMBER NODES via slice_processes -> hosts identity. Slice-scope:
        # the pair walk is observed by every member process of each pair,
        # so process 0 is the single actor (same rule as remote link
        # findings). A whole-slice implication can name MANY nodes — the
        # actuator's max_quarantined_nodes budget is the designed stop
        # against mass cordons from one fabric event; in dry-run (the
        # default) this yields would-quarantine decisions naming the
        # slice's nodes (ARCHITECTURE.md "DCN remediation").
        ms = report.multislice
        if (
            ms is not None
            and getattr(ms, "error", None) is None
            and not getattr(ms, "timing_unreliable", False)
        ):
            # Re-derive suspect slices from MEASURED defects only (slow
            # RTT, corrupt checksum) — ms.dcn_suspect_slices also counts
            # error records, and an agent-infrastructure failure that
            # error-marks many pairs (a compile error under the per-pair
            # containment) would otherwise implicate whole healthy slices
            # over a failure no probe ever measured. Same discipline as
            # the link-walk re-triangulation above.
            pair_counts: Dict[int, int] = {}
            for pair in getattr(ms, "suspect_pairs", None) or []:
                if pair.get("reason") not in ("slow", "corrupt"):
                    continue
                # device_ids on the "dcn" axis are SLICE indices
                for slice_idx in pair.get("device_ids", ()):
                    pair_counts[slice_idx] = pair_counts.get(slice_idx, 0) + 1
            slice_procs = getattr(ms, "slice_processes", None) or []
            n_sl = int(getattr(ms, "n_slices", 0) or 0)
            for slice_idx, count in sorted(pair_counts.items()):
                # A faulty slice ENDPOINT (NIC/path) stretches or corrupts
                # EVERY pair it touches, so the implication bar is ALL
                # n_slices-1 of its pairs suspect, with at least 2. The
                # link walk's plain >=2 rule cannot transfer here: the DCN
                # pair graph is COMPLETE, so two degraded slices would put
                # >=2 suspect pairs on every HEALTHY slice too (at n=4,
                # slices 0+1 bad gives counts {0:3, 1:3, 2:2, 3:2}) and a
                # >=2 bar would cordon the healthy ones' nodes. Requiring
                # the full n-1 also keeps n=2 route-only (one pair cannot
                # distinguish endpoint from route), and stays conservative
                # when a pair errored on its owner (count can't reach n-1
                # that cycle).
                if count < max(2, n_sl - 1):
                    continue
                members = (
                    slice_procs[slice_idx] if slice_idx < len(slice_procs) else []
                )
                if not members:
                    unmapped.append(
                        f"dcn probe: slice {slice_idx} is the common endpoint of "
                        f"{count} suspect DCN pairs, but the report carries no "
                        "member-process map for it"
                    )
                    continue
                for pidx in members:
                    implicate(
                        pidx,
                        f"dcn probe: slice {slice_idx} (host process {pidx}) is the "
                        f"common endpoint of {count} suspect DCN slice pairs",
                        scope="slice",
                    )
        for entry in devices:
            if entry.get("alive") is False:
                # liveness only runs on the reporting process's OWN chips
                # (remote chips are alive=None), so this is a local finding
                implicate(
                    entry.get("process_index"),
                    f"device probe: chip {entry.get('id')} failed its liveness computation",
                    scope="local",
                )
        # single-chip integrity findings implicate the REPORTING process's
        # own node: the MXU/HBM probes run on this process's local chip
        local = (report.devices or {}).get("process_index")
        mxu = report.mxu
        if mxu is not None and mxu.get("error") is None and mxu.get("finite") is False:
            implicate(local, "mxu probe: matmul produced non-finite values", scope="local")
        for label, probe in (("hbm read", report.hbm), ("hbm write", report.hbm_write)):
            if probe is None or probe.get("error") is not None:
                continue
            bad = probe.get("bad_blocks")
            if bad:
                implicate(
                    local,
                    f"{label} probe: {len(bad)} HBM block(s) failed pattern readback",
                    scope="local",
                )
            elif probe.get("integrity_ok") is False:
                implicate(local, f"{label} probe: checksum integrity failed", scope="local")
        if unmapped:
            logger.warning(
                "Probe implicates hardware on processes with no node_name "
                "(NODE_NAME downward-API env missing?) — cannot remediate: %s",
                unmapped,
            )
        if unmapped and not out:
            out["__unmapped__"] = unmapped  # visible in notifications, never acted on
        return out

    # -- the per-cycle fold ------------------------------------------------

    def observe_report(self, report) -> List[ActionRecord]:
        """Fold one probe report; returns the actions taken (possibly [])."""
        scoped = self._implicated(report)
        if jax.process_count() > 1 and jax.process_index() != 0:
            # non-0 processes act ONLY on LOCAL-scope findings naming their
            # OWN node: a dead chip or failed HBM block is visible only in
            # the local process's report (probe/device.py probes local
            # chips; process 0 sees alive=None for remote ones), so gating
            # everything on process 0 would silently drop those faults.
            # Slice-scope findings (the link walk) stay process-0-only even
            # when they name this node — cross-host links are OBSERVED by
            # both endpoint processes, and two actuators confirming the
            # same node would double every fence's accounting.
            hosts = report.hosts or {}
            own = (hosts.get(str(jax.process_index())) or {}).get("node_name")
            filtered: Dict[str, List] = {}
            if own and own in scoped:
                kept = [e for e in scoped[own] if e[0] == "local"]
                if kept:
                    filtered[own] = kept
            scoped = filtered
        # strip scopes: downstream (streaks, reasons, notifications) wants
        # plain evidence strings
        implicated = {
            n: (ev if n == "__unmapped__" else [e[1] for e in ev])
            for n, ev in scoped.items()
        }
        actionable = {n: ev for n, ev in implicated.items() if n != "__unmapped__"}
        records: List[ActionRecord] = []
        with self._lock:
            for node in list(self._streaks):
                if node not in actionable:
                    # one clean cycle resets: transient events must not
                    # accumulate toward a cordon across hours
                    del self._streaks[node]
                    self._reasons.pop(node, None)
            confirmed: List[str] = []
            for node, evidence in actionable.items():
                self._streaks[node] = self._streaks.get(node, 0) + 1
                self._reasons[node] = evidence
                if self._streaks[node] >= self.confirm_cycles:
                    confirmed.append(node)
        for node in confirmed:
            reason = (
                f"implicated in {self.confirm_cycles}+ consecutive probe cycles: "
                + "; ".join(self._reasons.get(node, []))[:400]
            )
            records.append(self.actuator.quarantine(node, reason))
            with self._lock:
                # restart the streak either way: an applied quarantine needs
                # no repeat, and a refused one (cooldown/rate/budget) must
                # re-earn confirmation rather than hammer the fences every
                # subsequent cycle
                self._streaks.pop(node, None)
        if self.metrics is not None and implicated.get("__unmapped__"):
            self.metrics.counter("remediation_unmappable").inc()
        if records or implicated:
            self._notify(implicated, records)
        return records

    def _notify(self, implicated: Dict[str, List[str]], records: List[ActionRecord]) -> None:
        if self.sink is None:
            return
        from datetime import datetime, timezone

        payload = {
            "event_type": "TPU_REMEDIATION",
            "environment": self.environment,
            "dry_run": self.actuator.dry_run,
            "implicated": implicated,
            "streaks": dict(self._streaks),
            "confirm_cycles": self.confirm_cycles,
            "actions": [r.to_dict() for r in records],
            "quarantined_nodes": self.actuator.quarantined_nodes(),
            "event_timestamp": datetime.now(timezone.utc).isoformat(),
        }
        try:
            self.sink(payload)
        except Exception as exc:  # noqa: BLE001 — reporting must not kill the probe loop
            logger.error("Remediation notification failed: %s", exc)

    def snapshot(self) -> Dict[str, Any]:
        """Debug-endpoint view of the policy state."""
        with self._lock:
            return {
                "streaks": dict(self._streaks),
                "confirm_cycles": self.confirm_cycles,
                "dry_run": self.actuator.dry_run,
                "quarantined_nodes": self.actuator.quarantined_nodes(),
            }
