"""Typed configuration schema.

Every key that appears in ``config/*.yaml`` maps to a field here and is
consumed somewhere in the framework; unknown keys are rejected by the loader.
This fixes reference defect #3 (SURVEY.md §2: dead config keys —
``watcher.watch_interval``, both ``retry`` blocks, ``clusterapi.endpoints``,
``clusterapi.timeout`` and ``kubernetes.use_mock`` were never consumed by
the reference).

Schema parity map (reference file:line -> field):

- base.yaml:4   watcher.watch_interval      -> WatcherConfig.watch_interval
- base.yaml:7   watcher.log_level           -> WatcherConfig.log_level
- base.yaml:10  watcher.retry               -> WatcherConfig.retry (now wired
                                               into the resilient watch loop)
- base.yaml:16  clusterapi.endpoints        -> ClusterApiConfig.endpoints (now
                                               wired; reference hardcoded the
                                               path at clusterapi_client.py:30)
- base.yaml:21  clusterapi.timeout          -> ClusterApiConfig.timeout (now
                                               actually passed to requests)
- development.yaml:6  kubernetes.config_file -> KubernetesConfig.config_file
- development.yaml:7  kubernetes.use_mock    -> KubernetesConfig.use_mock (now
                                               selects the in-process fake
                                               watch source)
- production.yaml:6   kubernetes.use_incluster_config
                                            -> KubernetesConfig.use_incluster_config
- production.yaml:24  watcher.alerts.critical_events_only
                                            -> WatcherConfig.critical_events_only

The ``tpu:`` section is net-new (north star): backend selection, the
accelerator resource key (``google.com/tpu``), slice-topology expectations,
and probe cadence/thresholds.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Mapping, Optional, Sequence


class SchemaError(ValueError):
    """A config value failed schema validation."""


# The single home of the accepted taint effects — the schema validates
# config against it and remediate.NodeActuator validates its argument
# against it (schema is the dependency-light layer, so it lives here).
VALID_TAINT_EFFECTS = ("NoSchedule", "PreferNoSchedule", "NoExecute")
# ingest.prefilter (native/scanner.py make_scanner) vocabulary
VALID_PREFILTER_MODES = ("auto", "native", "python", "off")


def _type_name(value: Any) -> str:
    return type(value).__name__


def _expect(value: Any, types: tuple, path: str) -> Any:
    if not isinstance(value, types):
        wanted = "/".join(t.__name__ for t in types)
        raise SchemaError(f"config key '{path}': expected {wanted}, got {_type_name(value)} ({value!r})")
    # bool is a subclass of int — reject bools where ints are wanted.
    if bool not in types and isinstance(value, bool) and int in types:
        raise SchemaError(f"config key '{path}': expected int, got bool")
    return value


def _opt_str(raw: Mapping[str, Any], key: str, path: str, default: Optional[str] = None) -> Optional[str]:
    if key not in raw or raw[key] is None:
        return default
    v = _expect(raw[key], (str,), f"{path}.{key}")
    return v if v != "" else default


def _opt_num(raw: Mapping[str, Any], key: str, path: str, default: float) -> float:
    if key not in raw or raw[key] is None:
        return default
    v = raw[key]
    if isinstance(v, str):  # env-substituted values arrive as strings
        if v.strip() == "":
            return default
        try:
            return float(v)
        except ValueError:
            raise SchemaError(f"config key '{path}.{key}': not a number: {v!r}")
    return float(_expect(v, (int, float), f"{path}.{key}"))


def _opt_int(raw: Mapping[str, Any], key: str, path: str, default: int) -> int:
    if key not in raw or raw[key] is None:
        return default
    v = raw[key]
    if isinstance(v, str):  # env-substituted values arrive as strings
        if v.strip() == "":
            return default
        try:
            return int(v)
        except ValueError:
            raise SchemaError(f"config key '{path}.{key}': not an integer: {v!r}")
    return _expect(v, (int,), f"{path}.{key}")


def _opt_bool(raw: Mapping[str, Any], key: str, path: str, default: bool) -> bool:
    if key not in raw or raw[key] is None:
        return default
    v = raw[key]
    # env-substituted values arrive as strings ("true"/"false")
    if isinstance(v, str):
        low = v.strip().lower()
        if low in ("true", "1", "yes", "on"):
            return True
        if low in ("false", "0", "no", "off", ""):
            return False
        raise SchemaError(f"config key '{path}.{key}': not a boolean: {v!r}")
    return _expect(v, (bool,), f"{path}.{key}")


def _check_known(raw: Mapping[str, Any], known: Sequence[str], path: str) -> None:
    unknown = sorted(set(raw) - set(known))
    if unknown:
        raise SchemaError(f"unknown config key(s) under '{path}': {', '.join(unknown)} (known: {', '.join(sorted(known))})")


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry/backoff policy (reference base.yaml:10-12,24-26 — dead there, wired here)."""

    max_attempts: int = 3
    delay_seconds: float = 5.0
    # net-new: exponential backoff knobs for the resilient watch loop
    max_delay_seconds: float = 60.0
    backoff_multiplier: float = 2.0

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any], path: str, *, delay_default: float = 5.0) -> "RetryPolicy":
        _check_known(raw, ("max_attempts", "delay_seconds", "max_delay_seconds", "backoff_multiplier"), path)
        return cls(
            max_attempts=_opt_int(raw, "max_attempts", path, 3),
            delay_seconds=_opt_num(raw, "delay_seconds", path, delay_default),
            max_delay_seconds=_opt_num(raw, "max_delay_seconds", path, 60.0),
            backoff_multiplier=_opt_num(raw, "backoff_multiplier", path, 2.0),
        )


def leader_timing_error(lease_duration: float, renew_deadline: float, retry_period: float) -> Optional[str]:
    """The one place the leader-election timing invariants live (used by both
    the config schema and ``LeaderElector.__init__``). Returns an error
    message, or None if the timings are safe.

    Compares against ``int(lease_duration)`` because ``leaseDurationSeconds``
    is an integer on the wire — a fractional duration would otherwise let
    ``renew_deadline`` exceed what observers actually enforce, and a leader
    could believe it still leads after a standby has legally stolen the lease.
    """
    if lease_duration < 1.0:
        return "lease_duration_seconds must be >= 1 (integer on the wire)"
    if retry_period <= 0 or renew_deadline <= 0:
        return "retry_period_seconds and renew_deadline_seconds must be > 0"
    if renew_deadline >= float(int(lease_duration)):
        return "renew_deadline_seconds must be < int(lease_duration_seconds) (the wire value is a truncated integer)"
    if retry_period >= renew_deadline:
        return "retry_period_seconds must be < renew_deadline_seconds (need >1 renew attempt per deadline)"
    return None


@dataclasses.dataclass(frozen=True)
class LeaderElectionConfig:
    """The ``watcher.leader_election:`` section — net-new HA (SURVEY.md §5
    failure detection: the reference was a singleton with no failover).

    N watcher replicas campaign for a coordination.k8s.io/v1 Lease; exactly
    one watches + notifies, the rest stand by hot and take over within
    ``lease_duration_seconds`` of a leader crash (immediately on clean exit).
    """

    enabled: bool = False
    lease_name: str = "k8s-watcher-tpu"
    lease_namespace: str = "default"
    lease_duration_seconds: float = 15.0
    renew_deadline_seconds: float = 10.0
    retry_period_seconds: float = 2.0
    identity: Optional[str] = None  # default: <hostname>-<pid>

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "LeaderElectionConfig":
        path = "watcher.leader_election"
        _check_known(
            raw,
            ("enabled", "lease_name", "lease_namespace", "lease_duration_seconds",
             "renew_deadline_seconds", "retry_period_seconds", "identity"),
            path,
        )
        cfg = cls(
            enabled=_opt_bool(raw, "enabled", path, False),
            lease_name=_opt_str(raw, "lease_name", path, cls.lease_name),
            lease_namespace=_opt_str(raw, "lease_namespace", path, cls.lease_namespace),
            lease_duration_seconds=_opt_num(raw, "lease_duration_seconds", path, 15.0),
            renew_deadline_seconds=_opt_num(raw, "renew_deadline_seconds", path, 10.0),
            retry_period_seconds=_opt_num(raw, "retry_period_seconds", path, 2.0),
            identity=_opt_str(raw, "identity", path, None),
        )
        if cfg.enabled:
            error = leader_timing_error(
                cfg.lease_duration_seconds, cfg.renew_deadline_seconds, cfg.retry_period_seconds
            )
            if error:
                raise SchemaError(f"config key '{path}': {error}")
        return cfg


@dataclasses.dataclass(frozen=True)
class WatcherConfig:
    """The ``watcher:`` section (reference base.yaml:1-12, production.yaml:16-25)."""

    watch_interval: float = 1.0
    log_level: str = "INFO"
    namespaces: tuple = ()
    retry: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    critical_events_only: bool = False
    # net-new observability + server-side filtering
    status_port: int = 0  # 0 = no /metrics//healthz endpoint
    # Bearer token required on every status route except /healthz; None
    # leaves the plane open (in-cluster behind NetworkPolicy — RUNBOOK
    # "Status-server threat model"). Inject via ${WATCHER_STATUS_TOKEN}
    # interpolation rather than a literal in a committed file.
    status_auth_token: Optional[str] = None
    liveness_stale_seconds: float = 900.0
    label_selector: Optional[str] = None  # k8s labelSelector pushed to the API server
    leader_election: LeaderElectionConfig = dataclasses.field(default_factory=LeaderElectionConfig)
    # last-N pipeline decisions served at /debug/events (0 disables)
    audit_ring_size: int = 256
    # LIST pagination (limit+continue) page size for the initial list and
    # every relist — bounds apiserver response size and watcher peak memory
    # on large clusters (client-go's default is 500)
    list_page_size: int = 500

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "WatcherConfig":
        _check_known(
            raw,
            ("watch_interval", "log_level", "namespaces", "retry", "alerts",
             "status_port", "status_auth_token", "liveness_stale_seconds",
             "label_selector", "leader_election",
             "audit_ring_size", "list_page_size"),
            "watcher",
        )
        namespaces = raw.get("namespaces") or ()
        if namespaces:
            _expect(namespaces, (list, tuple), "watcher.namespaces")
            namespaces = tuple(_expect(ns, (str,), "watcher.namespaces[]") for ns in namespaces)
        alerts = raw.get("alerts") or {}
        _expect(alerts, (dict,), "watcher.alerts")
        _check_known(alerts, ("critical_events_only",), "watcher.alerts")
        _expect(raw.get("leader_election") or {}, (dict,), "watcher.leader_election")
        level = _expect(raw.get("log_level", "INFO"), (str,), "watcher.log_level").upper()
        if level not in ("DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"):
            raise SchemaError(f"config key 'watcher.log_level': invalid level {level!r}")
        page_size = _opt_int(raw, "list_page_size", "watcher", 500)
        if page_size < 1:
            raise SchemaError(
                f"config key 'watcher.list_page_size': must be >= 1, got {page_size}"
            )
        return cls(
            watch_interval=_opt_num(raw, "watch_interval", "watcher", 1.0),
            log_level=level,
            namespaces=namespaces,
            retry=RetryPolicy.from_raw(raw.get("retry") or {}, "watcher.retry", delay_default=5.0),
            critical_events_only=_opt_bool(alerts, "critical_events_only", "watcher.alerts", False),
            status_port=_opt_int(raw, "status_port", "watcher", 0),
            status_auth_token=_opt_str(raw, "status_auth_token", "watcher", None) or None,
            liveness_stale_seconds=_opt_num(raw, "liveness_stale_seconds", "watcher", 900.0),
            label_selector=_opt_str(raw, "label_selector", "watcher", None),
            leader_election=LeaderElectionConfig.from_raw(raw.get("leader_election") or {}),
            audit_ring_size=_opt_int(raw, "audit_ring_size", "watcher", 256),
            list_page_size=page_size,
        )


@dataclasses.dataclass(frozen=True)
class ClusterApiConfig:
    """The ``clusterapi:`` section (reference base.yaml:14-26, clusterapi_client.py).

    Unlike the reference, ``endpoints`` and ``timeout`` are actually consumed
    (reference hardcoded ``/api/pods/update`` at clusterapi_client.py:30 and
    never passed a timeout to requests.post at :36).
    """

    base_url: str = "http://localhost:3000"
    api_key: Optional[str] = None
    pod_update_endpoint: str = "/api/pods/update"
    pod_update_batch_endpoint: str = "/api/pods/update_batch"
    health_endpoint: str = "/health"
    timeout: float = 30.0
    retry: RetryPolicy = dataclasses.field(default_factory=lambda: RetryPolicy(delay_seconds=2.0))
    # net-new: async egress-plane knobs (keyed worker fan-out so one slow
    # POST can't stall the watch stream — prerequisite for the <1s p50
    # target — and distinct pods POST concurrently under churn)
    queue_capacity: int = 1024
    # egress worker count (= lane count: notifications hash by coalesce
    # key onto per-worker FIFO lanes). 0 = auto: scale with ingest.shards
    # (max(2, 2 x shards) — the fan-in side grows with the fan-out side)
    workers: int = 0
    # latest-wins per pod/slice while queued: update_pod_status is a state
    # update, so a newer payload supersedes an unsent older one for the same
    # object (bounds queue growth per object under churn)
    coalesce: bool = True
    # lane depth at which latest-wins collapse starts. Below it same-key
    # updates queue uncollapsed (the receiver sees every transition while
    # egress keeps up); 0 = collapse whenever a same-key payload is still
    # waiting (the pre-round-7 behavior)
    coalesce_watermark: int = 0
    # pooled keep-alive connections to the notify target; 0 = match workers
    pool_size: int = 0
    # micro-batch size for the batched update_pod_statuses endpoint under
    # backlog; 0/1 = per-item sends only (a receiver without the batch
    # endpoint falls back automatically either way)
    batch_max: int = 0
    # /healthz turns 503 when a lane with backlog has made no progress for
    # this long (worker wedged inside a send against a hung target) or
    # every egress worker is dead — egress liveness, the counterpart of
    # watcher.liveness_stale_seconds for the notify side
    egress_stall_seconds: float = 120.0
    verify_tls: bool = True  # for https endpoints with self-signed certs

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "ClusterApiConfig":
        _check_known(
            raw,
            ("base_url", "auth", "endpoints", "timeout", "retry", "queue_capacity", "workers",
             "coalesce", "coalesce_watermark", "pool_size", "batch_max",
             "egress_stall_seconds", "verify_tls"),
            "clusterapi",
        )
        auth = raw.get("auth") or {}
        _expect(auth, (dict,), "clusterapi.auth")
        _check_known(auth, ("api_key",), "clusterapi.auth")
        endpoints = raw.get("endpoints") or {}
        _expect(endpoints, (dict,), "clusterapi.endpoints")
        _check_known(endpoints, ("pod_update", "pod_update_batch", "health"), "clusterapi.endpoints")
        for key, floor in (("workers", 0), ("coalesce_watermark", 0), ("pool_size", 0), ("batch_max", 0)):
            if _opt_int(raw, key, "clusterapi", 0) < floor:
                raise SchemaError(f"config key 'clusterapi.{key}': must be >= {floor}")
        stall = _opt_num(raw, "egress_stall_seconds", "clusterapi", 120.0)
        if stall <= 0:
            raise SchemaError(
                f"config key 'clusterapi.egress_stall_seconds': must be > 0, got {stall} "
                f"(a non-positive threshold would 503 on every queued send)"
            )
        return cls(
            base_url=_opt_str(raw, "base_url", "clusterapi", "http://localhost:3000").rstrip("/"),
            api_key=_opt_str(auth, "api_key", "clusterapi.auth", None),
            pod_update_endpoint=_opt_str(endpoints, "pod_update", "clusterapi.endpoints", "/api/pods/update"),
            pod_update_batch_endpoint=_opt_str(
                endpoints, "pod_update_batch", "clusterapi.endpoints", "/api/pods/update_batch"
            ),
            health_endpoint=_opt_str(endpoints, "health", "clusterapi.endpoints", "/health"),
            timeout=_opt_num(raw, "timeout", "clusterapi", 30.0),
            retry=RetryPolicy.from_raw(raw.get("retry") or {}, "clusterapi.retry", delay_default=2.0),
            queue_capacity=_opt_int(raw, "queue_capacity", "clusterapi", 1024),
            workers=_opt_int(raw, "workers", "clusterapi", 0),
            coalesce=_opt_bool(raw, "coalesce", "clusterapi", True),
            coalesce_watermark=_opt_int(raw, "coalesce_watermark", "clusterapi", 0),
            pool_size=_opt_int(raw, "pool_size", "clusterapi", 0),
            batch_max=_opt_int(raw, "batch_max", "clusterapi", 0),
            egress_stall_seconds=stall,
            verify_tls=_opt_bool(raw, "verify_tls", "clusterapi", True),
        )

    def resolved_workers(self, ingest_shards: int = 1) -> int:
        """The egress worker/lane count: explicit, or scaled with the
        ingest fan-out (max(2, 2 x shards)) when ``workers: 0``."""
        return self.workers or max(2, 2 * max(1, ingest_shards))

    def resolved_pool_size(self, ingest_shards: int = 1) -> int:
        """Connection-pool size: explicit, or one keep-alive connection
        per egress worker so workers never serialize on a socket."""
        return self.pool_size or self.resolved_workers(ingest_shards)


@dataclasses.dataclass(frozen=True)
class KubernetesConfig:
    """The ``kubernetes:`` section (reference development.yaml:4-7, production.yaml:4-8)."""

    use_incluster_config: bool = False
    config_file: Optional[str] = None
    use_mock: bool = False
    # net-new: resilient-watch knobs (reference had no reconnect at all —
    # SURVEY.md §2 defect #4)
    request_timeout: float = 30.0
    watch_timeout_seconds: int = 300
    verify_tls: bool = True

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "KubernetesConfig":
        _check_known(
            raw,
            ("use_incluster_config", "config_file", "use_mock", "request_timeout", "watch_timeout_seconds", "verify_tls"),
            "kubernetes",
        )
        return cls(
            use_incluster_config=_opt_bool(raw, "use_incluster_config", "kubernetes", False),
            config_file=_opt_str(raw, "config_file", "kubernetes", None),
            use_mock=_opt_bool(raw, "use_mock", "kubernetes", False),
            request_timeout=_opt_num(raw, "request_timeout", "kubernetes", 30.0),
            watch_timeout_seconds=_opt_int(raw, "watch_timeout_seconds", "kubernetes", 300),
            verify_tls=_opt_bool(raw, "verify_tls", "kubernetes", True),
        )


@dataclasses.dataclass(frozen=True)
class TpuConfig:
    """The ``tpu:`` section — net-new (north star: BASELINE.json).

    Selects the accelerator backend, the pod resource key used by the
    resource filter, slice-topology expectations, and in-slice probe
    cadence/thresholds.
    """

    backend: str = "tpu"  # "tpu" | "gpu" (gpu-compat mode filters nvidia.com/gpu)
    resource_key: str = "google.com/tpu"
    # native watch-frame prefilter (native/scanner.py): skip json.loads for
    # frames that cannot contain resource_key — pure speedup, no semantic
    # change (the pipeline's TpuResourceFilter would drop them anyway)
    prefilter: bool = True
    # GKE labels/annotations used for slice-topology inference
    topology_label: str = "cloud.google.com/gke-tpu-topology"
    accelerator_label: str = "cloud.google.com/gke-tpu-accelerator"
    # probe plane
    probe_enabled: bool = False
    probe_interval_seconds: float = 30.0
    # standalone probe agent's own scrape surface (scripts/probe_agent.py):
    # /metrics (gauges incl. per-cycle medians), /healthz (cycle liveness),
    # /debug/trend. 0 = off. The watcher's in-process agent shares the
    # watcher's watcher.status_port server instead.
    probe_status_port: int = 0
    # bearer token for the agent's status plane — same contract as
    # watcher.status_auth_token (RUNBOOK "Status-server threat model")
    probe_status_auth_token: Optional[str] = None
    probe_payload_bytes: int = 4 * 1024 * 1024
    probe_rtt_warn_ms: float = 50.0
    probe_matmul_size: int = 1024
    # dependent-matmul chain length per timed call: device time must dwarf
    # the host fence (2*size^3*inner FLOPs; over a dev tunnel the fence is
    # tens of ms, so soak/bench-grade fidelity wants size 4096 x inner 128)
    probe_matmul_inner_iters: int = 8
    probe_hbm_bytes: int = 256 * 1024 * 1024  # 0 disables the HBM sweep
    # write-bandwidth + pattern-integrity pass (block-indexed pattern write,
    # per-block checksum readback localizing bad HBM address ranges)
    probe_hbm_write_enabled: bool = True
    expected_chips_per_host: int = 0  # 0 = don't enforce
    # per-link localization probe (probe/links.py): O(links) small compiles,
    # so off by default; turn on to get which-chip/which-link diagnostics
    probe_links_enabled: bool = False
    probe_link_rtt_factor: float = 3.0
    # absolute outlier floor per hop — raise on fabrics whose healthy RTT
    # jitter exceeds the default (e.g. DCN-backed inter-host columns)
    probe_link_rtt_floor_ms: float = 0.05
    # cross-cycle drift detection (probe/trend.py): flags sustained decay
    # hiding inside the per-cycle noise band. Factors are deliberately far
    # outside the documented noise (ARCHITECTURE.md) to avoid false alerts
    # on tunneled dev links; tighten on local deployments.
    probe_trend_enabled: bool = True
    probe_trend_window: int = 16
    probe_trend_recent: int = 3
    probe_trend_drop_factor: float = 0.75
    probe_trend_rise_factor: float = 2.5
    probe_trend_min_history: int = 6
    # cross-slice DCN aggregation probe (probe/multislice.py)
    probe_multislice_enabled: bool = False
    probe_multislice_slices: int = 0  # 0 = infer from Device.slice_index
    # per-pair DCN walk: O(n_slices^2) small programs localizing WHICH
    # slice's DCN path is degraded (the slice-level analogue of the link
    # walk); cheap at realistic slice counts
    probe_multislice_pair_localization: bool = True
    # SURVEY.md §5 tracing substitute: when set, each probe cycle is wrapped
    # in jax.profiler.trace(dir) producing a TensorBoard-loadable trace
    probe_profile_dir: Optional[str] = None
    # node-plane watching: Ready→NotReady on a TPU node degrades its slices
    # immediately (pod eviction lags the node drop by minutes)
    node_watch_enabled: bool = False
    node_watch_label_selector: Optional[str] = None
    # remediation plane (remediate/): quarantine (cordon + taint) nodes the
    # probe implicates across confirm_cycles consecutive cycles. dry_run
    # stays the default — flip it only after watching the dry-run decisions
    # in production for a while (RUNBOOK.md "Remediation").
    remediation_enabled: bool = False
    remediation_dry_run: bool = True
    remediation_cordon: bool = True
    remediation_taint_key: str = "k8s-watcher-tpu/ici-fault"
    remediation_taint_value: str = "suspect"
    remediation_taint_effect: str = "NoSchedule"
    remediation_confirm_cycles: int = 3
    remediation_cooldown_seconds: float = 3600.0
    remediation_max_actions_per_hour: int = 4
    remediation_max_quarantined_nodes: int = 2

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "TpuConfig":
        _check_known(
            raw,
            (
                "backend",
                "resource_key",
                "prefilter",
                "topology_label",
                "accelerator_label",
                "probe",
                "node_watch",
                "remediation",
            ),
            "tpu",
        )
        backend = _opt_str(raw, "backend", "tpu", "tpu")
        if backend not in ("tpu", "gpu"):
            raise SchemaError(f"config key 'tpu.backend': must be 'tpu' or 'gpu', got {backend!r}")
        default_key = "google.com/tpu" if backend == "tpu" else "nvidia.com/gpu"
        node_watch = raw.get("node_watch") or {}
        _expect(node_watch, (dict,), "tpu.node_watch")
        _check_known(node_watch, ("enabled", "label_selector"), "tpu.node_watch")
        remediation = raw.get("remediation") or {}
        _expect(remediation, (dict,), "tpu.remediation")
        _check_known(
            remediation,
            ("enabled", "dry_run", "cordon", "taint_key", "taint_value", "taint_effect",
             "confirm_cycles", "cooldown_seconds", "max_actions_per_hour",
             "max_quarantined_nodes"),
            "tpu.remediation",
        )
        taint_effect = _opt_str(remediation, "taint_effect", "tpu.remediation", "NoSchedule")
        if taint_effect not in VALID_TAINT_EFFECTS:
            raise SchemaError(
                f"config key 'tpu.remediation.taint_effect': must be one of "
                f"{', '.join(VALID_TAINT_EFFECTS)}, got {taint_effect!r}"
            )
        remediation_confirm = _opt_int(remediation, "confirm_cycles", "tpu.remediation", 3)
        if remediation_confirm < 1:
            raise SchemaError("config key 'tpu.remediation.confirm_cycles': must be >= 1")
        remediation_budget = _opt_int(remediation, "max_quarantined_nodes", "tpu.remediation", 2)
        if remediation_budget < 1:
            raise SchemaError("config key 'tpu.remediation.max_quarantined_nodes': must be >= 1")
        remediation_rate = _opt_int(remediation, "max_actions_per_hour", "tpu.remediation", 4)
        if remediation_rate < 1:
            raise SchemaError("config key 'tpu.remediation.max_actions_per_hour': must be >= 1")
        remediation_cooldown = _opt_num(remediation, "cooldown_seconds", "tpu.remediation", 3600.0)
        if remediation_cooldown < 0:
            raise SchemaError(
                "config key 'tpu.remediation.cooldown_seconds': must be >= 0 "
                "(a negative value would silently disable the cooldown fence)"
            )
        probe = raw.get("probe") or {}
        _expect(probe, (dict,), "tpu.probe")
        _check_known(
            probe,
            ("enabled", "interval_seconds", "status_port", "status_auth_token", "payload_bytes", "rtt_warn_ms", "matmul_size",
             "matmul_inner_iters",
             "hbm_bytes", "hbm_write_enabled", "expected_chips_per_host", "links_enabled",
             "link_rtt_factor", "link_rtt_floor_ms", "multislice_enabled",
             "multislice_slices", "multislice_pair_localization",
             "profile_dir", "trend_enabled", "trend_window",
             "trend_recent", "trend_drop_factor", "trend_rise_factor",
             "trend_min_history"),
            "tpu.probe",
        )
        # trend knobs have relational constraints; reject them HERE with the
        # key path (the repo's SchemaError convention) instead of letting
        # TrendTracker's bare ValueError crash the watcher at agent startup
        trend_window = _opt_int(probe, "trend_window", "tpu.probe", 16)
        trend_recent = _opt_int(probe, "trend_recent", "tpu.probe", 3)
        trend_min_history = _opt_int(probe, "trend_min_history", "tpu.probe", 6)
        trend_drop = _opt_num(probe, "trend_drop_factor", "tpu.probe", 0.75)
        trend_rise = _opt_num(probe, "trend_rise_factor", "tpu.probe", 2.5)
        if not 0.0 < trend_drop < 1.0:
            raise SchemaError(
                f"config key 'tpu.probe.trend_drop_factor': must be in (0, 1) — a "
                f"factor >= 1 alerts on every healthy cycle — got {trend_drop}"
            )
        if trend_rise <= 1.0:
            raise SchemaError(
                f"config key 'tpu.probe.trend_rise_factor': must be > 1 — a "
                f"factor <= 1 alerts on every healthy cycle — got {trend_rise}"
            )
        if not 1 <= trend_recent < trend_window:
            raise SchemaError(
                f"config key 'tpu.probe.trend_recent': need trend_window > "
                f"trend_recent >= 1, got recent={trend_recent} window={trend_window}"
            )
        if not trend_recent + 1 <= trend_min_history <= trend_window:
            raise SchemaError(
                f"config key 'tpu.probe.trend_min_history': need trend_recent+1 <= "
                f"trend_min_history <= trend_window (the anchor freezes at window "
                f"samples), got min_history={trend_min_history} recent={trend_recent} "
                f"window={trend_window}"
            )
        return cls(
            backend=backend,
            resource_key=_opt_str(raw, "resource_key", "tpu", default_key),
            prefilter=_opt_bool(raw, "prefilter", "tpu", True),
            topology_label=_opt_str(raw, "topology_label", "tpu", cls.topology_label),
            accelerator_label=_opt_str(raw, "accelerator_label", "tpu", cls.accelerator_label),
            probe_enabled=_opt_bool(probe, "enabled", "tpu.probe", False),
            probe_interval_seconds=_opt_num(probe, "interval_seconds", "tpu.probe", 30.0),
            probe_status_port=_opt_int(probe, "status_port", "tpu.probe", 0),
            probe_status_auth_token=_opt_str(probe, "status_auth_token", "tpu.probe", None) or None,
            probe_payload_bytes=_opt_int(probe, "payload_bytes", "tpu.probe", 4 * 1024 * 1024),
            probe_rtt_warn_ms=_opt_num(probe, "rtt_warn_ms", "tpu.probe", 50.0),
            probe_matmul_size=_opt_int(probe, "matmul_size", "tpu.probe", 1024),
            probe_matmul_inner_iters=_opt_int(probe, "matmul_inner_iters", "tpu.probe", 8),
            probe_hbm_bytes=_opt_int(probe, "hbm_bytes", "tpu.probe", 256 * 1024 * 1024),
            probe_hbm_write_enabled=_opt_bool(probe, "hbm_write_enabled", "tpu.probe", True),
            expected_chips_per_host=_opt_int(probe, "expected_chips_per_host", "tpu.probe", 0),
            probe_links_enabled=_opt_bool(probe, "links_enabled", "tpu.probe", False),
            probe_link_rtt_factor=_opt_num(probe, "link_rtt_factor", "tpu.probe", 3.0),
            probe_link_rtt_floor_ms=_opt_num(probe, "link_rtt_floor_ms", "tpu.probe", 0.05),
            probe_trend_enabled=_opt_bool(probe, "trend_enabled", "tpu.probe", True),
            probe_trend_window=trend_window,
            probe_trend_recent=trend_recent,
            probe_trend_drop_factor=trend_drop,
            probe_trend_rise_factor=trend_rise,
            probe_trend_min_history=trend_min_history,
            probe_multislice_enabled=_opt_bool(probe, "multislice_enabled", "tpu.probe", False),
            probe_multislice_slices=_opt_int(probe, "multislice_slices", "tpu.probe", 0),
            probe_multislice_pair_localization=_opt_bool(
                probe, "multislice_pair_localization", "tpu.probe", True
            ),
            probe_profile_dir=_opt_str(probe, "profile_dir", "tpu.probe", None),
            node_watch_enabled=_opt_bool(node_watch, "enabled", "tpu.node_watch", False),
            node_watch_label_selector=_opt_str(node_watch, "label_selector", "tpu.node_watch", None),
            remediation_enabled=_opt_bool(remediation, "enabled", "tpu.remediation", False),
            remediation_dry_run=_opt_bool(remediation, "dry_run", "tpu.remediation", True),
            remediation_cordon=_opt_bool(remediation, "cordon", "tpu.remediation", True),
            remediation_taint_key=_opt_str(remediation, "taint_key", "tpu.remediation", cls.remediation_taint_key),
            remediation_taint_value=_opt_str(remediation, "taint_value", "tpu.remediation", cls.remediation_taint_value),
            remediation_taint_effect=taint_effect,
            remediation_confirm_cycles=remediation_confirm,
            remediation_cooldown_seconds=remediation_cooldown,
            remediation_max_actions_per_hour=remediation_rate,
            remediation_max_quarantined_nodes=remediation_budget,
        )


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """The ``ingest:`` section — net-new sharded watch ingest.

    ``shards`` watch streams (each its own connection + resume version,
    partitioned by a stable hash of the pod UID) feed one bounded MPSC
    queue drained in batches of up to ``batch_max`` events through
    ``EventPipeline.process_batch``. ``shards: 1`` runs the SAME queue +
    batch machinery over a single stream — there is no unsharded code
    path. Shard partition push-down rides a ``shard`` query param the
    in-repo mock apiserver (and a shard-aware proxy) honors; a stock
    apiserver ignores it and each stream drops non-owned events
    client-side, so shards > 1 against a stock apiserver multiplies
    watch-stream load by the shard count (see ARCHITECTURE.md "Sharded
    ingest").
    """

    shards: int = 1
    batch_max: int = 128
    queue_capacity: int = 8192
    # multi-process shard readers (watch/procpool.py): split the shard
    # streams across `processes` OS worker processes, each owning its
    # streams + prefilter + per-shard rv checkpoint, feeding the parent's
    # pipeline over a length-prefixed pipe wire. 0 = in-process (today's
    # behavior, the io_threads-0 legacy-reference pattern). Requires
    # checkpointing (state.checkpoint_path) — the crash-respawn resume
    # contract needs durable per-shard rv lines (AppConfig cross-check).
    processes: int = 0
    # watch-frame prefilter mode (native/scanner.py make_scanner):
    # auto (native when it builds, Python otherwise — one INFO on the
    # downgrade) | native (pinned: same fallback, WARNING) | python |
    # off (full json.loads on every frame — the reference behavior).
    # tpu.prefilter: false (legacy bool) forces off.
    prefilter: str = "auto"

    def resolved_prefilter(self, tpu_prefilter: bool = True) -> str:
        """Effective prefilter mode: the legacy ``tpu.prefilter: false``
        bool still forces ``off`` (one release of overlap)."""
        return "off" if not tpu_prefilter else self.prefilter

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "IngestConfig":
        _check_known(
            raw,
            ("shards", "batch_max", "queue_capacity", "processes", "prefilter"),
            "ingest",
        )
        shards = _opt_int(raw, "shards", "ingest", 1)
        if shards < 1:
            raise SchemaError(f"config key 'ingest.shards': must be >= 1, got {shards}")
        batch_max = _opt_int(raw, "batch_max", "ingest", 128)
        if batch_max < 1:
            raise SchemaError(f"config key 'ingest.batch_max': must be >= 1, got {batch_max}")
        queue_capacity = _opt_int(raw, "queue_capacity", "ingest", 8192)
        if queue_capacity < batch_max:
            raise SchemaError(
                f"config key 'ingest.queue_capacity': must be >= batch_max "
                f"({batch_max}), got {queue_capacity} (a queue smaller than one "
                f"batch can never fill a batch and would throttle the drain)"
            )
        processes = _opt_int(raw, "processes", "ingest", 0)
        if processes < 0:
            raise SchemaError(
                f"config key 'ingest.processes': must be >= 0 (0 = in-process), got {processes}"
            )
        if processes > shards:
            raise SchemaError(
                f"config key 'ingest.processes': must be <= ingest.shards "
                f"({shards}), got {processes} (a worker process owns >= 1 whole "
                f"shard stream; more processes than shards would idle)"
            )
        raw_prefilter = raw.get("prefilter")
        if isinstance(raw_prefilter, bool):
            # YAML 1.1 reads a bare `off`/`on` as a boolean — honor the
            # obvious intent (and the legacy tpu.prefilter bool semantics)
            # instead of rejecting the natural spelling
            prefilter = "auto" if raw_prefilter else "off"
        else:
            prefilter = _opt_str(raw, "prefilter", "ingest", "auto")
        if prefilter not in VALID_PREFILTER_MODES:
            raise SchemaError(
                f"config key 'ingest.prefilter': must be one of "
                f"{', '.join(VALID_PREFILTER_MODES)}, got {prefilter!r}"
            )
        return cls(
            shards=shards,
            batch_max=batch_max,
            queue_capacity=queue_capacity,
            processes=processes,
            prefilter=prefilter,
        )


@dataclasses.dataclass(frozen=True)
class TraceFederationConfig:
    """The ``trace.federation:`` sub-section — cross-cluster trace
    joining at a federator (trace/federation.py): upstream subscribers
    negotiate ``?trace=1`` so sampled deltas carry their journey's
    compact trace in-band; the federator joins them with the
    ``serve_wire``/``federate_merge``/``global_serve`` stages, serves the
    fleet-wide journey at ``/debug/trace?uid=`` and slowest-stage
    attribution at ``/debug/trace/diagnosis``, and emits the labeled
    ``trace_stage_seconds{stage=,upstream=}`` histograms the SLO and
    health planes consume. Requires ``trace.enabled`` AND
    ``federation.enabled`` (schema-enforced pairing).
    """

    enabled: bool = False
    # keep the upstream's forwarded local spans in the joined traces;
    # false bounds federator memory to the cross-cluster stages and the
    # stitched query fetches upstream spans lazily from the upstream's
    # serve-port /debug/trace (partial answer when it is unreachable)
    forward_spans: bool = True
    # joined journeys retained for stitched queries / diagnosis examples
    # (newest wins — the production memory bound)
    max_joined: int = 256

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "TraceFederationConfig":
        path = "trace.federation"
        _check_known(raw, ("enabled", "forward_spans", "max_joined"), path)
        max_joined = _opt_int(raw, "max_joined", path, 256)
        if max_joined < 1:
            raise SchemaError(
                f"config key '{path}.max_joined': must be >= 1 (use "
                f"{path}.enabled: false to turn trace joining off), got {max_joined}"
            )
        return cls(
            enabled=_opt_bool(raw, "enabled", path, False),
            forward_spans=_opt_bool(raw, "forward_spans", path, True),
            max_joined=max_joined,
        )


@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """The ``trace:`` section — net-new end-to-end event tracing plane
    (trace/trace.py): head-sampled span trees across every hand-off an
    event crosses (shard stream -> queue -> pipeline -> lane -> connection
    borrow -> POST), with always-sample for anomalous terminals.

    ``sample_rate: N`` keeps every Nth pod event per shard stream
    (deterministic modular counter); ``0`` disables head sampling while
    anomaly capture keeps recording. Unsampled events pay only the
    sampling branch — no allocation, no lock (the <3% overhead budget the
    bench smoke gates).

    ``federation:`` extends sampled journeys across the serve/federation
    wire (see ``TraceFederationConfig``).
    """

    enabled: bool = True
    sample_rate: int = 256
    ring_size: int = 512
    federation: TraceFederationConfig = dataclasses.field(
        default_factory=TraceFederationConfig
    )

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "TraceConfig":
        _check_known(raw, ("enabled", "sample_rate", "ring_size", "federation"), "trace")
        sample_rate = _opt_int(raw, "sample_rate", "trace", 256)
        if sample_rate < 0:
            raise SchemaError(
                f"config key 'trace.sample_rate': must be >= 0 (0 = anomaly-only), got {sample_rate}"
            )
        ring_size = _opt_int(raw, "ring_size", "trace", 512)
        if ring_size < 1:
            raise SchemaError(
                f"config key 'trace.ring_size': must be >= 1 (use trace.enabled: false to turn tracing off), got {ring_size}"
            )
        federation = raw.get("federation") or {}
        _expect(federation, (dict,), "trace.federation")
        return cls(
            enabled=_opt_bool(raw, "enabled", "trace", True),
            sample_rate=sample_rate,
            ring_size=ring_size,
            federation=TraceFederationConfig.from_raw(federation),
        )


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """The ``serve:`` section — net-new fleet-state serving plane
    (serve/): a kube-apiserver-style watch cache over the pipeline's
    output. ``GET /serve/fleet`` answers a ``{rv, objects}`` snapshot;
    ``?watch=1&rv=N`` streams resumable deltas from that rv, with
    latest-wins per-key compaction once a subscriber's backlog exceeds
    ``queue_depth`` and 410-Gone resync once its resume token falls
    behind ``compact_horizon`` journaled deltas. Streams ride the
    encode-once broadcast core: each delta's wire frame is serialized
    once at publish and ``io_threads`` epoll loops write the shared
    bytes to every subscriber, buffering slow clients up to
    ``sub_buffer_bytes`` before lag shedding (ARCHITECTURE.md
    "Serving plane").
    """

    enabled: bool = False
    port: int = 0  # 0 = bind an ephemeral port (tests/smoke); fixed in prod
    max_subscribers: int = 5000
    # per-subscriber backlog bound: pulls with more pending deltas than
    # this are compacted latest-wins per key before delivery
    queue_depth: int = 128
    # delta-journal length: resume tokens older than this many deltas get
    # 410 Gone and must re-snapshot (the serve-side etcd compaction)
    compact_horizon: int = 8192
    # broadcast event-loop pool size: ?watch=1 streams are handed off the
    # HTTP thread to selectors-based loops writing publish-time-encoded
    # frame bytes (one loop drives thousands of streams; more loops
    # spread send() syscall load). 0 = legacy thread-per-connection
    # streaming (one OS thread per stream — debugging/comparison only)
    io_threads: int = 1
    # per-subscriber outbound buffer budget (bytes): a slow client's
    # unsent frames queue up to this, then the loop stops pulling for it
    # and its lag resolves through read-time latest-wins compaction
    sub_buffer_bytes: int = 1 << 20
    # fleet-state core selector: "auto"/"on" = the columnar core
    # (serve/columns.py — parts + int columns, the million-object
    # representation), "off" = the legacy dict-of-dicts core (the A/B
    # reference; byte-identical wire either way)
    columnar: str = "auto"

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "ServeConfig":
        _check_known(
            raw,
            ("enabled", "port", "max_subscribers", "queue_depth", "compact_horizon",
             "io_threads", "sub_buffer_bytes", "columnar"),
            "serve",
        )
        port = _opt_int(raw, "port", "serve", 0)
        if port < 0 or port > 65535:
            raise SchemaError(f"config key 'serve.port': must be 0..65535, got {port}")
        max_subscribers = _opt_int(raw, "max_subscribers", "serve", 5000)
        if max_subscribers < 1:
            raise SchemaError(
                f"config key 'serve.max_subscribers': must be >= 1 (use serve.enabled: "
                f"false to turn the plane off), got {max_subscribers}"
            )
        queue_depth = _opt_int(raw, "queue_depth", "serve", 128)
        if queue_depth < 1:
            raise SchemaError(f"config key 'serve.queue_depth': must be >= 1, got {queue_depth}")
        compact_horizon = _opt_int(raw, "compact_horizon", "serve", 8192)
        if compact_horizon < queue_depth:
            raise SchemaError(
                f"config key 'serve.compact_horizon': must be >= queue_depth "
                f"({queue_depth}), got {compact_horizon} (a horizon shorter than one "
                f"subscriber queue would 410 subscribers before lag shedding could engage)"
            )
        io_threads = _opt_int(raw, "io_threads", "serve", 1)
        if io_threads < 0 or io_threads > 64:
            raise SchemaError(
                f"config key 'serve.io_threads': must be 0..64 (0 = legacy "
                f"thread-per-connection streaming), got {io_threads}"
            )
        sub_buffer_bytes = _opt_int(raw, "sub_buffer_bytes", "serve", 1 << 20)
        if sub_buffer_bytes < 4096:
            raise SchemaError(
                f"config key 'serve.sub_buffer_bytes': must be >= 4096 (one "
                f"outbound buffer must hold at least a frame), got {sub_buffer_bytes}"
            )
        columnar = raw.get("columnar", "auto")
        if columnar not in VALID_COLUMNAR_MODES:
            raise SchemaError(
                f"config key 'serve.columnar': must be one of "
                f"{'/'.join(VALID_COLUMNAR_MODES)} ('auto' = on; 'off' keeps the "
                f"legacy dict-of-dicts core), got {columnar!r}"
            )
        return cls(
            enabled=_opt_bool(raw, "enabled", "serve", False),
            port=port,
            max_subscribers=max_subscribers,
            queue_depth=queue_depth,
            compact_horizon=compact_horizon,
            io_threads=io_threads,
            sub_buffer_bytes=sub_buffer_bytes,
            columnar=columnar,
        )


#: accepted serve.columnar modes ("auto" resolves to the columnar core)
VALID_COLUMNAR_MODES = ("auto", "on", "off")


#: accepted history.fsync policies (mirrored by history/wal.py)
VALID_FSYNC_POLICIES = ("never", "interval", "always")


@dataclasses.dataclass(frozen=True)
class HistoryConfig:
    """The ``history:`` section — net-new durable fleet history plane
    (history/): a segmented, CRC-framed WAL under the serving plane's
    delta journal. Every FleetView delta persists; recovery rebuilds the
    view at boot (same instance id, same monotonic rv line) so resume
    tokens survive restarts; ``GET /serve/fleet?at=rv`` reconstructs
    historical snapshots; ``scripts/history_replay.py`` turns any
    capture into a deterministic regression fixture (ARCHITECTURE.md
    "History plane"). Requires ``serve.enabled`` (the WAL records the
    serving plane's deltas).
    """

    enabled: bool = False
    dir: Optional[str] = None  # required when enabled
    # durability knob: "never" (page cache only — a lost checkpoint costs
    # replayable history, not correctness), "interval" (default: one
    # fsync per fsync_interval_seconds), "always" (per write batch)
    fsync: str = "interval"
    fsync_interval_seconds: float = 1.0
    # rotation: the active segment seals once it outgrows either bound;
    # every new segment opens with a full snapshot record
    segment_max_bytes: int = 8 * 1024 * 1024
    segment_max_age_seconds: float = 3600.0
    # retention: newest N segments kept; the oldest retained segment's
    # opening snapshot is the durable horizon (410 past it)
    retain_segments: int = 8

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "HistoryConfig":
        _check_known(
            raw,
            ("enabled", "dir", "fsync", "fsync_interval_seconds",
             "segment_max_bytes", "segment_max_age_seconds", "retain_segments"),
            "history",
        )
        enabled = _opt_bool(raw, "enabled", "history", False)
        directory = _opt_str(raw, "dir", "history", None)
        if enabled and not directory:
            raise SchemaError(
                "config key 'history.dir': required when history.enabled (the WAL "
                "needs a directory to persist segments into)"
            )
        fsync = _opt_str(raw, "fsync", "history", "interval")
        if fsync not in VALID_FSYNC_POLICIES:
            raise SchemaError(
                f"config key 'history.fsync': must be one of "
                f"{', '.join(VALID_FSYNC_POLICIES)}, got {fsync!r}"
            )
        fsync_interval = _opt_num(raw, "fsync_interval_seconds", "history", 1.0)
        if fsync_interval <= 0:
            raise SchemaError(
                f"config key 'history.fsync_interval_seconds': must be > 0, got {fsync_interval}"
            )
        segment_max_bytes = _opt_int(raw, "segment_max_bytes", "history", 8 * 1024 * 1024)
        if segment_max_bytes < 4096:
            raise SchemaError(
                f"config key 'history.segment_max_bytes': must be >= 4096, got "
                f"{segment_max_bytes} (a segment smaller than its own opening "
                f"snapshot record rotates on every batch)"
            )
        segment_max_age = _opt_num(raw, "segment_max_age_seconds", "history", 3600.0)
        if segment_max_age <= 0:
            raise SchemaError(
                f"config key 'history.segment_max_age_seconds': must be > 0, got {segment_max_age}"
            )
        retain = _opt_int(raw, "retain_segments", "history", 8)
        if retain < 2:
            raise SchemaError(
                f"config key 'history.retain_segments': must be >= 2 (the active "
                f"segment plus at least one sealed anchor), got {retain}"
            )
        return cls(
            enabled=enabled,
            dir=directory,
            fsync=fsync,
            fsync_interval_seconds=fsync_interval,
            segment_max_bytes=segment_max_bytes,
            segment_max_age_seconds=segment_max_age,
            retain_segments=retain,
        )


@dataclasses.dataclass(frozen=True)
class MetricsConfig:
    """The ``metrics:`` section — observability-plane knobs.

    ``process_export``: with worker processes live (``ingest.processes``
    / ``federation.processes``), each worker ships its full registry
    sample (+ completed traces) on its periodic stats frame and the
    parent folds it under a ``process`` label — one scrape sees the
    whole fleet. Off = workers ship only the ad-hoc stats fields
    (pre-PR-18 wire), for the bench A/B and byte-budget-critical
    deploys.

    ``process_top_series``: how many hottest (by recent rate) process-
    labeled counter series ``/debug/processes`` reports per worker.

    The PR-10 ``legacy_suffix_names`` migration flag is gone: the
    suffix-mangled series (``federation_upstream_lag_*_<name>``,
    ``serve_snapshot_cache_*_{json,msgpack}``) were promised one
    release of overlap and the labeled forms have been canonical since.
    """

    process_export: bool = True
    process_top_series: int = 5

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "MetricsConfig":
        if "legacy_suffix_names" in raw:
            raise SchemaError(
                "config key 'metrics.legacy_suffix_names': removed — the "
                "suffix-mangled series are gone; use the labeled forms "
                "(federation_upstream_lag_*{upstream=...}, "
                "serve_snapshot_cache_*{codec=...})"
            )
        _check_known(raw, ("process_export", "process_top_series"), "metrics")
        top = _opt_int(raw, "process_top_series", "metrics", 5)
        if top < 1:
            raise SchemaError(
                f"config key 'metrics.process_top_series': must be >= 1, got {top}"
            )
        return cls(
            process_export=_opt_bool(raw, "process_export", "metrics", True),
            process_top_series=top,
        )


#: accepted SLO objective kinds (slo/engine.py mirrors the semantics)
VALID_SLO_KINDS = ("quantile", "gauge", "ratio")

#: SLO objective names become Prometheus label values and /debug/slo keys
_SLO_NAME_RE = re.compile(r"^[a-zA-Z0-9_.\-]{1,64}$")


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One declared service-level objective (``slo.objectives[]``).

    Three kinds, keyed by which spec field is present in the raw entry:

    - ``quantile`` (``histogram:`` + ``max_seconds:``): a request-based
      latency SLO — the error rate over a window is the fraction of
      observations ABOVE ``max_seconds`` (computed from cumulative
      bucket deltas, so it is exact at bucket resolution); ``quantile``
      only picks which windowed percentile /debug/slo reports.
    - ``gauge`` (``gauge:`` + ``max:``): a state SLO — the error rate is
      the fraction of ring ticks on which the gauge (max across its
      label children) exceeded ``max``.
    - ``ratio`` (``ratio_good:`` + ``ratio_total:`` + ``min_ratio:``):
      a success-ratio SLO over two counters — the error rate is
      ``1 - Δgood/Δtotal`` over the window.

    ``target`` is the compliance target; the error budget is
    ``1 - target`` and a burn rate of 1.0 means the budget is being
    spent exactly as fast as it accrues (ratio objectives budget off
    ``min_ratio`` directly).
    """

    name: str
    kind: str
    metric: str = ""  # histogram name (quantile) / gauge name (gauge)
    quantile: float = 0.99
    max_seconds: float = 0.0  # quantile threshold
    max_value: float = 0.0  # gauge threshold
    good: str = ""  # ratio numerator counter
    total: str = ""  # ratio denominator counter
    min_ratio: float = 0.999
    target: float = 0.99

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any], path: str) -> "SloObjective":
        _check_known(
            raw,
            ("name", "histogram", "quantile", "max_seconds", "gauge", "max",
             "ratio_good", "ratio_total", "min_ratio", "target"),
            path,
        )
        name = _opt_str(raw, "name", path, None)
        if not name or not _SLO_NAME_RE.match(name):
            raise SchemaError(
                f"config key '{path}.name': required, 1-64 chars of [a-zA-Z0-9_.-] "
                f"(it becomes the slo_burn_rate{{objective=...}} label value), got {name!r}"
            )
        specs = [k for k in ("histogram", "gauge", "ratio_good") if raw.get(k)]
        if len(specs) != 1:
            raise SchemaError(
                f"config key '{path}': exactly one of histogram:/gauge:/ratio_good: "
                f"must be set (got {specs or 'none'})"
            )
        target = _opt_num(raw, "target", path, 0.99)
        if not 0.0 < target < 1.0:
            raise SchemaError(
                f"config key '{path}.target': must be in (0, 1) — the error budget "
                f"is 1 - target — got {target}"
            )
        if specs[0] == "histogram":
            quantile = _opt_num(raw, "quantile", path, 0.99)
            if not 0.0 < quantile <= 1.0:
                raise SchemaError(f"config key '{path}.quantile': must be in (0, 1], got {quantile}")
            max_seconds = _opt_num(raw, "max_seconds", path, 0.0)
            if max_seconds <= 0:
                raise SchemaError(
                    f"config key '{path}.max_seconds': required > 0 for a histogram objective"
                )
            return cls(name=name, kind="quantile", metric=_opt_str(raw, "histogram", path, ""),
                       quantile=quantile, max_seconds=max_seconds, target=target)
        if specs[0] == "gauge":
            if "max" not in raw or raw["max"] is None:
                raise SchemaError(f"config key '{path}.max': required for a gauge objective")
            return cls(name=name, kind="gauge", metric=_opt_str(raw, "gauge", path, ""),
                       max_value=_opt_num(raw, "max", path, 0.0), target=target)
        total = _opt_str(raw, "ratio_total", path, None)
        if not total:
            raise SchemaError(
                f"config key '{path}.ratio_total': required alongside ratio_good"
            )
        min_ratio = _opt_num(raw, "min_ratio", path, 0.999)
        if not 0.0 < min_ratio < 1.0:
            raise SchemaError(
                f"config key '{path}.min_ratio': must be in (0, 1), got {min_ratio}"
            )
        # the budget defaults to the ratio floor itself (budget =
        # 1 - min_ratio), but an EXPLICIT target: is honored — silently
        # overriding an accepted key would page at the wrong rate
        ratio_target = target if raw.get("target") is not None else min_ratio
        return cls(name=name, kind="ratio", good=_opt_str(raw, "ratio_good", path, ""),
                   total=total, min_ratio=min_ratio, target=ratio_target)


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """The ``slo:`` section — net-new SLO/burn-rate engine (slo/): a
    bounded in-process timeseries ring samples every registered metric
    on a tick; config-declared objectives are evaluated with the
    standard two-window burn rate (fast + slow, both over the error
    budget ``1 - target``; breaching requires BOTH windows hot — the
    page-worthy "burning fast AND not a blip" rule). Results serve at
    ``/debug/slo``, export as ``slo_burn_rate{objective=,window=}`` /
    ``slo_breaching{objective=}``, and fold into the /healthz BODY
    (degraded, never the liveness verdict — restarting a watcher does
    not refund an error budget).
    """

    enabled: bool = False
    tick_seconds: float = 5.0
    # ring capacity in ticks; must cover the slow window
    ring_size: int = 1024
    fast_window_seconds: float = 300.0
    slow_window_seconds: float = 3600.0
    # both windows' burn rates must exceed this to breach (1.0 = budget
    # being spent exactly at the sustainable rate)
    burn_threshold: float = 1.0
    objectives: tuple = ()  # tuple[SloObjective, ...]

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "SloConfig":
        path = "slo"
        _check_known(
            raw,
            ("enabled", "tick_seconds", "ring_size", "fast_window_seconds",
             "slow_window_seconds", "burn_threshold", "objectives"),
            path,
        )
        enabled = _opt_bool(raw, "enabled", path, False)
        tick = _opt_num(raw, "tick_seconds", path, 5.0)
        if tick <= 0:
            raise SchemaError(f"config key '{path}.tick_seconds': must be > 0, got {tick}")
        fast = _opt_num(raw, "fast_window_seconds", path, 300.0)
        slow = _opt_num(raw, "slow_window_seconds", path, 3600.0)
        if not tick <= fast < slow:
            raise SchemaError(
                f"config key '{path}': need tick_seconds <= fast_window_seconds < "
                f"slow_window_seconds, got tick={tick} fast={fast} slow={slow}"
            )
        ring_size = _opt_int(raw, "ring_size", path, 1024)
        if ring_size < 2:
            raise SchemaError(f"config key '{path}.ring_size': must be >= 2, got {ring_size}")
        if ring_size * tick < slow:
            raise SchemaError(
                f"config key '{path}.ring_size': {ring_size} ticks x {tick}s does not "
                f"cover slow_window_seconds={slow} — the slow burn window would "
                f"silently evaluate over less history than it claims"
            )
        burn_threshold = _opt_num(raw, "burn_threshold", path, 1.0)
        if burn_threshold <= 0:
            raise SchemaError(
                f"config key '{path}.burn_threshold': must be > 0, got {burn_threshold}"
            )
        raw_objectives = raw.get("objectives") or ()
        _expect(raw_objectives, (list, tuple), f"{path}.objectives")
        objectives = []
        seen = set()
        for i, entry in enumerate(raw_objectives):
            entry_path = f"{path}.objectives[{i}]"
            _expect(entry, (dict,), entry_path)
            objective = SloObjective.from_raw(entry, entry_path)
            if objective.name in seen:
                raise SchemaError(
                    f"config key '{entry_path}.name': duplicate objective name "
                    f"{objective.name!r}"
                )
            seen.add(objective.name)
            objectives.append(objective)
        if enabled and not objectives:
            raise SchemaError(
                "config key 'slo.objectives': at least one objective is required "
                "when slo.enabled (an SLO engine with nothing to evaluate)"
            )
        return cls(
            enabled=enabled,
            tick_seconds=tick,
            ring_size=ring_size,
            fast_window_seconds=fast,
            slow_window_seconds=slow,
            burn_threshold=burn_threshold,
            objectives=tuple(objectives),
        )


#: signal sources the health detector may fuse (health.sources.*)
VALID_HEALTH_SOURCES = ("probe", "phase", "freshness", "trace")


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """The ``health:`` section — net-new straggler & node-health detection
    plane (health/): fuses signals the platform already produces — probe
    RTTs + suspect-link findings, per-upstream freshness watermarks, pod
    phase-transition latencies from the fleet view, trace stage outliers —
    into per-node / per-slice / per-upstream verdicts using PEER-RELATIVE
    outlier scoring (a node is a straggler relative to its slice peers,
    never against an absolute threshold). Verdicts walk a config-declared
    escalation state machine (healthy -> suspect -> confirmed ->
    remediating) with confirm-cycle hysteresis and clean-cycle decay;
    confirmed NODE verdicts feed the existing budgeted (dry-run by
    default) remediation actuator. Full detail at ``GET /debug/health``;
    ``node_health_score{node=}`` / ``health_state{node=,state=}`` labeled
    gauges; the verdict folds into the /healthz BODY (degraded, never
    liveness). See ARCHITECTURE.md "Health & remediation plane".
    """

    enabled: bool = False
    tick_seconds: float = 5.0
    # peer-relative robust z-score (deviation from the peer median in
    # MAD units) at which a subject turns suspicious
    suspect_z: float = 4.0
    # consecutive suspicious ticks before suspect escalates to confirmed
    # (one clean tick resets — mirrors remediate/policy.py)
    confirm_cycles: int = 3
    # consecutive CLEAN ticks before a confirmed/remediating subject
    # de-escalates back to healthy (absence of signal is NOT clean)
    decay_cycles: int = 2
    # which signal planes the detector reads (each requires its plane)
    source_probe: bool = True
    source_phase: bool = True
    source_freshness: bool = False
    source_trace: bool = True

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "HealthConfig":
        path = "health"
        _check_known(
            raw,
            ("enabled", "tick_seconds", "suspect_z", "confirm_cycles",
             "decay_cycles", "sources"),
            path,
        )
        enabled = _opt_bool(raw, "enabled", path, False)
        tick = _opt_num(raw, "tick_seconds", path, 5.0)
        if tick <= 0:
            raise SchemaError(f"config key '{path}.tick_seconds': must be > 0, got {tick}")
        suspect_z = _opt_num(raw, "suspect_z", path, 4.0)
        if suspect_z <= 0:
            raise SchemaError(
                f"config key '{path}.suspect_z': must be > 0, got {suspect_z} "
                f"(a non-positive threshold would call every subject a straggler)"
            )
        confirm = _opt_int(raw, "confirm_cycles", path, 3)
        if confirm < 1:
            raise SchemaError(f"config key '{path}.confirm_cycles': must be >= 1, got {confirm}")
        decay = _opt_int(raw, "decay_cycles", path, 2)
        if decay < 1:
            raise SchemaError(f"config key '{path}.decay_cycles': must be >= 1, got {decay}")
        sources = raw.get("sources") or {}
        _expect(sources, (dict,), f"{path}.sources")
        _check_known(sources, VALID_HEALTH_SOURCES, f"{path}.sources")
        cfg = cls(
            enabled=enabled,
            tick_seconds=tick,
            suspect_z=suspect_z,
            confirm_cycles=confirm,
            decay_cycles=decay,
            source_probe=_opt_bool(sources, "probe", f"{path}.sources", True),
            source_phase=_opt_bool(sources, "phase", f"{path}.sources", True),
            source_freshness=_opt_bool(sources, "freshness", f"{path}.sources", False),
            source_trace=_opt_bool(sources, "trace", f"{path}.sources", True),
        )
        if enabled and not (
            cfg.source_probe or cfg.source_phase or cfg.source_freshness or cfg.source_trace
        ):
            raise SchemaError(
                "config key 'health.sources': at least one source must be enabled "
                "when health.enabled (a detector with nothing to fuse)"
            )
        return cfg


#: accepted analytics.backend values (analytics/backend.py mirrors this —
#: the schema is the dependency-light layer, so it re-declares the
#: vocabulary instead of importing numpy/jax at config-load time)
VALID_ANALYTICS_BACKENDS = ("auto", "jax", "numpy")


@dataclasses.dataclass(frozen=True)
class AnalyticsConfig:
    """The ``analytics:`` section — net-new JAX-vectorized fleet
    analytics & what-if engine (analytics/): the FleetView encoded into
    dense integer columns (stable interning dictionaries, incrementally
    maintained from the delta stream), jitted kernels over a jnp/numpy
    backend seam (vectorized slice aggregates cross-checked exactly
    against the incremental counters, quorum math, topology scoring),
    and batched placement what-ifs ("drain cluster A — which slices
    lose quorum?") at ``GET /serve/analytics``. Requires
    ``serve.enabled`` (the columns are the serving plane's view).
    See ARCHITECTURE.md "Analytics plane".
    """

    enabled: bool = False
    # array substrate: auto (jax when importable AND executable, else
    # numpy), jax (same probe, WARNs on fallback), numpy (never touches
    # jax — debugging / byte-stable baselines). Kernel RESULTS are
    # identical across backends (integer contract, parity-suite pinned).
    backend: str = "auto"
    # per-request scenario cap for /serve/analytics?scenarios= (400 past
    # it) — one request's mask matrix is [scenarios x workers]
    max_scenarios: int = 16
    # run the vectorized-vs-incremental slice-aggregate cross-check on
    # every request (cheap: one extra segment-sum) and surface failures
    # via analytics_crosscheck_failures + the response body
    crosscheck: bool = True

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "AnalyticsConfig":
        path = "analytics"
        _check_known(raw, ("enabled", "backend", "max_scenarios", "crosscheck"), path)
        backend = _opt_str(raw, "backend", path, "auto")
        if backend not in VALID_ANALYTICS_BACKENDS:
            raise SchemaError(
                f"config key '{path}.backend': must be one of "
                f"{', '.join(VALID_ANALYTICS_BACKENDS)}, got {backend!r}"
            )
        max_scenarios = _opt_int(raw, "max_scenarios", path, 16)
        if max_scenarios < 1:
            raise SchemaError(
                f"config key '{path}.max_scenarios': must be >= 1, got {max_scenarios}"
            )
        return cls(
            enabled=_opt_bool(raw, "enabled", path, False),
            backend=backend,
            max_scenarios=max_scenarios,
            crosscheck=_opt_bool(raw, "crosscheck", path, True),
        )


def metric_safe_name(name: str) -> str:
    """Cluster/upstream name -> metric-name- and filename-safe form
    (Prometheus charset). The ONE sanitizer the federation plane uses for
    per-upstream gauge suffixes and resume-token filenames — the schema
    validates uniqueness against exactly this mapping, so two upstreams
    can never alias one gauge or one token file."""
    return re.sub(r"[^0-9a-zA-Z_]", "_", name)


#: serve wire codec preferences a federation subscriber may be pinned to
#: (mirrors federate/client.py: "auto" offers msgpack + JSON fallback)
VALID_SERVE_CODECS = ("auto", "json", "msgpack")


@dataclasses.dataclass(frozen=True)
class FederationUpstream:
    """One upstream serving plane the federation tier subscribes to."""

    url: str
    name: str
    token: Optional[str] = None  # upstream bearer (watcher.status_auth_token there)


@dataclasses.dataclass(frozen=True)
class FederationConfig:
    """The ``federation:`` section — net-new multi-cluster fan-in plane
    (federate/): one resume-protocol subscriber per upstream serving
    plane (e.g. the watchers of several GKE clusters / v5p pod-slices),
    merged into the LOCAL FleetView under ``(kind, "<cluster>/<key>")``
    keys so the existing serve/history planes republish the global fleet
    (encode-once fan-out, restart-surviving resume tokens, ?at= time
    travel — all on the merged view). Requires ``serve.enabled``.
    See ARCHITECTURE.md "Federation plane".
    """

    enabled: bool = False
    upstreams: tuple = ()  # tuple[FederationUpstream, ...]
    # an upstream with no frame (delta or SYNC heartbeat) for this long is
    # stale: /healthz degrades, and drop_stale decides its objects' fate
    stale_after_seconds: float = 10.0
    # reconnect/resync backoff base (jittered, exponential to ~30 s)
    resync_backoff_seconds: float = 1.0
    # True: a dark upstream's objects are DELETED from the global view
    # (consumers see only live state; recovery re-snapshots them back).
    # False (default): keep last-known state, surface staleness via
    # /healthz + federation_upstream_stale — zero rv churn on a blip.
    drop_stale: bool = False
    # serve wire codec preference for the upstream subscribers: "auto"
    # (default) offers msgpack and falls back transparently to JSON when
    # the peer or the local import lacks it (the downgrade is logged
    # once per upstream); "msgpack" is the same offer with a WARNING
    # posture; "json" never offers msgpack (debugging / byte-stable
    # wire captures). The codec changes wire bytes only — decoded
    # frames are identical.
    codec: str = "auto"
    # merge-worker OS processes for the fan-in (0 = today's in-process
    # path, byte for byte). > 0 shards upstreams across supervised
    # worker processes by hash(cluster): each worker consumes its
    # upstreams' frames and ships prepared view batches to the parent
    # sequencer over the length-prefixed msgpack pipe — the ingest
    # tier's processes knob, applied to the fan-in (ARCHITECTURE.md
    # "Sharded fan-in"). More processes than upstreams waste nothing
    # (surplus workers own zero clusters and exit idle-cheap).
    processes: int = 0

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "FederationConfig":
        path = "federation"
        _check_known(
            raw,
            ("enabled", "upstreams", "stale_after_seconds",
             "resync_backoff_seconds", "drop_stale", "codec", "processes"),
            path,
        )
        enabled = _opt_bool(raw, "enabled", path, False)
        raw_upstreams = raw.get("upstreams") or ()
        _expect(raw_upstreams, (list, tuple), f"{path}.upstreams")
        upstreams = []
        seen_names = set()
        for i, entry in enumerate(raw_upstreams):
            entry_path = f"{path}.upstreams[{i}]"
            _expect(entry, (dict,), entry_path)
            _check_known(entry, ("name", "url", "token"), entry_path)
            url = _opt_str(entry, "url", entry_path, None)
            if not url:
                raise SchemaError(f"config key '{entry_path}.url': required (the upstream serving plane's base URL)")
            name = _opt_str(entry, "name", entry_path, None)
            if not name:
                # stable default: the URL's host:port (metric/key-safe
                # sanitization happens at the metrics layer)
                from urllib.parse import urlsplit

                parts = urlsplit(url if "//" in url else f"http://{url}")
                name = parts.netloc or f"upstream{i}"
            if "/" in name:
                # "/" separates the cluster prefix in merged keys
                # ("<cluster>/<key>"): a name containing it would make
                # split_global_key misattribute the cluster, and two
                # names like "us" and "us/east" could mint the SAME
                # global key from different upstreams
                raise SchemaError(
                    f"config key '{entry_path}.name': {name!r} must not contain '/' "
                    f"(it is the cluster/key separator in merged global keys)"
                )
            if name in seen_names:
                raise SchemaError(
                    f"config key '{entry_path}.name': duplicate upstream name {name!r} "
                    f"(names key the merged view's cluster prefix — they must be unique)"
                )
            seen_names.add(name)
            # distinct raw names can still collapse to one sanitized form
            # ("us-east.1" and "us-east_1" -> "us_east_1"), which would
            # alias their lag/stale gauges AND their resume-token files
            # (each restart resuming with the OTHER cluster's token)
            sanitized = metric_safe_name(name)
            if sanitized in (metric_safe_name(n) for n in seen_names - {name}):
                raise SchemaError(
                    f"config key '{entry_path}.name': {name!r} collides with another "
                    f"upstream after metric/filename sanitization (both become "
                    f"{sanitized!r}); pick names that differ in [a-zA-Z0-9_]"
                )
            upstreams.append(FederationUpstream(
                url=url, name=name, token=_opt_str(entry, "token", entry_path, None) or None,
            ))
        if enabled and not upstreams:
            raise SchemaError(
                "config key 'federation.upstreams': at least one upstream is required "
                "when federation.enabled (a federator with nothing to federate)"
            )
        stale_after = _opt_num(raw, "stale_after_seconds", path, 10.0)
        if stale_after <= 0:
            raise SchemaError(
                f"config key '{path}.stale_after_seconds': must be > 0, got {stale_after}"
            )
        backoff = _opt_num(raw, "resync_backoff_seconds", path, 1.0)
        if backoff <= 0:
            raise SchemaError(
                f"config key '{path}.resync_backoff_seconds': must be > 0, got {backoff}"
            )
        codec = _opt_str(raw, "codec", path, "auto")
        if codec not in VALID_SERVE_CODECS:
            raise SchemaError(
                f"config key '{path}.codec': must be one of "
                f"{', '.join(VALID_SERVE_CODECS)}, got {codec!r}"
            )
        processes = _opt_int(raw, "processes", path, 0)
        if processes < 0 or processes > 64:
            raise SchemaError(
                f"config key '{path}.processes': must be in [0, 64], got {processes}"
            )
        return cls(
            enabled=enabled,
            upstreams=tuple(upstreams),
            stale_after_seconds=stale_after,
            resync_backoff_seconds=backoff,
            drop_stale=_opt_bool(raw, "drop_stale", path, False),
            codec=codec,
            processes=processes,
        )


@dataclasses.dataclass(frozen=True)
class RelayConfig:
    """The ``relay:`` section — net-new relay/edge fan-out tier
    (relay/): this serve node's FleetView mirrors ONE upstream serving
    plane over the raw-bytes passthrough (same view instance id, same
    rv line, the upstream's wire frames re-broadcast VERBATIM — zero
    re-encode), forming a depth-stamped fan-out tree that carries 100k+
    streaming subscribers off one publisher. Requires ``serve.enabled``;
    mutually exclusive with ``federation.enabled`` and
    ``history.enabled`` (both would mint/persist rvs against a foreign
    rv line). See ARCHITECTURE.md "Relay tier".
    """

    enabled: bool = False
    upstream: Optional[FederationUpstream] = None  # required when enabled
    # tree-depth bound, counted from the root (a root serve plane is
    # depth 0, its relays are depth 1, ...). The loop-breaker: a
    # mis-wired relay cycle re-discovers a growing depth every reconnect
    # and self-quarantines at the limit instead of circulating frames.
    depth_limit: int = 2
    # upstream wire codec preference (mirrors federation.codec): the
    # passthrough stores whatever shape actually rides the wire; local
    # subscribers on other shapes pay the usual lazy once-per-delta fill
    codec: str = "auto"
    # negotiate ?fresh=1 upstream (default on: depth-stamped per-hop
    # freshness reads the ts field, and stamped frames pass through to
    # leaves so tier-N consumers measure true end-to-end age)
    fresh: bool = True
    # negotiate ?trace=1 upstream (trace implies fresh on the wire):
    # sampled journeys' in-band trace dicts pass through verbatim
    trace: bool = False
    # journal warm-up on (re)connect: subscribe this many rvs BELOW the
    # snapshot (floored by the upstream's retention) so resume tokens
    # minted before a relay restart keep resuming gapless against the
    # new process. 0 disables (tokens older than the restart re-snapshot)
    backfill: int = 4096
    # an upstream with no frame (delta or SYNC) for this long is stale:
    # the relay reconnects and its health body degrades
    stale_after_seconds: float = 10.0
    # reconnect/resync backoff base (jittered, exponential)
    resync_backoff_seconds: float = 1.0
    # how long app startup waits for the first upstream adopt before
    # serving anyway (degraded): bounded availability-over-strictness
    sync_timeout_seconds: float = 15.0

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "RelayConfig":
        path = "relay"
        _check_known(
            raw,
            ("enabled", "upstream", "depth_limit", "codec", "fresh", "trace",
             "backfill", "stale_after_seconds", "resync_backoff_seconds",
             "sync_timeout_seconds"),
            path,
        )
        enabled = _opt_bool(raw, "enabled", path, False)
        upstream = None
        raw_upstream = raw.get("upstream")
        if raw_upstream is not None:
            entry_path = f"{path}.upstream"
            _expect(raw_upstream, (dict,), entry_path)
            _check_known(raw_upstream, ("name", "url", "token"), entry_path)
            url = _opt_str(raw_upstream, "url", entry_path, None)
            if not url:
                raise SchemaError(
                    f"config key '{entry_path}.url': required (the upstream "
                    f"serving plane this relay mirrors)"
                )
            name = _opt_str(raw_upstream, "name", entry_path, None)
            if not name:
                from urllib.parse import urlsplit

                parts = urlsplit(url if "//" in url else f"http://{url}")
                name = parts.netloc or "upstream"
            upstream = FederationUpstream(
                url=url, name=name,
                token=_opt_str(raw_upstream, "token", entry_path, None) or None,
            )
        if enabled and upstream is None:
            raise SchemaError(
                "config key 'relay.upstream': required when relay.enabled "
                "(a relay with nothing to relay)"
            )
        depth_limit = _opt_int(raw, "depth_limit", path, 2)
        if depth_limit < 1:
            raise SchemaError(
                f"config key '{path}.depth_limit': must be >= 1 (a relay is "
                f"at least depth 1), got {depth_limit}"
            )
        codec = _opt_str(raw, "codec", path, "auto")
        if codec not in VALID_SERVE_CODECS:
            raise SchemaError(
                f"config key '{path}.codec': must be one of "
                f"{', '.join(VALID_SERVE_CODECS)}, got {codec!r}"
            )
        backfill = _opt_int(raw, "backfill", path, 4096)
        if backfill < 0:
            raise SchemaError(
                f"config key '{path}.backfill': must be >= 0 (0 disables the "
                f"journal warm-up), got {backfill}"
            )
        stale_after = _opt_num(raw, "stale_after_seconds", path, 10.0)
        if stale_after <= 0:
            raise SchemaError(
                f"config key '{path}.stale_after_seconds': must be > 0, got {stale_after}"
            )
        backoff = _opt_num(raw, "resync_backoff_seconds", path, 1.0)
        if backoff <= 0:
            raise SchemaError(
                f"config key '{path}.resync_backoff_seconds': must be > 0, got {backoff}"
            )
        sync_timeout = _opt_num(raw, "sync_timeout_seconds", path, 15.0)
        if sync_timeout < 0:
            raise SchemaError(
                f"config key '{path}.sync_timeout_seconds': must be >= 0, got {sync_timeout}"
            )
        return cls(
            enabled=enabled,
            upstream=upstream,
            depth_limit=depth_limit,
            codec=codec,
            fresh=_opt_bool(raw, "fresh", path, True),
            trace=_opt_bool(raw, "trace", path, False),
            backfill=backfill,
            stale_after_seconds=stale_after,
            resync_backoff_seconds=backoff,
            sync_timeout_seconds=sync_timeout,
        )


@dataclasses.dataclass(frozen=True)
class StateConfig:
    """The ``state:`` section — net-new checkpoint/resume (SURVEY.md §5).

    The reference lost all state on restart (no resourceVersion passed at
    pod_watcher.py:264); we persist the last-seen resourceVersion and the
    slice-state cache so a restart neither drops nor duplicates notifications.
    """

    checkpoint_path: Optional[str] = None
    checkpoint_interval_seconds: float = 5.0

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any]) -> "StateConfig":
        _check_known(raw, ("checkpoint_path", "checkpoint_interval_seconds"), "state")
        return cls(
            checkpoint_path=_opt_str(raw, "checkpoint_path", "state", None),
            checkpoint_interval_seconds=_opt_num(raw, "checkpoint_interval_seconds", "state", 5.0),
        )


@dataclasses.dataclass(frozen=True)
class AppConfig:
    """Fully-validated application config (one per process)."""

    environment: str
    watcher: WatcherConfig
    clusterapi: ClusterApiConfig
    kubernetes: KubernetesConfig
    tpu: TpuConfig
    state: StateConfig
    ingest: IngestConfig = dataclasses.field(default_factory=IngestConfig)
    trace: TraceConfig = dataclasses.field(default_factory=TraceConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    history: HistoryConfig = dataclasses.field(default_factory=HistoryConfig)
    federation: FederationConfig = dataclasses.field(default_factory=FederationConfig)
    relay: RelayConfig = dataclasses.field(default_factory=RelayConfig)
    metrics: MetricsConfig = dataclasses.field(default_factory=MetricsConfig)
    slo: SloConfig = dataclasses.field(default_factory=SloConfig)
    health: HealthConfig = dataclasses.field(default_factory=HealthConfig)
    analytics: AnalyticsConfig = dataclasses.field(default_factory=AnalyticsConfig)

    TOP_LEVEL_KEYS = ("environment", "watcher", "clusterapi", "kubernetes", "tpu", "state", "ingest", "trace", "serve", "history", "federation", "relay", "metrics", "slo", "health", "analytics")

    @classmethod
    def from_raw(cls, raw: Mapping[str, Any], environment: str) -> "AppConfig":
        _check_known(raw, cls.TOP_LEVEL_KEYS, "<root>")
        for section in ("watcher", "clusterapi", "kubernetes", "tpu", "state", "ingest", "trace", "serve", "history", "federation", "relay", "metrics", "slo", "health", "analytics"):
            _expect(raw.get(section) or {}, (dict,), section)
        # The reference's development.yaml declared `environment: local` while
        # the CLI only accepted development|staging|production, leaving the
        # "local" branch unreachable (SURVEY.md §2 defect #5). Here the
        # declared name is advisory only; the CLI name wins and both are kept.
        declared = raw.get("environment")
        if declared is not None:
            _expect(declared, (str,), "environment")
        serve = ServeConfig.from_raw(raw.get("serve") or {})
        history = HistoryConfig.from_raw(raw.get("history") or {})
        if history.enabled and not serve.enabled:
            raise SchemaError(
                "config key 'history.enabled': requires serve.enabled (the WAL "
                "persists the serving plane's FleetView deltas; without the "
                "serving plane there is nothing to record)"
            )
        federation = FederationConfig.from_raw(raw.get("federation") or {})
        if federation.enabled and not serve.enabled:
            raise SchemaError(
                "config key 'federation.enabled': requires serve.enabled (the "
                "merged global view republishes through the serving plane's "
                "FleetView; without it the fan-in has nowhere to land)"
            )
        relay = RelayConfig.from_raw(raw.get("relay") or {})
        if relay.enabled:
            if not serve.enabled:
                raise SchemaError(
                    "config key 'relay.enabled': requires serve.enabled (a relay "
                    "IS a serve node — the mirrored view re-broadcasts through "
                    "the serving plane's fan-out core)"
                )
            if federation.enabled:
                raise SchemaError(
                    "config key 'relay.enabled': conflicts with "
                    "federation.enabled — federation MINTS local rvs into the "
                    "view while a relay MIRRORS its upstream's rv line verbatim; "
                    "one view cannot serve both rv spaces. Run them as separate "
                    "processes (relay in front of a federator works fine)."
                )
            if history.enabled:
                raise SchemaError(
                    "config key 'relay.enabled': conflicts with history.enabled "
                    "— a relay is a stateless edge on its UPSTREAM's rv line; "
                    "durability (and the restart-surviving token story) belongs "
                    "to the root that owns the line. Relay restarts re-warm "
                    "their journal via relay.backfill instead."
                )
        trace = TraceConfig.from_raw(raw.get("trace") or {})
        if trace.federation.enabled:
            # schema-enforced pairing (same posture as health.sources.*):
            # a silently plane-less joined-trace config would look like
            # "no cross-cluster traces" instead of a wiring mistake
            if not trace.enabled:
                raise SchemaError(
                    "config key 'trace.federation.enabled': requires trace.enabled "
                    "(joined journeys land in the tracing plane's ring and ride "
                    "its sampled deltas)"
                )
            if not federation.enabled:
                raise SchemaError(
                    "config key 'trace.federation.enabled': requires "
                    "federation.enabled (trace joining happens on the federation "
                    "fan-in path; without upstreams there is nothing to join)"
                )
            if federation.processes > 0:
                raise SchemaError(
                    "config key 'trace.federation.enabled': requires "
                    "federation.processes: 0 (the joined-trace collector rides "
                    "the in-process fan-in thread; sharded merge workers "
                    "negotiate trace off and would silently join nothing)"
                )
        analytics = AnalyticsConfig.from_raw(raw.get("analytics") or {})
        if analytics.enabled and not serve.enabled:
            raise SchemaError(
                "config key 'analytics.enabled': requires serve.enabled (the "
                "columnar encoder's source of truth is the serving plane's "
                "FleetView, and /serve/analytics rides its HTTP surface)"
            )
        ingest = IngestConfig.from_raw(raw.get("ingest") or {})
        state = StateConfig.from_raw(raw.get("state") or {})
        kubernetes = KubernetesConfig.from_raw(raw.get("kubernetes") or {})
        if ingest.processes > 0:
            if not state.checkpoint_path:
                raise SchemaError(
                    "config key 'ingest.processes': requires checkpointing "
                    "(state.checkpoint_path) — each shard-reader process resumes "
                    "its watch from a durable per-shard resourceVersion after a "
                    "crash/respawn; without it every worker death replays or "
                    "relists the whole shard"
                )
            if kubernetes.use_mock:
                raise SchemaError(
                    "config key 'ingest.processes': conflicts with "
                    "kubernetes.use_mock — the in-process fake pod lifecycle "
                    "cannot cross process boundaries; point the workers at a "
                    "real (or mock-apiserver) URL instead"
                )
        health = HealthConfig.from_raw(raw.get("health") or {})
        if health.enabled:
            # each enabled source must have the plane it reads — a silently
            # signal-less source would look like "everything healthy"
            if health.source_phase and not serve.enabled:
                raise SchemaError(
                    "config key 'health.sources.phase': requires serve.enabled "
                    "(phase-transition latencies are read from the FleetView)"
                )
            if health.source_freshness and not federation.enabled:
                raise SchemaError(
                    "config key 'health.sources.freshness': requires "
                    "federation.enabled (per-upstream watermarks are the "
                    "federation plane's telemetry)"
                )
            if health.source_trace and not trace.enabled:
                raise SchemaError(
                    "config key 'health.sources.trace': requires trace.enabled "
                    "(stage outliers are read from the tracing plane's histograms)"
                )
        return cls(
            environment=environment,
            watcher=WatcherConfig.from_raw(raw.get("watcher") or {}),
            clusterapi=ClusterApiConfig.from_raw(raw.get("clusterapi") or {}),
            kubernetes=kubernetes,
            tpu=TpuConfig.from_raw(raw.get("tpu") or {}),
            state=state,
            ingest=ingest,
            trace=trace,
            serve=serve,
            history=history,
            federation=federation,
            relay=relay,
            metrics=MetricsConfig.from_raw(raw.get("metrics") or {}),
            slo=SloConfig.from_raw(raw.get("slo") or {}),
            health=health,
            analytics=analytics,
        )
