"""Config loading: base + environment overlay + env-var substitution.

Contract parity with the reference (pod_watcher.py:19-75):

- ``config/base.yaml`` is loaded first, then ``config/{environment}.yaml``
  is overlaid with a recursive dict merge where the overlay wins
  (pod_watcher.py:47-57).
- String values of the exact form ``${VAR}`` or ``${VAR:-default}`` are
  replaced from the process environment (pod_watcher.py:59-75). Only
  whole-string tokens are substituted, matching the reference contract.
- A missing config file degrades to ``{}`` with a warning
  (pod_watcher.py:39-41); a malformed file is an error (the reference
  swallowed parse errors into ``{}`` — we consider that a defect and raise).

Environment resolution order (main.py:7-10): ``ENVIRONMENT`` env var, then
CLI argument, then the default ``development``; validated against the
supported set (main.py:13-17).
"""

from __future__ import annotations

import logging
import os
from pathlib import Path
from typing import Any, Dict, Mapping, Optional, Sequence

import yaml

from k8s_watcher_tpu.config.schema import AppConfig, SchemaError

logger = logging.getLogger(__name__)

SUPPORTED_ENVIRONMENTS = ("development", "staging", "production")
DEFAULT_ENVIRONMENT = "development"


class ConfigError(Exception):
    """Raised for unreadable/malformed config files or schema violations."""


def resolve_environment(
    argv: Optional[Sequence[str]] = None,
    env: Optional[Mapping[str, str]] = None,
) -> str:
    """Resolve the runtime environment name.

    Order (reference main.py:7-10): CLI argument overrides the
    ``ENVIRONMENT`` env var, which overrides the default. Raises
    ``ConfigError`` for unsupported names (reference main.py:13-17 exits 1).
    """
    env = os.environ if env is None else env
    name = env.get("ENVIRONMENT", DEFAULT_ENVIRONMENT)
    if argv:
        name = argv[0]
    if name not in SUPPORTED_ENVIRONMENTS:
        raise ConfigError(
            f"Unsupported environment '{name}'. Supported environments: {list(SUPPORTED_ENVIRONMENTS)}"
        )
    return name


def load_yaml_file(path: os.PathLike | str) -> Dict[str, Any]:
    """Load one YAML file; missing -> {} with a warning; malformed -> error."""
    path = Path(path)
    try:
        with open(path, "r") as fh:
            data = yaml.safe_load(fh)
    except FileNotFoundError:
        logger.warning("Config file %s not found", path)
        return {}
    except yaml.YAMLError as exc:
        raise ConfigError(f"Error loading config {path}: {exc}") from exc
    if data is None:
        return {}  # empty file (e.g. reference staging.yaml is 0 bytes)
    if not isinstance(data, dict):
        raise ConfigError(f"Config {path} must be a mapping, got {type(data).__name__}")
    return data


def deep_merge(base: Mapping[str, Any], override: Mapping[str, Any]) -> Dict[str, Any]:
    """Recursive merge; override wins (parity: pod_watcher.py:47-57)."""
    result: Dict[str, Any] = dict(base)
    for key, value in override.items():
        if key in result and isinstance(result[key], Mapping) and isinstance(value, Mapping):
            result[key] = deep_merge(result[key], value)
        else:
            result[key] = value
    return result


def substitute_env_vars(obj: Any, env: Optional[Mapping[str, str]] = None) -> Any:
    """Replace whole-string ``${VAR}`` / ``${VAR:-default}`` tokens.

    Parity: pod_watcher.py:59-75 — substitution applies only when the entire
    string starts with ``${`` and ends with ``}``; an unset variable with no
    default becomes ``""``.
    """
    env = os.environ if env is None else env
    if isinstance(obj, Mapping):
        return {k: substitute_env_vars(v, env) for k, v in obj.items()}
    if isinstance(obj, list):
        return [substitute_env_vars(v, env) for v in obj]
    if isinstance(obj, str) and obj.startswith("${") and obj.endswith("}"):
        token = obj[2:-1]
        default = ""
        if ":-" in token:
            token, default = token.split(":-", 1)
        return env.get(token, default)
    return obj


def load_raw_config(
    environment: str,
    config_dir: os.PathLike | str = "config",
    env: Optional[Mapping[str, str]] = None,
) -> Dict[str, Any]:
    """base.yaml + {environment}.yaml merge + env substitution, unvalidated."""
    config_dir = Path(config_dir)
    base = load_yaml_file(config_dir / "base.yaml")
    overlay = load_yaml_file(config_dir / f"{environment}.yaml")
    merged = deep_merge(base, overlay)
    return substitute_env_vars(merged, env)


def load_config(
    environment: str,
    config_dir: os.PathLike | str = "config",
    env: Optional[Mapping[str, str]] = None,
) -> AppConfig:
    """Load, merge, substitute and validate the config for ``environment``."""
    raw = load_raw_config(environment, config_dir, env)
    try:
        return AppConfig.from_raw(raw, environment)
    except SchemaError as exc:
        raise ConfigError(str(exc)) from exc
