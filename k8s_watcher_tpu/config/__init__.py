"""Layered configuration subsystem.

Contract parity with the reference config stack (pod_watcher.py:19-75,
config/*.yaml): base + environment overlay with recursive merge, then
``${VAR}`` / ``${VAR:-default}`` environment-variable substitution over the
whole tree; missing files degrade to ``{}`` with a warning.

Improvements over the reference (SURVEY.md §2 defect #3): every key is either
consumed by the typed schema or rejected — no dead keys.
"""

from k8s_watcher_tpu.config.loader import (  # noqa: F401
    ConfigError,
    deep_merge,
    load_config,
    load_yaml_file,
    resolve_environment,
    substitute_env_vars,
)
from k8s_watcher_tpu.config.schema import (  # noqa: F401
    AppConfig,
    ClusterApiConfig,
    KubernetesConfig,
    RetryPolicy,
    ServeConfig,
    TpuConfig,
    WatcherConfig,
)
